"""paddle.text equivalent. Reference analog: python/paddle/text/
(datasets: Imdb/Imikolov/Movielens/UCIHousing/WMT14/WMT16/Conll05st; plus
ViterbiDecoder under paddle.text.viterbi_decode in this era).

Network downloads are unavailable, so datasets synthesize deterministic data
unless given local files — same Dataset contract as the vision datasets.
ViterbiDecoder is TPU-first: the DP recursion is a lax.scan (static trip
count over time steps), not a per-step python loop.
"""
from __future__ import annotations

import os

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..io.dataset import Dataset
from ..nn.layer_base import Layer
from ..ops._helpers import ensure_tensor

__all__ = ["Imdb", "Imikolov", "Movielens", "UCIHousing", "WMT14", "WMT16",
           "Conll05st", "viterbi_decode", "ViterbiDecoder"]


# ------------------------------------------------------------------ datasets

class _SyntheticTextDataset(Dataset):
    """Deterministic synthetic fallback shared by the text datasets."""

    N_TRAIN = 512
    N_TEST = 128

    def __init__(self, mode="train", seed_offset=0):
        self.mode = mode
        n = self.N_TRAIN if mode == "train" else self.N_TEST
        self._rng = np.random.default_rng(
            (0 if mode == "train" else 1) + seed_offset)
        self._build(n)

    def _build(self, n):
        raise NotImplementedError

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        return self.data[idx]


class Imdb(_SyntheticTextDataset):
    """Sentiment classification: (token_ids, label)."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=False):
        self.cutoff = cutoff
        super().__init__(mode=mode, seed_offset=10)

    def _build(self, n):
        self.data = []
        for _ in range(n):
            length = int(self._rng.integers(8, 64))
            label = int(self._rng.integers(0, 2))
            toks = self._rng.integers(2 + label, 5000, length).astype(np.int64)
            self.data.append((toks, np.asarray(label, np.int64)))


class Imikolov(_SyntheticTextDataset):
    """n-gram LM dataset: tuples of n token ids."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=False):
        self.window_size = window_size
        super().__init__(mode=mode, seed_offset=20)

    def _build(self, n):
        self.data = [tuple(self._rng.integers(0, 2000, self.window_size)
                           .astype(np.int64))
                     for _ in range(n)]


class Movielens(_SyntheticTextDataset):
    """Rating prediction: (user_id, gender, age, job, movie_id, title,
    categories, rating)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=False):
        super().__init__(mode=mode, seed_offset=30)

    def _build(self, n):
        self.data = []
        for _ in range(n):
            self.data.append((
                np.asarray(self._rng.integers(1, 6041), np.int64),
                np.asarray(self._rng.integers(0, 2), np.int64),
                np.asarray(self._rng.integers(0, 7), np.int64),
                np.asarray(self._rng.integers(0, 21), np.int64),
                np.asarray(self._rng.integers(1, 3953), np.int64),
                self._rng.integers(0, 5000, 10).astype(np.int64),
                self._rng.integers(0, 19, 3).astype(np.int64),
                np.asarray(self._rng.random() * 4 + 1, np.float32)))


class UCIHousing(_SyntheticTextDataset):
    """Regression: (13 features, price)."""

    def __init__(self, data_file=None, mode="train", download=False):
        if data_file and os.path.exists(data_file):
            raw = np.loadtxt(data_file).astype(np.float32)
            self.mode = mode
            self.data = [(r[:-1], r[-1:]) for r in raw]
            return
        super().__init__(mode=mode, seed_offset=40)

    def _build(self, n):
        feats = self._rng.random((n, 13)).astype(np.float32)
        w = np.linspace(0.5, 2.0, 13, dtype=np.float32)
        prices = (feats @ w + 5).astype(np.float32)
        self.data = [(feats[i], prices[i:i + 1]) for i in range(n)]


class WMT14(_SyntheticTextDataset):
    """Translation: (src_ids, trg_ids, trg_ids_next)."""

    def __init__(self, data_file=None, mode="train", dict_size=30000,
                 download=False):
        self.dict_size = dict_size
        super().__init__(mode=mode, seed_offset=50)

    def _build(self, n):
        self.data = []
        for _ in range(n):
            ls, lt = int(self._rng.integers(4, 20)), int(self._rng.integers(4, 20))
            src = self._rng.integers(3, self.dict_size, ls).astype(np.int64)
            trg = self._rng.integers(3, self.dict_size, lt).astype(np.int64)
            trg_next = np.concatenate([trg[1:], [1]]).astype(np.int64)
            self.data.append((src, trg, trg_next))


class WMT16(WMT14):
    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en", download=False):
        super().__init__(mode=mode, dict_size=src_dict_size)


class Conll05st(_SyntheticTextDataset):
    """SRL: (word_ids, predicate_mark, label_ids)."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, mode="train",
                 download=False):
        super().__init__(mode=mode, seed_offset=60)

    def _build(self, n):
        self.data = []
        for _ in range(n):
            length = int(self._rng.integers(5, 30))
            words = self._rng.integers(0, 5000, length).astype(np.int64)
            labels = self._rng.integers(0, 67, length).astype(np.int64)
            mark = np.zeros(length, np.int64)
            mark[int(self._rng.integers(0, length))] = 1
            self.data.append((words, mark, labels))


# ------------------------------------------------------- viterbi decoding

def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Batched Viterbi decode. Reference analog: the viterbi_decode op
    (phi viterbi_decode kernel; python/paddle/text/viterbi_decode.py).

    potentials: [B, T, N] unary emissions; transition_params: [N, N];
    lengths: [B] actual sequence lengths.
    Returns (scores [B], paths [B, T] int64, zero-padded past length).
    """
    pot = ensure_tensor(potentials)._value
    trans = ensure_tensor(transition_params)._value
    lens = ensure_tensor(lengths)._value
    b, t, n = pot.shape

    if include_bos_eos_tag:
        # reference convention: last tag (n-1) is BOS/start, second-to-last
        # (n-2) is EOS/stop (python/paddle/text/viterbi_decode.py)
        bos, eos = n - 1, n - 2

    def step(carry, xs):
        alpha, step_i = carry
        emit = xs  # [B, N]
        # scores[b, i, j] = alpha[b, i] + trans[i, j]
        scores = alpha[:, :, None] + trans[None, :, :]
        best_prev = jnp.argmax(scores, axis=1)             # [B, N]
        best_score = jnp.max(scores, axis=1) + emit        # [B, N]
        # only advance where step_i < length
        active = (step_i < lens)[:, None]
        alpha_new = jnp.where(active, best_score, alpha)
        return (alpha_new, step_i + 1), best_prev

    init_alpha = pot[:, 0, :]
    if include_bos_eos_tag:
        init_alpha = init_alpha + trans[bos][None, :]
    (alpha, _), history = jax.lax.scan(
        step, (init_alpha, jnp.asarray(1)),
        jnp.transpose(pot[:, 1:, :], (1, 0, 2)))
    if include_bos_eos_tag:
        alpha = alpha + trans[:, eos][None, :]

    scores = jnp.max(alpha, axis=1)
    last_tag = jnp.argmax(alpha, axis=1).astype(jnp.int64)  # [B]

    # backtrack with a reverse scan; history: [T-1, B, N]
    def back(carry, hist_t):
        tag, step_i = carry
        prev = jnp.take_along_axis(hist_t, tag[:, None], axis=1)[:, 0]
        # freeze when beyond length: positions t >= len keep tag
        active = (step_i < lens - 1)
        tag_new = jnp.where(active, prev.astype(jnp.int64), tag)
        return (tag_new, step_i - 1), tag_new

    rev_hist = history[::-1]
    (first_tag, _), rev_tags = jax.lax.scan(
        back, (last_tag, jnp.asarray(t - 2)), (rev_hist))
    # path = [first..., last]; rev_tags are tags at positions t-2..0
    path = jnp.concatenate([rev_tags[::-1].T, last_tag[:, None]], axis=1)
    # zero out positions >= length (paddle pads with 0)
    mask = jnp.arange(t)[None, :] < lens[:, None]
    path = jnp.where(mask, path, 0)
    return Tensor(scores), Tensor(path.astype(jnp.int64))


class ViterbiDecoder(Layer):
    """Layer wrapper over viterbi_decode. Reference analog:
    python/paddle/text/viterbi_decode.py ViterbiDecoder."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = ensure_tensor(transitions)
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
