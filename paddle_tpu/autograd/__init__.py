"""paddle.autograd surface. Reference analog: python/paddle/autograd/
(backward, PyLayer, functional jacobian/hessian; incubate/autograd primapi)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.autograd import (  # noqa: F401
    grad, no_grad, enable_grad, set_grad_enabled, saved_tensors_hooks)
from ..framework.core import Tensor

__all__ = ["backward", "grad", "no_grad", "enable_grad", "set_grad_enabled",
           "saved_tensors_hooks",
           "PyLayer", "PyLayerContext", "jacobian", "hessian", "vjp", "jvp"]


def backward(tensors, grad_tensors=None, retain_graph=False):
    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    for t, g in zip(tensors, grad_tensors):
        t.backward(g, retain_graph=retain_graph)


class PyLayerContext:
    """Reference analog: eager/pylayer — save_for_backward storage."""

    def __init__(self):
        self._saved = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayer:
    """Custom autograd op with user forward/backward.

    Reference analog: python/paddle/autograd/py_layer.py over
    fluid/eager/pylayer/. Implemented by registering a manual GradNode whose
    vjp calls the user's backward.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..framework.autograd import GradNode, is_grad_enabled
        from ..ops.dispatch import _make_edges
        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(out, (list, tuple))
        outs = list(out) if multi else [out]

        if not is_grad_enabled() or not any(
                not t.stop_gradient for t in tensor_inputs):
            return out

        def vjp_fn(gs):
            gs_t = gs if isinstance(gs, tuple) else (gs,)
            grads_in = cls.backward(
                ctx, *[Tensor(g, stop_gradient=True) for g in gs_t])
            if not isinstance(grads_in, (list, tuple)):
                grads_in = (grads_in,)
            vals = []
            for g in grads_in:
                vals.append(None if g is None else
                            (g._value if isinstance(g, Tensor)
                             else jnp.asarray(g)))
            return tuple(vals)

        node = GradNode(cls.__name__, vjp_fn, _make_edges(tensor_inputs),
                        tuple((o.shape, o._value.dtype) for o in outs))
        for j, o in enumerate(outs):
            o.stop_gradient = False
            o._grad_node = node
            o._out_index = j
        return out if multi else outs[0]


def _as_pure(func):
    def pure(*vals):
        ts = [Tensor(v, stop_gradient=True) for v in vals]
        with no_grad():
            out = func(*ts)
        return out._value if isinstance(out, Tensor) else out
    return pure


def jacobian(func, xs, create_graph=False, allow_unused=False):
    single = not isinstance(xs, (list, tuple))
    xs_l = [xs] if single else list(xs)
    vals = [x._value for x in xs_l]
    jac = jax.jacrev(_as_pure(func), argnums=tuple(range(len(vals))))(*vals)
    out = tuple(Tensor(j) for j in jac)
    return out[0] if single else out


def hessian(func, xs, create_graph=False, allow_unused=False):
    single = not isinstance(xs, (list, tuple))
    xs_l = [xs] if single else list(xs)
    vals = [x._value for x in xs_l]
    hes = jax.hessian(_as_pure(func), argnums=tuple(range(len(vals))))(*vals)
    if single:
        return Tensor(hes[0][0]) if isinstance(hes, tuple) else Tensor(hes)
    return hes


def vjp(func, xs, v=None):
    single = not isinstance(xs, (list, tuple))
    xs_l = [xs] if single else list(xs)
    vals = [x._value for x in xs_l]
    out, vjp_fn = jax.vjp(_as_pure(func), *vals)
    if v is None:
        cot = jnp.ones_like(out)
    else:
        cot = v._value if isinstance(v, Tensor) else jnp.asarray(v)
    grads = vjp_fn(cot)
    grads_t = tuple(Tensor(g) for g in grads)
    return Tensor(out), (grads_t[0] if single else grads_t)


def jvp(func, xs, v=None):
    single = not isinstance(xs, (list, tuple))
    xs_l = [xs] if single else list(xs)
    vals = [x._value for x in xs_l]
    if v is None:
        tangents = [jnp.ones_like(x) for x in vals]
    else:
        v_l = [v] if single else list(v)
        tangents = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
                    for t in v_l]
    out, tangent_out = jax.jvp(_as_pure(func), tuple(vals), tuple(tangents))
    return Tensor(out), Tensor(tangent_out)
