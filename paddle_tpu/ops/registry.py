"""Op schema registry — single source of truth for the op corpus.

Reference analog: paddle/phi/api/yaml/{ops,legacy_ops}.yaml + KernelFactory
(phi/core/kernel_factory.h:268) + custom kernel plug-in
(phi/core/custom_kernel.cc). TPU-first: instead of per-backend kernel
variants keyed by (Backend, Layout, DataType), every op has one jax
implementation that XLA lowers for the active platform. The registry holds
the schema the reference keeps in YAML — generated from the code instead of
codegen'd into it:

  - args:       the op's python signature (the yaml `args:` row)
  - infer_meta: shape/dtype inference = jax abstract eval (`infer_meta()`
                runs the op under jax.eval_shape — no separate rule table)
  - backward:   `differentiable` (VJPs are captured at dispatch, so every
                differentiable op has its backward by construction)
  - kernel:     the jax entry point, plus named overrides (e.g. a Pallas
                kernel) that dispatch consults when activated
"""
from __future__ import annotations

import inspect
from dataclasses import dataclass, field
from typing import Callable, Optional

__all__ = ["OpDef", "register_op", "get_op", "all_ops", "override_kernel",
           "use_kernel", "infer_meta", "describe"]


@dataclass
class OpDef:
    name: str
    category: str                       # math / creation / manipulation / ...
    fn: Optional[Callable] = None       # the python-level op entry point
    differentiable: bool = True
    ref: str = ""                       # reference citation (file:line)
    args: tuple = ()                    # entry-point signature (arg names)
    overrides: dict = field(default_factory=dict)  # impl_name -> callable
    active: Optional[str] = None        # activated override, if any
    # bumped whenever the overrides table changes (a kernel registered or
    # re-registered under an existing name); together with the active impl
    # name it forms the registry token in the eager executable-cache key
    # (ops/dispatch.py), so entries compiled against a superseded kernel
    # become unreachable immediately
    generation: int = 0


_REGISTRY: dict[str, OpDef] = {}


def register_op(name: str, category: str, differentiable: bool = True,
                ref: str = ""):
    """Decorator registering a python op entry point into the corpus table."""
    def deco(fn):
        try:
            args = tuple(inspect.signature(fn).parameters)
        except (TypeError, ValueError):
            args = ()
        _REGISTRY[name] = OpDef(name=name, category=category, fn=fn,
                                differentiable=differentiable, ref=ref,
                                args=args)
        return fn
    return deco


def get_op(name: str) -> OpDef:
    return _REGISTRY[name]


def all_ops() -> dict[str, OpDef]:
    return dict(_REGISTRY)


def describe(name: str) -> dict:
    """The op's schema row (yaml-table analog): args / kernel / backward /
    overrides."""
    od = _REGISTRY[name]
    return {"op": od.name, "category": od.category, "args": list(od.args),
            "backward": f"{od.name}_grad (vjp)" if od.differentiable
            else None, "kernel": "jax/XLA" if od.fn is not None else None,
            "overrides": list(od.overrides), "active_override": od.active,
            "ref": od.ref}


def infer_meta(name: str, *specs):
    """Shape/dtype inference via jax abstract eval (the InferMeta analog —
    SURVEY §2.2 row: InferMeta ≙ jax.eval_shape). `specs` are
    jax.ShapeDtypeStruct-likes (or arrays); returns the output
    ShapeDtypeStruct(s) without computing anything."""
    import jax
    from ..framework.core import Tensor
    od = _REGISTRY[name]
    if od.fn is None:
        raise ValueError(f"op {name!r} has no registered entry point")

    def run(*vals):
        out = od.fn(*[Tensor(v, stop_gradient=True) for v in vals])
        if isinstance(out, (tuple, list)):
            return tuple(o._value if isinstance(o, Tensor) else o
                         for o in out)
        return out._value if isinstance(out, Tensor) else out
    return jax.eval_shape(run, *specs)


def override_kernel(name: str, impl_name: str, fn: Callable,
                    activate: bool = False):
    """Install an alternative kernel (e.g. Pallas) for an op; activation
    (routing dispatch through `fn` instead of the built-in jax
    implementation) is explicit — pass activate=True or use the use_kernel
    switch — so registering a kernel for benchmarking/introspection never
    reroutes global dispatch as a side effect. The override receives the
    same positional jax values the built-in kernel closure receives (the
    op's tensor operands; non-tensor attrs stay with the built-in closure
    contract). Reference analog: phi/core/custom_kernel.cc
    RegisterKernelWithMetaInfo.
    """
    od = _REGISTRY.get(name)
    if od is None:
        od = _REGISTRY.setdefault(name, OpDef(name=name, category="custom"))
    od.overrides[impl_name] = fn
    od.generation += 1
    if activate:
        od.active = impl_name
    return fn


class use_kernel:
    """Context manager / switch selecting which implementation an op
    dispatches to: use_kernel("softmax", "pallas") activates the named
    override; use_kernel("softmax", None) restores the built-in kernel."""

    def __init__(self, name: str, impl_name: Optional[str]):
        od = _REGISTRY[name]
        if impl_name is not None and impl_name not in od.overrides:
            raise KeyError(
                f"op {name!r} has no override {impl_name!r}; installed: "
                f"{list(od.overrides)}")
        self._od = od
        self._prev = od.active
        # no generation bump: the active impl NAME is part of the dispatch
        # cache token, so (de)activation re-keys by itself — and restoring
        # the previous impl re-matches its still-valid cached executables
        od.active = impl_name

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._od.active = self._prev
        return False


def _active_override(name: str):
    """The activated override callable for `name`, or None (thin view over
    _dispatch_state so the two can never drift)."""
    return _dispatch_state(name)[0]


def _dispatch_state(name: str):
    """Dispatch hook: (override_callable_or_None, active_impl_name,
    generation). The (name, generation) pair is the registry token in the
    eager executable-cache key — activation changes the name, re-registering
    the same impl name bumps the generation, and either way stale cache
    entries stop matching."""
    od = _REGISTRY.get(name)
    if od is None:
        return None, None, 0
    fn = od.overrides.get(od.active) if od.active is not None else None
    return fn, od.active, od.generation
