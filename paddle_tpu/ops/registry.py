"""Op schema registry — single source of truth for the op corpus.

Reference analog: paddle/phi/api/yaml/{ops,legacy_ops}.yaml + KernelFactory
(phi/core/kernel_factory.h:268). TPU-first: instead of per-backend kernel
variants keyed by (Backend, Layout, DataType), every op has one jax
implementation that XLA lowers for the active platform; the registry exists for
introspection, parity auditing, and pluggable overrides (e.g. swapping a Pallas
kernel in for a hot op).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

__all__ = ["OpDef", "register_op", "get_op", "all_ops", "override_kernel"]


@dataclass
class OpDef:
    name: str
    category: str                       # math / creation / manipulation / ...
    fn: Optional[Callable] = None       # the python-level op entry point
    differentiable: bool = True
    ref: str = ""                       # reference citation (file:line)
    overrides: dict = field(default_factory=dict)  # e.g. {"pallas": fn}


_REGISTRY: dict[str, OpDef] = {}


def register_op(name: str, category: str, differentiable: bool = True,
                ref: str = ""):
    """Decorator registering a python op entry point into the corpus table."""
    def deco(fn):
        _REGISTRY[name] = OpDef(name=name, category=category, fn=fn,
                                differentiable=differentiable, ref=ref)
        return fn
    return deco


def get_op(name: str) -> OpDef:
    return _REGISTRY[name]


def all_ops() -> dict[str, OpDef]:
    return dict(_REGISTRY)


def override_kernel(name: str, impl_name: str, fn: Callable):
    """Install an alternative implementation (e.g. a Pallas kernel) for an op.
    Reference analog: custom kernel plug-in (phi/core/custom_kernel.cc)."""
    _REGISTRY[name].overrides[impl_name] = fn
