"""Elementwise math, matmul, and reductions.

Reference analog: python/paddle/tensor/math.py (24k LoC corpus root) backed by
phi elementwise/reduce/matmul kernels. TPU-first: each op is one jnp/lax
expression XLA fuses; reductions keep static shapes for MXU-friendly layouts.
"""
from __future__ import annotations

import math as _math
import numbers

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework.dtype import to_jax_dtype, get_default_dtype
from .registry import register_op
from ._helpers import ensure_tensor, unary, binary, nary, call_op, axis_tuple, const_input, \
    scalar_or_value, jnp_dtype

__all__ = [
    # binary elementwise
    "add", "subtract", "multiply", "divide", "floor_divide", "mod", "remainder",
    "pow", "maximum", "minimum", "fmax", "fmin", "atan2", "heaviside",
    "floor_mod", "inner", "outer", "kron", "lerp", "gcd", "lcm", "nextafter",
    "copysign", "ldexp", "hypot",
    # unary elementwise
    "sqrt", "rsqrt", "exp", "expm1", "log", "log2", "log10", "log1p", "abs",
    "neg", "sign", "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh",
    "tanh", "asinh", "acosh", "atanh", "ceil", "floor", "round", "trunc",
    "reciprocal", "square", "erf", "erfinv", "lgamma", "digamma", "logit",
    "frac", "rad2deg", "deg2rad", "angle", "conj", "real", "imag", "scale",
    "nan_to_num", "sgn", "i0", "i0e", "i1", "i1e", "polygamma", "sinc",
    # clip / misc
    "clip", "stanh", "multiplex", "increment",
    # matmul family
    "matmul", "mm", "bmm", "dot", "mv", "addmm", "t", "inner", "outer",
    # reductions
    "sum", "mean", "max", "min", "prod", "std", "var", "median", "nanmedian",
    "nanmean", "nansum", "logsumexp", "amax", "amin", "all", "any", "count_nonzero",
    # cumulative
    "cumsum", "cumprod", "cummax", "cummin", "logcumsumexp", "diff",
    # comparisons returning bool handled in logic.py; numeric checks here
    "isfinite", "isinf", "isnan", "isneginf", "isposinf", "isreal",
    "allclose", "isclose", "equal_all", "trace", "diagonal",
]


# ---------------------------------------------------------------------------
# binary elementwise
# ---------------------------------------------------------------------------

@register_op("add", "math", ref="phi/kernels/elementwise_add_kernel.h")
def add(x, y, name=None):
    return binary("add", jnp.add, x, y)


@register_op("subtract", "math")
def subtract(x, y, name=None):
    return binary("subtract", jnp.subtract, x, y)


@register_op("multiply", "math")
def multiply(x, y, name=None):
    return binary("multiply", jnp.multiply, x, y)


@register_op("divide", "math")
def divide(x, y, name=None):
    return binary("divide", jnp.divide, x, y)


@register_op("floor_divide", "math")
def floor_divide(x, y, name=None):
    return binary("floor_divide", jnp.floor_divide, x, y)


@register_op("mod", "math")
def mod(x, y, name=None):
    return binary("mod", jnp.mod, x, y)


remainder = mod
floor_mod = mod


@register_op("pow", "math")
def pow(x, y, name=None):
    return binary("pow", jnp.power, x, y)


@register_op("maximum", "math")
def maximum(x, y, name=None):
    return binary("maximum", jnp.maximum, x, y)


@register_op("minimum", "math")
def minimum(x, y, name=None):
    return binary("minimum", jnp.minimum, x, y)


@register_op("fmax", "math")
def fmax(x, y, name=None):
    return binary("fmax", jnp.fmax, x, y)


@register_op("fmin", "math")
def fmin(x, y, name=None):
    return binary("fmin", jnp.fmin, x, y)


@register_op("atan2", "math")
def atan2(x, y, name=None):
    return binary("atan2", jnp.arctan2, x, y)


@register_op("heaviside", "math")
def heaviside(x, y, name=None):
    return binary("heaviside", jnp.heaviside, x, y)


@register_op("gcd", "math", differentiable=False)
def gcd(x, y, name=None):
    return binary("gcd", jnp.gcd, x, y)


@register_op("lcm", "math", differentiable=False)
def lcm(x, y, name=None):
    return binary("lcm", jnp.lcm, x, y)


@register_op("nextafter", "math", differentiable=False)
def nextafter(x, y, name=None):
    return binary("nextafter", jnp.nextafter, x, y)


@register_op("copysign", "math")
def copysign(x, y, name=None):
    return binary("copysign", jnp.copysign, x, y)


@register_op("ldexp", "math")
def ldexp(x, y, name=None):
    return binary("ldexp", jnp.ldexp, x, y)


@register_op("hypot", "math")
def hypot(x, y, name=None):
    return binary("hypot", jnp.hypot, x, y)


@register_op("lerp", "math")
def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return nary("lerp", lambda a, b, w: a + w * (b - a), (x, y, weight))
    return binary("lerp", lambda a, b: a + weight * (b - a), x, y)


# ---------------------------------------------------------------------------
# unary elementwise
# ---------------------------------------------------------------------------

def _u(name, fn):
    @register_op(name, "math")
    def op(x, name=None, _fn=fn, _opname=name):
        return unary(_opname, _fn, x)
    op.__name__ = name
    op.__qualname__ = name
    return op


sqrt = _u("sqrt", jnp.sqrt)
rsqrt = _u("rsqrt", jax.lax.rsqrt)
exp = _u("exp", jnp.exp)
expm1 = _u("expm1", jnp.expm1)
log = _u("log", jnp.log)
log2 = _u("log2", jnp.log2)
log10 = _u("log10", jnp.log10)
log1p = _u("log1p", jnp.log1p)
abs = _u("abs", jnp.abs)
neg = _u("neg", jnp.negative)
sign = _u("sign", jnp.sign)
sgn = _u("sgn", jnp.sign)
sin = _u("sin", jnp.sin)
cos = _u("cos", jnp.cos)
tan = _u("tan", jnp.tan)
asin = _u("asin", jnp.arcsin)
acos = _u("acos", jnp.arccos)
atan = _u("atan", jnp.arctan)
sinh = _u("sinh", jnp.sinh)
cosh = _u("cosh", jnp.cosh)
tanh = _u("tanh", jnp.tanh)
asinh = _u("asinh", jnp.arcsinh)
acosh = _u("acosh", jnp.arccosh)
atanh = _u("atanh", jnp.arctanh)
ceil = _u("ceil", jnp.ceil)
floor = _u("floor", jnp.floor)
round = _u("round", jnp.round)
trunc = _u("trunc", jnp.trunc)
reciprocal = _u("reciprocal", jnp.reciprocal)
square = _u("square", jnp.square)
erf = _u("erf", jax.scipy.special.erf)
erfinv = _u("erfinv", jax.scipy.special.erfinv)
lgamma = _u("lgamma", jax.scipy.special.gammaln)
digamma = _u("digamma", jax.scipy.special.digamma)
frac = _u("frac", lambda v: v - jnp.trunc(v))
rad2deg = _u("rad2deg", jnp.rad2deg)
deg2rad = _u("deg2rad", jnp.deg2rad)
angle = _u("angle", jnp.angle)
conj = _u("conj", jnp.conj)
real = _u("real", jnp.real)
imag = _u("imag", jnp.imag)
i0 = _u("i0", jax.scipy.special.i0)
i0e = _u("i0e", jax.scipy.special.i0e)
i1 = _u("i1", jax.scipy.special.i1)
i1e = _u("i1e", jax.scipy.special.i1e)
sinc = _u("sinc", jnp.sinc)
isreal = _u("isreal", jnp.isreal)


@register_op("polygamma", "math")
def polygamma(x, n, name=None):
    return unary("polygamma", lambda v: jax.scipy.special.polygamma(n, v), x)


@register_op("logit", "math")
def logit(x, eps=None, name=None):
    def fn(v):
        if eps is not None:
            v = jnp.clip(v, eps, 1.0 - eps)
        return jnp.log(v / (1.0 - v))
    return unary("logit", fn, x)


@register_op("nan_to_num", "math")
def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return unary("nan_to_num",
                 lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf,
                                          neginf=neginf), x)


@register_op("scale", "math")
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s = scalar_or_value(scale)
    if bias_after_scale:
        out = unary("scale", lambda v: v * jnp.asarray(s, v.dtype) + jnp.asarray(bias, v.dtype), x)
    else:
        out = unary("scale", lambda v: (v + jnp.asarray(bias, v.dtype)) * jnp.asarray(s, v.dtype), x)
    return out


@register_op("stanh", "math")
def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return unary("stanh", lambda v: scale_b * jnp.tanh(scale_a * v), x)


@register_op("clip", "math")
def clip(x, min=None, max=None, name=None):
    mn = scalar_or_value(min)
    mx = scalar_or_value(max)
    return unary("clip", lambda v: jnp.clip(v, mn, mx), x)


@register_op("increment", "math")
def increment(x, value=1.0, name=None):
    x = ensure_tensor(x)
    x._value = x._value + jnp.asarray(value, x._value.dtype)
    return x


@register_op("multiplex", "math")
def multiplex(inputs, index, name=None):
    idx = const_input(index)

    def fn(*vals):
        iv = vals[-1].reshape(-1)
        stacked = jnp.stack(vals[:-1])      # [n, batch, ...]
        rows = jnp.arange(stacked.shape[1])
        return stacked[iv, rows]
    return nary("multiplex", fn, list(inputs) + [idx])


# ---------------------------------------------------------------------------
# matmul family — the MXU path
# ---------------------------------------------------------------------------

@register_op("matmul", "math", ref="phi/kernels/matmul_kernel.h")
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        return jnp.matmul(a, b)
    return binary("matmul", fn, x, y)


@register_op("mm", "math")
def mm(input, mat2, name=None):
    return binary("matmul", jnp.matmul, input, mat2)


@register_op("bmm", "math")
def bmm(x, y, name=None):
    return binary("matmul", jnp.matmul, x, y)


@register_op("dot", "math")
def dot(x, y, name=None):
    return binary("dot", lambda a, b: jnp.sum(a * b, axis=-1), x, y)


@register_op("mv", "math")
def mv(x, vec, name=None):
    return binary("matmul", jnp.matmul, x, vec)


@register_op("addmm", "math")
def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return nary("addmm", lambda i, a, b: beta * i + alpha * (a @ b),
                (input, x, y))


@register_op("t", "math")
def t(input, name=None):
    x = ensure_tensor(input)
    if x.ndim > 2:
        raise ValueError("paddle.t only supports <=2-D tensors")
    return unary("t", lambda v: v.T, x)


@register_op("inner", "math")
def inner(x, y, name=None):
    return binary("inner", jnp.inner, x, y)


@register_op("outer", "math")
def outer(x, y, name=None):
    return binary("outer", lambda a, b: jnp.outer(a, b), x, y)


@register_op("kron", "math")
def kron(x, y, name=None):
    return binary("kron", jnp.kron, x, y)


@register_op("trace", "math")
def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return unary("trace", lambda v: jnp.trace(v, offset=offset, axis1=axis1,
                                              axis2=axis2), x)


@register_op("diagonal", "math")
def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return unary("diagonal", lambda v: jnp.diagonal(v, offset=offset,
                                                    axis1=axis1, axis2=axis2), x)


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------

def _reduce(name, jfn, x, axis=None, keepdim=False, dtype=None):
    x = ensure_tensor(x)
    ax = axis_tuple(axis, x.ndim)
    jd = to_jax_dtype(dtype) if dtype is not None else None
    def fn(v):
        out = jfn(v, axis=ax, keepdims=keepdim) if jd is None else \
            jfn(v, axis=ax, keepdims=keepdim, dtype=jd)
        return out
    return unary(name, fn, x)


@register_op("sum", "reduction", ref="phi/kernels/reduce_sum_kernel.h")
def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    if dtype is None and jnp_dtype(x) in (jnp.int32.dtype, jnp.bool_.dtype):
        dtype = "int64"
    return _reduce("sum", jnp.sum, x, axis, keepdim, dtype)


@register_op("mean", "reduction")
def mean(x, axis=None, keepdim=False, name=None):
    return _reduce("mean", jnp.mean, x, axis, keepdim)


@register_op("max", "reduction")
def max(x, axis=None, keepdim=False, name=None):
    return _reduce("max", jnp.max, x, axis, keepdim)


@register_op("min", "reduction")
def min(x, axis=None, keepdim=False, name=None):
    return _reduce("min", jnp.min, x, axis, keepdim)


@register_op("amax", "reduction")
def amax(x, axis=None, keepdim=False, name=None):
    return _reduce("amax", jnp.max, x, axis, keepdim)


@register_op("amin", "reduction")
def amin(x, axis=None, keepdim=False, name=None):
    return _reduce("amin", jnp.min, x, axis, keepdim)


@register_op("prod", "reduction")
def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    return _reduce("prod", jnp.prod, x, axis, keepdim, dtype)


@register_op("nanmean", "reduction")
def nanmean(x, axis=None, keepdim=False, name=None):
    return _reduce("nanmean", jnp.nanmean, x, axis, keepdim)


@register_op("nansum", "reduction")
def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return _reduce("nansum", jnp.nansum, x, axis, keepdim, dtype)


@register_op("std", "reduction")
def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = axis_tuple(axis, x.ndim)
    ddof = 1 if unbiased else 0
    return unary("std", lambda v: jnp.std(v, axis=ax, ddof=ddof,
                                          keepdims=keepdim), x)


@register_op("var", "reduction")
def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = axis_tuple(axis, x.ndim)
    ddof = 1 if unbiased else 0
    return unary("var", lambda v: jnp.var(v, axis=ax, ddof=ddof,
                                          keepdims=keepdim), x)


@register_op("median", "reduction")
def median(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = None if axis is None else axis
    return unary("median", lambda v: jnp.median(v, axis=ax, keepdims=keepdim), x)


@register_op("nanmedian", "reduction")
def nanmedian(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    return unary("nanmedian", lambda v: jnp.nanmedian(v, axis=axis,
                                                      keepdims=keepdim), x)


@register_op("logsumexp", "reduction")
def logsumexp(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = axis_tuple(axis, x.ndim)
    return unary("logsumexp", lambda v: jax.scipy.special.logsumexp(
        v, axis=ax, keepdims=keepdim), x)


@register_op("all", "reduction", differentiable=False)
def all(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = axis_tuple(axis, x.ndim)
    return Tensor(jnp.all(x._value, axis=ax, keepdims=keepdim))


@register_op("any", "reduction", differentiable=False)
def any(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = axis_tuple(axis, x.ndim)
    return Tensor(jnp.any(x._value, axis=ax, keepdims=keepdim))


@register_op("count_nonzero", "reduction", differentiable=False)
def count_nonzero(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = axis_tuple(axis, x.ndim)
    return Tensor(jnp.count_nonzero(x._value, axis=ax, keepdims=keepdim)
                  .astype(jnp.int64))


# ---------------------------------------------------------------------------
# cumulative
# ---------------------------------------------------------------------------

@register_op("cumsum", "math")
def cumsum(x, axis=None, dtype=None, name=None):
    x = ensure_tensor(x)
    jd = to_jax_dtype(dtype) if dtype else None
    if axis is None:
        return unary("cumsum", lambda v: jnp.cumsum(v.reshape(-1), dtype=jd), x)
    return unary("cumsum", lambda v: jnp.cumsum(v, axis=axis, dtype=jd), x)


@register_op("cumprod", "math")
def cumprod(x, dim=None, dtype=None, name=None):
    x = ensure_tensor(x)
    jd = to_jax_dtype(dtype) if dtype else None
    return unary("cumprod", lambda v: jnp.cumprod(v, axis=dim, dtype=jd), x)


@register_op("cummax", "math", differentiable=False)
def cummax(x, axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)
    v = x._value if axis is not None else x._value.reshape(-1)
    ax = axis if axis is not None else 0
    # running argmax via associative scan over (value, index) pairs
    n = v.shape[ax]
    idx = jnp.arange(n).reshape([-1 if i == ax % v.ndim else 1
                                 for i in range(v.ndim)])
    idx = jnp.broadcast_to(idx, v.shape)
    def combine(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv >= av
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)
    vals, inds = jax.lax.associative_scan(combine, (v, idx), axis=ax)
    return Tensor(vals), Tensor(inds.astype(to_jax_dtype(dtype)))


@register_op("cummin", "math", differentiable=False)
def cummin(x, axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)
    v = x._value if axis is not None else x._value.reshape(-1)
    ax = axis if axis is not None else 0
    n = v.shape[ax]
    idx = jnp.arange(n).reshape([-1 if i == ax % v.ndim else 1
                                 for i in range(v.ndim)])
    idx = jnp.broadcast_to(idx, v.shape)
    def combine(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv <= av
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)
    vals, inds = jax.lax.associative_scan(combine, (v, idx), axis=ax)
    return Tensor(vals), Tensor(inds.astype(to_jax_dtype(dtype)))


@register_op("logcumsumexp", "math")
def logcumsumexp(x, axis=None, name=None):
    x = ensure_tensor(x)
    def fn(v):
        vv = v if axis is not None else v.reshape(-1)
        ax = axis if axis is not None else 0
        return jax.lax.cumlogsumexp(vv, axis=ax)
    return unary("logcumsumexp", fn, x)


@register_op("diff", "math")
def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    x = ensure_tensor(x)
    # prepend/append ride as dispatch inputs (None stays a keyable
    # closure constant); has_pre/has_app select them inside the fn
    extra = tuple(const_input(t) for t in (prepend, append)
                  if t is not None)
    has_pre, has_app = prepend is not None, append is not None

    def fn(v, *pa):
        it = iter(pa)
        pre = next(it) if has_pre else None
        app = next(it) if has_app else None
        return jnp.diff(v, n=n, axis=axis, prepend=pre, append=app)
    return call_op("diff", fn, (x,) + extra)


# ---------------------------------------------------------------------------
# float-status checks
# ---------------------------------------------------------------------------

def _check(name, fn):
    @register_op(name, "math", differentiable=False)
    def op(x, name=None, _fn=fn):
        return Tensor(_fn(ensure_tensor(x)._value))
    op.__name__ = name
    return op


isfinite = _check("isfinite", jnp.isfinite)
isinf = _check("isinf", jnp.isinf)
isnan = _check("isnan", jnp.isnan)
isneginf = _check("isneginf", jnp.isneginf)
isposinf = _check("isposinf", jnp.isposinf)


@register_op("allclose", "math", differentiable=False)
def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return Tensor(jnp.allclose(x._value, y._value, rtol=rtol, atol=atol,
                               equal_nan=equal_nan))


@register_op("isclose", "math", differentiable=False)
def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return Tensor(jnp.isclose(x._value, y._value, rtol=rtol, atol=atol,
                              equal_nan=equal_nan))


@register_op("equal_all", "math", differentiable=False)
def equal_all(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    if x.shape != y.shape:
        return Tensor(jnp.asarray(False))
    return Tensor(jnp.array_equal(x._value, y._value))
