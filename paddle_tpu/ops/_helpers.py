"""Shared helpers for op wrappers."""
from __future__ import annotations

import numbers

import numpy as np
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework.dtype import get_default_dtype, to_jax_dtype
from .dispatch import call_op, call_op_multi

__all__ = ["ensure_tensor", "unary", "binary", "nary", "scalar_or_value",
           "call_op", "call_op_multi", "axis_tuple", "jnp_dtype",
           "const_input"]


def jnp_dtype(t):
    """jnp dtype of a Tensor, answered from chain metadata when `t` is a
    deferred fusion placeholder (ops/fusion.py) — pre-dispatch dtype peeks
    in op wrappers must not force a pending chain to materialize. (Shape
    peeks use Tensor.shape/ndim, which are already aval-answerable.)"""
    av = getattr(t, "_fusion_aval", None)
    return av[1] if av is not None else t._value.dtype


def ensure_tensor(x, dtype=None):
    if isinstance(x, Tensor):
        return x
    if isinstance(x, (numbers.Number, np.bool_)):
        return Tensor(jnp.asarray(x))
    return Tensor(jnp.asarray(x, dtype=to_jax_dtype(dtype) if dtype else None))


def unary(name, fn, x):
    """Dispatch fn(x) where all non-tensor args are closed over in fn."""
    return call_op(name, fn, (ensure_tensor(x),))


def binary(name, fn, x, y):
    """Dispatch fn(x, y), keeping python scalars as closures (they carry no
    grad and shouldn't force weak-type promotion surprises)."""
    x_is_t = isinstance(x, Tensor)
    y_is_t = isinstance(y, Tensor)
    if x_is_t and y_is_t:
        return call_op(name, fn, (x, y))
    if x_is_t:
        return call_op(name, lambda a: fn(a, y if isinstance(y, numbers.Number)
                                          else jnp.asarray(y)), (x,))
    if y_is_t:
        return call_op(name, lambda b: fn(x if isinstance(x, numbers.Number)
                                          else jnp.asarray(x), b), (y,))
    return call_op(name, fn, (ensure_tensor(x), ensure_tensor(y)))


def nary(name, fn, tensors):
    return call_op(name, fn, tuple(ensure_tensor(t) for t in tensors))


def const_input(x, dtype=None):
    """Thread a value into an op as a NON-differentiable dispatch input.

    The replacement for baking an index/mask/label/stat array into the op
    fn's closure (the PR 3/4 `unkeyable_closure` bug class, now linted by
    analysis rule R1): as an input the value joins the cache key's avals
    — the op keys on structure and stays chain/step-promotable — while
    `stop_gradient` keeps it off the tape exactly like the closure
    constant it replaces."""
    t = ensure_tensor(x, dtype)
    return t if t.stop_gradient else t.detach()


def scalar_or_value(v):
    """Extract a python scalar / numpy value from Tensor-or-scalar attrs."""
    if isinstance(v, Tensor):
        return v._value
    return v


def axis_tuple(axis, ndim):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(a % ndim for a in axis)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return (axis % ndim,)
