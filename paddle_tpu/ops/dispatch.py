"""Op dispatch: the single funnel every eager op call goes through.

Reference analog: the generated `<op>_ad_func` forwards
(eager/auto_code_generator/generator/eager_gen.py:1217) — AMP cast, kernel
call, GradNode creation + Edge wiring. TPU-first: the "kernel" is a jax
callable; when grad is required the VJP is captured at forward time via
`jax.vjp`, so residuals are device arrays and backward is XLA-compiled.
"""
from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework.autograd import GradNode, is_grad_enabled

__all__ = ["call_op", "call_op_multi"]


def _values(tensors):
    return tuple(t._value for t in tensors)


def _differentiable(t):
    return (not t.stop_gradient) and jnp.issubdtype(t._value.dtype, jnp.inexact)


def _requires_grad(tensors):
    return is_grad_enabled() and any(_differentiable(t) for t in tensors)


def _amp_transform(op_name, tensors):
    """Apply AMP autocast policy if active (mirrors eager amp_utils.h)."""
    from ..amp.auto_cast import amp_cast_inputs
    return amp_cast_inputs(op_name, tensors)


def _make_edges(tensors):
    edges = []
    for t in tensors:
        if not _differentiable(t):
            edges.append(None)
        else:
            node = t._grad_node if t._grad_node is not None else t._ensure_grad_node()
            edges.append((node, t._out_index))
    return edges


def call_op(name: str, fn: Callable, inputs: Sequence[Tensor], **_ignored) -> Tensor:
    """Dispatch a single-output op. `fn` maps jax values -> jax value; all
    non-tensor arguments must already be closed over in `fn`."""
    inputs = _amp_transform(name, inputs)
    vals = _values(inputs)
    if not _requires_grad(inputs):
        return Tensor(fn(*vals), stop_gradient=True)

    diff_mask = [_differentiable(t) for t in inputs]
    if all(diff_mask):
        out_val, vjp_fn = jax.vjp(fn, *vals)
        wrapped_vjp = vjp_fn
    else:
        # only differentiate w.r.t. non-stop-gradient inputs; close over the rest
        diff_idx = [i for i, d in enumerate(diff_mask) if d]

        def partial_fn(*diff_vals):
            full = list(vals)
            for i, v in zip(diff_idx, diff_vals):
                full[i] = v
            return fn(*full)

        out_val, vjp_fn = jax.vjp(partial_fn, *(vals[i] for i in diff_idx))

        def wrapped_vjp(g, _vjp=vjp_fn, _idx=diff_idx, _n=len(inputs)):
            partial = _vjp(g)
            full = [None] * _n
            for i, pg in zip(_idx, partial):
                full[i] = pg
            return tuple(full)

    node = GradNode(name, wrapped_vjp, _make_edges(inputs),
                    ((out_val.shape, out_val.dtype),))
    out = Tensor(out_val, stop_gradient=False)
    out._grad_node = node
    out._out_index = 0
    return out


def call_op_multi(name: str, fn: Callable, inputs: Sequence[Tensor],
                  num_outputs: int) -> list:
    """Dispatch an op whose fn returns a tuple of `num_outputs` jax values."""
    inputs = _amp_transform(name, inputs)
    vals = _values(inputs)
    if not _requires_grad(inputs):
        out_vals = fn(*vals)
        return [Tensor(v, stop_gradient=True) for v in out_vals]

    diff_mask = [_differentiable(t) for t in inputs]
    diff_idx = [i for i, d in enumerate(diff_mask) if d]

    def partial_fn(*diff_vals):
        full = list(vals)
        for i, v in zip(diff_idx, diff_vals):
            full[i] = v
        return fn(*full)

    out_vals, vjp_fn = jax.vjp(partial_fn, *(vals[i] for i in diff_idx))

    def wrapped_vjp(gs, _vjp=vjp_fn, _idx=diff_idx, _n=len(inputs)):
        partial = _vjp(gs)
        full = [None] * _n
        for i, pg in zip(_idx, partial):
            full[i] = pg
        return tuple(full)

    node = GradNode(name, wrapped_vjp, _make_edges(inputs),
                    tuple((v.shape, v.dtype) for v in out_vals))
    outs = []
    for j, v in enumerate(out_vals):
        t = Tensor(v, stop_gradient=False)
        t._grad_node = node
        t._out_index = j
        outs.append(t)
    return outs
