"""Op dispatch: the single funnel every eager op call goes through.

Reference analog: the generated `<op>_ad_func` forwards
(eager/auto_code_generator/generator/eager_gen.py:1217) — AMP cast, kernel
call, GradNode creation + Edge wiring. TPU-first: the "kernel" is a jax
callable; when grad is required the VJP is captured at forward time via
`jax.vjp`, so residuals are device arrays and backward is XLA-compiled.
"""
from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework.autograd import pack_saved_values as _pack_saved, GradNode, is_grad_enabled
from ..framework.flags import _FLAGS

__all__ = ["call_op", "call_op_multi"]


def _values(tensors):
    return tuple(t._value for t in tensors)


def _debug_checks(name, out_vals):
    """FLAGS_check_nan_inf: scan op outputs for non-finite values, raising
    (level 0) or warning (level >= 1) with the op name — the eager analog of
    framework/details/nan_inf_utils.h:29 CheckOpHasNanOrInf.
    FLAGS_benchmark: block until the op's result is ready so per-op wall
    times are honest (platform/flags.cc FLAGS_benchmark sync semantics)."""
    if _FLAGS.get("FLAGS_check_nan_inf"):
        from jax.errors import TracerBoolConversionError
        for v in out_vals:
            if not jnp.issubdtype(v.dtype, jnp.inexact):
                continue
            try:
                finite = bool(jnp.all(jnp.isfinite(v)))
            except TracerBoolConversionError:
                continue   # inside a jit trace: the fused TrainStep checks
            if not finite:
                msg = f"Operator '{name}' output contains NaN/Inf"
                if int(_FLAGS.get("FLAGS_check_nan_inf_level", 0)) == 0:
                    raise FloatingPointError(msg)
                import warnings
                warnings.warn(msg)
    elif _FLAGS.get("FLAGS_benchmark"):
        for v in out_vals:
            jax.block_until_ready(v)


def _differentiable(t):
    return (not t.stop_gradient) and jnp.issubdtype(t._value.dtype, jnp.inexact)


def _requires_grad(tensors):
    return is_grad_enabled() and any(_differentiable(t) for t in tensors)


def _amp_transform(op_name, tensors):
    """Apply AMP autocast policy if active (mirrors eager amp_utils.h)."""
    from ..amp.auto_cast import amp_cast_inputs
    return amp_cast_inputs(op_name, tensors)


def _make_edges(tensors):
    edges = []
    for t in tensors:
        if not _differentiable(t):
            edges.append(None)
        else:
            node = t._grad_node if t._grad_node is not None else t._ensure_grad_node()
            edges.append((node, t._out_index))
    return edges


def call_op(name: str, fn: Callable, inputs: Sequence[Tensor], **_ignored) -> Tensor:
    """Dispatch a single-output op. `fn` maps jax values -> jax value; all
    non-tensor arguments must already be closed over in `fn`."""
    from .registry import _active_override
    override = _active_override(name)
    if override is not None:
        fn = override
    inputs = _amp_transform(name, inputs)
    vals = _values(inputs)
    debug = _FLAGS.get("FLAGS_check_nan_inf") or _FLAGS.get("FLAGS_benchmark")
    if not _requires_grad(inputs):
        out_val = fn(*vals)
        if debug:
            _debug_checks(name, (out_val,))
        return Tensor(out_val, stop_gradient=True)

    diff_mask = [_differentiable(t) for t in inputs]
    if all(diff_mask):
        out_val, vjp_fn = jax.vjp(fn, *vals)
        wrapped_vjp = vjp_fn
    else:
        # only differentiate w.r.t. non-stop-gradient inputs; close over the rest
        diff_idx = [i for i, d in enumerate(diff_mask) if d]

        def partial_fn(*diff_vals):
            full = list(vals)
            for i, v in zip(diff_idx, diff_vals):
                full[i] = v
            return fn(*full)

        out_val, vjp_fn = jax.vjp(partial_fn, *(vals[i] for i in diff_idx))

        def wrapped_vjp(g, _vjp=vjp_fn, _idx=diff_idx, _n=len(inputs)):
            partial = _vjp(g)
            full = [None] * _n
            for i, pg in zip(_idx, partial):
                full[i] = pg
            return tuple(full)

    if debug:
        _debug_checks(name, (out_val,))
    node = GradNode(name, wrapped_vjp, _make_edges(inputs),
                    ((out_val.shape, out_val.dtype),))
    node.fwd_fn = fn
    node.in_vals, node.unpack_hook = _pack_saved(vals, node.edges)
    out = Tensor(out_val, stop_gradient=False)
    out._grad_node = node
    out._out_index = 0
    return out


def call_op_multi(name: str, fn: Callable, inputs: Sequence[Tensor],
                  num_outputs: int) -> list:
    """Dispatch an op whose fn returns a tuple of `num_outputs` jax values."""
    from .registry import _active_override
    override = _active_override(name)
    if override is not None:
        fn = override
    inputs = _amp_transform(name, inputs)
    vals = _values(inputs)
    debug = _FLAGS.get("FLAGS_check_nan_inf") or _FLAGS.get("FLAGS_benchmark")
    if not _requires_grad(inputs):
        out_vals = fn(*vals)
        if debug:
            _debug_checks(name, out_vals)
        return [Tensor(v, stop_gradient=True) for v in out_vals]

    diff_mask = [_differentiable(t) for t in inputs]
    diff_idx = [i for i, d in enumerate(diff_mask) if d]

    def partial_fn(*diff_vals):
        full = list(vals)
        for i, v in zip(diff_idx, diff_vals):
            full[i] = v
        return fn(*full)

    out_vals, vjp_fn = jax.vjp(partial_fn, *(vals[i] for i in diff_idx))
    if debug:
        _debug_checks(name, out_vals)

    def wrapped_vjp(gs, _vjp=vjp_fn, _idx=diff_idx, _n=len(inputs)):
        if not isinstance(gs, tuple):
            # the engine passes a bare cotangent when the op has exactly one
            # output; jax.vjp of a tuple-returning fn wants a tuple
            gs = (gs,)
        partial = _vjp(gs)
        full = [None] * _n
        for i, pg in zip(_idx, partial):
            full[i] = pg
        return tuple(full)

    node = GradNode(name, wrapped_vjp, _make_edges(inputs),
                    tuple((v.shape, v.dtype) for v in out_vals))
    node.fwd_fn = fn
    node.in_vals, node.unpack_hook = _pack_saved(vals, node.edges)
    outs = []
    for j, v in enumerate(out_vals):
        t = Tensor(v, stop_gradient=False)
        t._grad_node = node
        t._out_index = j
        outs.append(t)
    return outs
