"""Op dispatch: the single funnel every eager op call goes through.

Reference analog: the generated `<op>_ad_func` forwards
(eager/auto_code_generator/generator/eager_gen.py:1217) — AMP cast, kernel
call, GradNode creation + Edge wiring. TPU-first: the "kernel" is a jax
callable; when grad is required the VJP is captured at forward time, so
residuals are device arrays and backward is XLA-compiled.

Compiled eager dispatch (the `<op>_ad_func` fast-path analog). The reference
beat per-op dispatch overhead with the PHI kernel library plus codegen'd C++
forwards; here the same cost is beaten with a per-op executable cache:

  key   = (op name, fn token, input (shape, dtype, weak_type) avals,
           diff mask, AMP-state token, registry override token,
           guardian check flag)
  value = a jitted forward (no-grad path), or a jitted forward+vjp pair
          (grad path) whose vjp comes back as a `jax.tree_util.Partial`
          pytree — residual buffers as leaves — applied through one shared
          jitted applier, so backward reuses a compiled executable too
          instead of re-tracing `jax.vjp` on every differentiable call.

The fn token keys the implementation by VALUE: code object + closure cell
contents, accepted only for types whose hash is value-based (scalars,
dtypes, nested tuples/functions). Anything else — arrays, Tensors in
closures, tracer inputs, jit-incompatible ops — bypasses the cache and
takes the original eager path, so caching can never change numerics, only
whether jax re-traces. Registry override (de)activation bumps a per-op
generation counter (ops/registry.py) that is part of the key, so stale
entries become unreachable and age out of the LRU. Flags:
framework/flags.py FLAGS_eager_op_cache / _size / _donate; telemetry:
paddle_tpu.profiler.dispatch_cache_stats().
"""
from __future__ import annotations

import enum
import functools
import threading
import time
import types
from collections import OrderedDict
from typing import Callable, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework.autograd import pack_saved_values as _pack_saved, GradNode, is_grad_enabled
from ..framework.flags import _FLAGS
from ..profiler.dispatch import STATS as _STATS
from ..profiler.events import EVENTS as _EVENTS
from . import guardian as _guardian
from . import aot_cache as _aot

__all__ = ["call_op", "call_op_multi", "clear_dispatch_cache",
           "dispatch_cache_info", "mark_collective"]


def _values(tensors):
    return tuple(t._value for t in tensors)


def _scan_nan_inf(name, out_vals):
    """FLAGS_check_nan_inf: scan op outputs for non-finite values, raising
    (level 0) or warning (level >= 1) with the op name — the eager analog of
    framework/details/nan_inf_utils.h:29 CheckOpHasNanOrInf. Forces a device
    sync per inexact output (the reduction must materialize)."""
    from jax.errors import TracerBoolConversionError
    for v in out_vals:
        if not jnp.issubdtype(v.dtype, jnp.inexact):
            continue
        try:
            finite = bool(jnp.all(jnp.isfinite(v)))
        except TracerBoolConversionError:
            continue   # inside a jit trace: the fused TrainStep checks
        if not finite:
            msg = f"Operator '{name}' output contains NaN/Inf"
            if int(_FLAGS.get("FLAGS_check_nan_inf_level", 0)) == 0:
                raise FloatingPointError(msg)
            import warnings
            warnings.warn(msg)


def _sync_outputs(out_vals):
    """FLAGS_benchmark: block until the op's results are ready so per-op wall
    times are honest (platform/flags.cc FLAGS_benchmark sync semantics).
    Pure wait — no reduction, no transfer."""
    for v in out_vals:
        jax.block_until_ready(v)


def _debug_checks(name, out_vals):
    """Split debug paths: the NaN scan (device-syncing reduction) and the
    benchmark sync (pure wait) are independent helpers, so benchmark mode
    never pays the NaN reduction."""
    if _FLAGS.get("FLAGS_check_nan_inf"):
        _scan_nan_inf(name, out_vals)
    elif _FLAGS.get("FLAGS_benchmark"):
        _sync_outputs(out_vals)


def _input_aval(t):
    """(shape, dtype, weak_type) of a dispatch input. Answered from chain
    metadata for a deferred fusion placeholder (ops/fusion.py) so keying
    never forces a pending chain to materialize; None means the input is a
    tracer and the call must bypass the cache."""
    av = getattr(t, "_fusion_aval", None)
    if av is not None:
        return av
    v = t._value
    if isinstance(v, jax.core.Tracer):
        # inside an outer trace (TrainStep/to_static) the op is absorbed
        # into the enclosing jaxpr; caching per-trace executables would
        # only pollute the LRU and risk nested-jit edge cases
        return None
    return (v.shape, v.dtype, getattr(v, "weak_type", False))


def _differentiable(t):
    av = getattr(t, "_fusion_aval", None)
    if av is not None:
        return (not t.stop_gradient) and jnp.issubdtype(av[1], jnp.inexact)
    return (not t.stop_gradient) and jnp.issubdtype(t._value.dtype, jnp.inexact)


def _requires_grad(tensors):
    return is_grad_enabled() and any(_differentiable(t) for t in tensors)


def _amp_transform(op_name, tensors):
    """Apply AMP autocast policy if active (mirrors eager amp_utils.h)."""
    from ..amp.auto_cast import amp_cast_inputs
    return amp_cast_inputs(op_name, tensors)


def _make_edges(tensors):
    edges = []
    for t in tensors:
        if not _differentiable(t):
            edges.append(None)
        else:
            node = t._grad_node if t._grad_node is not None else t._ensure_grad_node()
            edges.append((node, t._out_index))
    return edges


# ---------------------------------------------------------------------------
# cache keying: hash op implementations by VALUE, or refuse
# ---------------------------------------------------------------------------

_UNKEYABLE = object()

# Per-thread keying-failure context for the fusion flight recorder: WHAT
# kind of value made the last key attempt fail (array/tensor/object/tracer)
# and the RNG epoch at the last classified bypass — together they turn an
# anonymous bypass into a `rng_rekey` / `unkeyable_closure` / `tracer_input`
# reason code (profiler/events.py). Written only on the (already slow)
# bypass path; the keyable fast path never touches it.
_keyctx = threading.local()


def _note_unkeyable(v):
    if isinstance(v, Tensor):
        _keyctx.kind = "tensor"
    elif hasattr(v, "shape") and hasattr(v, "dtype"):
        _keyctx.kind = "array"
    else:
        _keyctx.kind = "object"


def _classify_bypass(name):
    """Reason code for a key=None bypass, consuming the per-thread keying
    context. An array-like closure capture right after a global-RNG epoch
    advance is the dropout signature: the op re-keys every call."""
    kind = getattr(_keyctx, "kind", None)
    _keyctx.kind = None
    if kind == "tracer":
        return "tracer_input"
    if kind == "collective":
        # a collective op whose group/mesh could not be canonically keyed
        # (distributed/collective.py mark_collective): the cycle can never
        # promote around it — the doctor names this directly
        return "collective_unkeyed"
    if kind in ("array", "tensor"):
        from ..framework.random import rng_epoch
        ep = rng_epoch()
        seen = getattr(_keyctx, "rng_seen", None)
        _keyctx.rng_seen = ep
        # the very first classified bypass has no epoch baseline — stay
        # conservative (unkeyable_closure) rather than blaming the RNG
        if seen is not None and ep != seen:
            return "rng_rekey"
    return "unkeyable_closure"

# Types whose hash/equality is value-based and whose value cannot change
# under the key's feet. Anything outside this set (arrays, Tensors — whose
# __hash__ is id() but whose _value mutates in-place, arbitrary objects)
# makes the fn un-keyable: baking such a cell into a cached trace would go
# stale silently.
_SAFE_SCALARS = (int, float, bool, complex, str, bytes, type(None), type,
                 np.dtype, np.generic)

# callables without a __code__ object that are still safely identity-keyed:
# stateless module-level singletons (jnp.add is a jnp.ufunc; jnp.exp /
# jax.nn.* are PjitFunction wrappers; python builtins)
_SAFE_CALLABLE_TYPES = (types.BuiltinFunctionType, np.ufunc, jnp.ufunc,
                        type(jax.jit(lambda: None)))


def _token_of(v, depth):
    if depth > 4:
        return _UNKEYABLE
    if isinstance(v, _SAFE_SCALARS) or isinstance(v, enum.Enum):
        return v
    if isinstance(v, slice):
        # slice objects are unhashable (3.10) but value-like: token their
        # (start, stop, step) so indexing ops (ops/manipulation.py slice /
        # strided_slice close over jnp.s_ tuples) stay cacheable
        parts = tuple(_token_of(p, depth + 1)
                      for p in (v.start, v.stop, v.step))
        if any(p is _UNKEYABLE for p in parts):
            return _UNKEYABLE
        return ("slice",) + parts
    if isinstance(v, (tuple, list)):
        items = tuple(_token_of(i, depth + 1) for i in v)
        if any(i is _UNKEYABLE for i in items):
            return _UNKEYABLE
        return (type(v).__name__, items)
    if isinstance(v, dict):
        try:
            keys = sorted(v)
        except TypeError:
            return _UNKEYABLE
        items = tuple((k, _token_of(v[k], depth + 1)) for k in keys)
        if any(t is _UNKEYABLE for _, t in items):
            return _UNKEYABLE
        return ("dict", items)
    if callable(v):
        return _fn_token(v, depth + 1)
    _note_unkeyable(v)
    return _UNKEYABLE


def _stable_library_fn(fn):
    """Module-level functions of the jax/numpy libraries are stable
    singletons: their behavior cannot change under an identity key, so they
    token by identity instead of a deep code/closure/globals scan — the
    same contract _globals_token applies to module-level defs. (Without
    this, a closure cell holding e.g. `lax.max` — pooling reducers — walks
    into jax internals and marks the whole op un-keyable.)"""
    import sys
    mod = getattr(fn, "__module__", None) or ""
    if not (mod in ("jax", "numpy") or mod.startswith(("jax.", "numpy."))):
        return False
    m = sys.modules.get(mod)
    return m is not None and \
        getattr(m, getattr(fn, "__qualname__", ""), None) is fn


# Collective-op keying (distributed/collective.py): a collective's fn
# closes over a compiled process-group callable — unkeyable by the closure
# scan — but its IDENTITY is fully determined by (kind, reduce op, the
# canonical mesh key of its group). mark_collective() stamps that identity
# onto the fn; _fn_token honors it before any closure walk. A collective
# whose mesh cannot be canonically keyed is stamped unkeyable and the
# bypass classifies as `collective_unkeyed`.
_COLLECTIVE_UNKEYABLE = object()


def mark_collective(fn, key):
    """Stamp a collective identity onto an op fn. `key` is a hashable
    (kind, ...) tuple ending in the mesh key (distributed/mesh.mesh_key),
    or None when the group has no canonically-keyable mesh."""
    fn._collective_key = ("collective",) + tuple(key) \
        if key is not None else _COLLECTIVE_UNKEYABLE
    return fn


def _fn_token(fn, depth=0):
    """Value-identity for an op implementation: code object plus closure
    cell / default tokens. Returns _UNKEYABLE when the fn cannot be keyed
    safely (→ the call bypasses the cache)."""
    ck = getattr(fn, "_collective_key", None)
    if ck is not None:
        if ck is _COLLECTIVE_UNKEYABLE:
            _keyctx.kind = "collective"
            return _UNKEYABLE
        return ck
    if depth > 4:
        return _UNKEYABLE
    if isinstance(fn, types.FunctionType) and _stable_library_fn(fn):
        return fn
    if isinstance(fn, functools.partial):
        inner = _fn_token(fn.func, depth + 1)
        args = _token_of(tuple(fn.args), depth + 1)
        kw = _token_of(fn.keywords or {}, depth + 1)
        if _UNKEYABLE in (inner, args, kw):
            return _UNKEYABLE
        return ("partial", inner, args, kw)
    bound_self = getattr(fn, "__self__", None)
    if bound_self is not None:
        # bound method: the code object is shared across instances, so the
        # receiver must be part of the token — which for arbitrary
        # (mutable) objects it can't be → bypass
        stok = _token_of(bound_self, depth + 1)
        inner = _fn_token(getattr(fn, "__func__", None) or fn.__call__,
                          depth + 1) if stok is not _UNKEYABLE else _UNKEYABLE
        if _UNKEYABLE in (stok, inner):
            return _UNKEYABLE
        return ("bound", stok, inner)
    code = getattr(fn, "__code__", None)
    if code is None:
        # no python code object: accept only known-stateless singleton
        # types (jnp ufuncs, jitted wrappers, builtins) whose behavior
        # cannot mutate under an identity key; arbitrary callable objects
        # may carry mutable state (e.g. a Layer's weights) → bypass
        if isinstance(fn, _SAFE_CALLABLE_TYPES):
            return fn
        return _UNKEYABLE
    cells = []
    for cell in (fn.__closure__ or ()):
        try:
            v = cell.cell_contents
        except ValueError:           # empty cell
            return _UNKEYABLE
        t = _token_of(v, depth + 1)
        if t is _UNKEYABLE:
            return _UNKEYABLE
        cells.append(t)
    dflt = _token_of(fn.__defaults__ or (), depth + 1)
    kwdflt = _token_of(getattr(fn, "__kwdefaults__", None) or {}, depth + 1)
    if _UNKEYABLE in (dflt, kwdflt):
        return _UNKEYABLE
    gtok = _globals_token(fn, code, depth)
    if gtok is None:
        return _UNKEYABLE
    return (code, tuple(cells), dflt, kwdflt, gtok)


_code_names_cache: dict = {}


def _all_code_names(code):
    """Sorted co_names of `code` and of every nested code object (inner
    defs / lambdas in co_consts), so globals read by an inner function
    still make it into the key. Code objects are immutable, so the walk is
    memoized per code object (the dict stays small: one row per distinct
    op-fn definition site)."""
    names = _code_names_cache.get(code)
    if names is None:
        def walk(c, out, depth):
            out.update(c.co_names)
            if depth <= 4:
                for const in c.co_consts:
                    if isinstance(const, types.CodeType):
                        walk(const, out, depth + 1)
        acc: set = set()
        walk(code, acc, 0)
        names = _code_names_cache[code] = tuple(sorted(acc))
    return names


def _globals_token(fn, code, depth):
    """Token for the module globals an op fn references (co_names of the fn
    AND its nested code objects, ∩ __globals__): a fn can read mutable
    module state the closure scan never sees, and baking it into a cached
    trace would go stale. Scalars are keyed by value (a changed global →
    new key); modules and module-level functions/classes are stable
    singletons keyed by identity — state read INDIRECTLY through such a
    helper's own globals is frozen at trace time, the same contract as
    jax.jit (recursing into helpers would cascade into dispatch internals
    and mark every op unkeyable); any other global — arrays, Tensors,
    stateful objects — returns None → the call bypasses the cache."""
    g = getattr(fn, "__globals__", None)
    if g is None:
        return ()
    toks = []
    for nm in _all_code_names(code):
        if nm not in g:
            continue                 # builtin or pure attribute name
        v = g[nm]
        if isinstance(v, types.ModuleType):
            continue
        if isinstance(v, (types.FunctionType, type)) \
                or isinstance(v, _SAFE_CALLABLE_TYPES):
            toks.append((nm, v))     # stable module-level def: identity
            continue
        t = _token_of(v, depth + 1)
        if t is _UNKEYABLE:
            return None
        toks.append((nm, t))
    return tuple(toks)


def _amp_token(name):
    from ..amp.auto_cast import current_amp_state
    st = current_amp_state()
    if st is None or not st.enabled:
        return None
    return (st.level, st.dtype, name in st.white, name in st.black)


def _make_key(name, fn, inputs, diff_mask, reg_token, check=False):
    """The cache key, or None when this call must bypass the cache. Takes
    the input TENSORS (not raw values) so avals of deferred fusion
    placeholders come from chain metadata instead of forcing a
    materialization. `check` (FLAGS_check_numerics) is the LAST component:
    executables built under the guardian return an extra all-finite
    scalar, so the two shapes must never share a cache entry — and
    _cached_call reads the flag back off the key to unwrap."""
    ftok = _fn_token(fn)
    if ftok is _UNKEYABLE:
        return None
    avals = []
    for t in inputs:
        av = _input_aval(t)
        if av is None:          # tracer input
            _keyctx.kind = "tracer"
            return None
        avals.append(av)
    return (name, ftok, tuple(avals), diff_mask, _amp_token(name), reg_token,
            check)


# ---------------------------------------------------------------------------
# the executable cache (LRU, FLAGS_eager_op_cache_size entries)
# ---------------------------------------------------------------------------

_BYPASS = object()        # negative-cache: this key is known un-jittable

_cache: OrderedDict = OrderedDict()
_cache_lock = threading.Lock()


def _cache_get(key):
    with _cache_lock:
        exe = _cache.get(key)
        if exe is not None:
            _cache.move_to_end(key)
        return exe


def _cache_put(key, exe):
    cap = int(_FLAGS.get("FLAGS_eager_op_cache_size", 512) or 0)
    if cap <= 0:
        # size 0 disables caching (dispatch already bypasses before keying;
        # this guards a mid-call flag flip)
        return
    with _cache_lock:
        _cache[key] = exe
        _cache.move_to_end(key)
        while len(_cache) > cap:
            _cache.popitem(last=False)
            _STATS.evictions += 1


def clear_dispatch_cache():
    """Drop every cached executable (test hook / manual invalidation),
    including the shared backward appliers' jit caches — the LRU only
    bounds forward entries; backward traces live in the appliers keyed by
    the vjp Partial treedef and are released here. Fused chain executables
    (ops/fusion.py) obey the same invalidation: registered chains,
    detection state, and the chain backward appliers are cleared too."""
    with _cache_lock:
        _cache.clear()
    for applier in (_vjp_applier, _vjp_applier_donate):
        try:
            applier.clear_cache()
        except Exception:
            pass
    if _fusion_mod is not None:
        _fusion_mod.clear_chain_cache()
    if _step_fusion_mod is not None:
        _step_fusion_mod.clear_step_cache()


def dispatch_cache_info():
    """Entry count + capacity + live keys of the executable cache."""
    with _cache_lock:
        keys = list(_cache)
    return {"entries": len(keys),
            "capacity": int(_FLAGS.get("FLAGS_eager_op_cache_size", 512)),
            "keys": keys}


def _build_fwd(name, fn, check=False):
    def traced(*vals):
        _STATS.retraces += 1      # side effect: runs only while tracing
        _EVENTS.emit("dispatch.retrace", name)
        out = fn(*vals)
        if check:
            # guardian (FLAGS_check_numerics): ONE fused all-finite scalar
            # compiled into the executable — no extra launch, no sync
            outs = out if isinstance(out, tuple) else (out,)
            return out, _guardian.finite_all(outs)
        return out
    return jax.jit(traced)


def _build_fwd_vjp(name, fn, diff_idx, check=False):
    """Jitted (out, vjp) pair. jax.vjp's pullback is a jax.tree_util.Partial
    — a pytree with the residual buffers as leaves — so it crosses the jit
    boundary; the compiled forward then emits fresh residuals every call
    with zero re-tracing, and the shared `_vjp_applier` runs the backward
    as one cached executable keyed on the Partial's (stable) treedef."""
    def traced(*vals):
        _STATS.retraces += 1
        _EVENTS.emit("dispatch.retrace", name)
        if len(diff_idx) == len(vals):
            res = jax.vjp(fn, *vals)
        else:
            def pf(*dv):
                full = list(vals)
                for i, v in zip(diff_idx, dv):
                    full[i] = v
                return fn(*full)
            res = jax.vjp(pf, *(vals[i] for i in diff_idx))
        if check:
            out = res[0]
            outs = out if isinstance(out, tuple) else (out,)
            return res, _guardian.finite_all(outs)
        return res
    return jax.jit(traced)


def _apply_vjp(vjp_fn, g):
    _STATS.retraces += 1
    return vjp_fn(g)


_vjp_applier = jax.jit(_apply_vjp)
# donating variant: hands the residual buffers to XLA on the final backward
# (gated by FLAGS_eager_op_cache_donate — see the flag's docstring for the
# aliasing hazard; donation is a warn-and-skip no-op on CPU)
_vjp_applier_donate = jax.jit(_apply_vjp, donate_argnums=(0,))


def _cached_call(key, name, fn, diff_idx, vals):
    """Run the op through the executable cache. Returns (ok, result);
    ok=False → the caller must take the uncached path (also the landing
    spot for keys negative-cached after a failed trace, so jit-incompatible
    ops fail over exactly once). Keys built under FLAGS_check_numerics
    (key[-1]) carry executables that return an extra all-finite scalar;
    it is stripped and queued for the guardian here so every caller —
    dispatch, chain-split replay, step-split replay — gets the original
    result shape."""
    check = key[-1]
    exe = _cache_get(key)
    if exe is _BYPASS:
        _STATS.bypass(name)
        _EVENTS.emit("dispatch.bypass", name, key, "unjittable")
        return False, None
    if exe is not None:
        _STATS.hit(name)
        _EVENTS.emit("dispatch.hit", name, key)
        try:
            res = exe(*vals)
        except jax.errors.JaxRuntimeError:
            _EVENTS.emit("dispatch.bypass", name, key, "exec_fault")
            # same transient-fault contract as the miss path: fall back to
            # the eager call this once, keep the executable for next time
            return False, None
        if check:
            res, fin = res
            _guardian.enqueue_fwd(name, fin)
        return True, res
    _STATS.miss(name)
    _EVENTS.emit("dispatch.miss", name, key)
    # AOT warm start (ops/aot_cache.py): a restarting worker deserializes
    # yesterday's executable instead of tracing — corrupt/skewed artifacts
    # fall through to the live build below, attributed but never fatal
    exe = _aot.load_op(key, name, fn, diff_idx, check) \
        if _aot.enabled() else None
    fresh = exe is None
    if fresh:
        exe = _build_fwd(name, fn, check) if diff_idx is None \
            else _build_fwd_vjp(name, fn, diff_idx, check)
    try:
        res = exe(*vals)
    except jax.errors.JaxRuntimeError:
        # transient execution fault (OOM, device reset): do NOT negative-
        # cache a jittable key — let the next call try again
        _EVENTS.emit("dispatch.bypass", name, key, "exec_fault")
        return False, None
    except Exception:
        # un-jittable (value-dependent python control flow, dynamic output
        # shape, ...) or a genuine user error: either way the eager path
        # owns this call — and raises the uncached error message
        _cache_put(key, _BYPASS)
        _EVENTS.emit("dispatch.bypass", name, key, "unjittable")
        return False, None
    _cache_put(key, exe)
    if fresh and _aot.enabled():
        # store-if-absent AFTER the executable proved itself on real
        # inputs (an exported unjittable op can't exist — it already ran)
        _aot.store_op(key, name, fn, diff_idx, check, vals)
    if check:
        res, fin = res
        _guardian.enqueue_fwd(name, fin)
    return True, res


def _make_cached_vjp(vjp_partial, diff_idx, n_in, multi):
    """Engine-facing pullback over the cached backward executable. The
    `donate` kwarg (passed by GradNode.collect_input_grads on the final,
    non-retained backward) routes through the donating applier. An
    AOT-restored executable hands back an AotPullback instead of a
    residual Partial — its stored rematerializing backward program plays
    the applier's role (ops/aot_cache.py)."""
    if isinstance(vjp_partial, _aot.AotPullback):
        return vjp_partial.make_wrapped(diff_idx, n_in, multi)

    def wrapped(g, donate=False):
        if multi and not isinstance(g, tuple):
            # the engine passes a bare cotangent when the op has exactly
            # one output; the vjp of a tuple-returning fn wants a tuple
            g = (g,)
        if donate and _FLAGS.get("FLAGS_eager_op_cache_donate"):
            partial = _vjp_applier_donate(vjp_partial, g)
        else:
            partial = _vjp_applier(vjp_partial, g)
        full = [None] * n_in
        for i, pg in zip(diff_idx, partial):
            full[i] = pg
        return tuple(full)
    wrapped._supports_donate = True
    return wrapped


def _slow_vjp(fn, vals, diff_idx, n_in, multi):
    """The original uncached path: eager jax.vjp at every call."""
    if not multi and len(diff_idx) == n_in:
        return jax.vjp(fn, *vals)

    def partial_fn(*diff_vals):
        full = list(vals)
        for i, v in zip(diff_idx, diff_vals):
            full[i] = v
        return fn(*full)

    out, vjp_fn = jax.vjp(partial_fn, *(vals[i] for i in diff_idx))

    def wrapped(g, _vjp=vjp_fn, _idx=diff_idx, _n=n_in):
        if multi and not isinstance(g, tuple):
            g = (g,)
        partial = _vjp(g)
        full = [None] * _n
        for i, pg in zip(_idx, partial):
            full[i] = pg
        return tuple(full)
    return out, wrapped


# ---------------------------------------------------------------------------
# the funnel
# ---------------------------------------------------------------------------

# ops/fusion.py + ops/step_fusion.py, resolved on first dispatch (lazy:
# both import framework.core/autograd, and importing them at module top
# would order the package init around the funnel instead of the other way
# around)
_fusion_mod = None
_step_fusion_mod = None


def _fusion():
    global _fusion_mod
    if _fusion_mod is None:
        from . import fusion
        _fusion_mod = fusion
    return _fusion_mod


def _step_fusion():
    global _step_fusion_mod
    if _step_fusion_mod is None:
        from . import step_fusion
        _step_fusion_mod = step_fusion
    return _step_fusion_mod


def _prologue(name, fn, inputs):
    """Shared call_op/call_op_multi preamble: registry override resolution,
    AMP input casts, and the registry part of the cache key — in one place
    so the cache logic exists exactly once. Raw value extraction is the
    caller's job AFTER the fusion step: reading `_value` here would force
    deferred chain placeholders that the fusion layer can keep symbolic."""
    from .registry import _dispatch_state
    override, active, generation = _dispatch_state(name)
    if override is not None:
        fn = override
    inputs = _amp_transform(name, inputs)
    return fn, inputs, (active, generation)


def _dispatch(name, fn, inputs, num_outputs):
    multi = num_outputs is not None
    fn, inputs, reg_token = _prologue(name, fn, inputs)
    debug = _FLAGS.get("FLAGS_check_nan_inf") or _FLAGS.get("FLAGS_benchmark")
    cache_on = bool(_FLAGS.get("FLAGS_eager_op_cache"))
    bypass_reason = None
    if cache_on and int(_FLAGS.get("FLAGS_eager_op_cache_size", 512) or 0) <= 0:
        # size 0 disables caching entirely — keyable or not, every call
        # takes the uncached path and is counted as a bypass
        cache_on = False
        bypass_reason = "cache_disabled"
        _STATS.bypass(name)
        _EVENTS.emit("dispatch.bypass", name, None, bypass_reason)

    grad_on = _requires_grad(inputs)
    diff_mask = tuple(_differentiable(t) for t in inputs) if grad_on else None

    # guardian (FLAGS_check_numerics): the check compiles INTO the cached
    # executables (keyed), so fusion stays engaged — unlike the strict
    # debug path above
    chk = _guardian.enabled()
    key = _make_key(name, fn, inputs, diff_mask, reg_token, chk) \
        if cache_on else None
    if cache_on and key is None:
        bypass_reason = _classify_bypass(name)
        _STATS.bypass(name)
        _EVENTS.emit("dispatch.bypass", name, None, bypass_reason)

    fus = _fusion()
    sf = _step_fusion()
    if debug:
        # debug modes need materialized outputs op-by-op: resolve any
        # pending replay and keep both fusion layers out of the way
        sf.STEP.interrupt()
        fus.MANAGER.flush(reason="debug_interrupt")
        fus.MANAGER.reset()
    else:
        # whole-step replay gets first crack: while it is matching, the
        # chain layer is quiescent (the fused step IS the chain)
        res = sf.STEP.step(name, fn, inputs, num_outputs, key, diff_mask,
                           bypass_reason=bypass_reason)
        if res is not sf.MISS:
            return res
        res = fus.MANAGER.step(name, fn, inputs, num_outputs, key, diff_mask,
                               bypass_reason=bypass_reason)
        if res is not fus.MISS:
            # chain-deferred ops still feed the step-cycle recorder: the
            # placeholders carry avals, so nothing materializes
            sf.STEP.record(name, fn, inputs, num_outputs, key, diff_mask,
                           tuple(res) if num_outputs is not None else (res,),
                           cached_ok=True)
            return res

    t0 = time.perf_counter_ns()
    vals = _values(inputs)

    if not grad_on:
        ok = False
        if key is not None:
            ok, out_vals = _cached_call(key, name, fn, None, vals)
        if not ok:
            out_vals = fn(*vals)
            if chk:
                _guardian.observe(name, out_vals if multi else (out_vals,))
        if _guardian._INJECTORS:
            out_vals = _guardian.maybe_inject(name, out_vals, multi)
        if multi:
            if debug:
                _debug_checks(name, out_vals)
            outs = [Tensor(v, stop_gradient=True) for v in out_vals]
            _record_dispatch(fus, ok, debug, name, fn, inputs, num_outputs,
                             key, None, outs, t0, bypass_reason)
            return outs
        if debug:
            _debug_checks(name, (out_vals,))
        out = Tensor(out_vals, stop_gradient=True)
        _record_dispatch(fus, ok, debug, name, fn, inputs, num_outputs,
                         key, None, (out,), t0, bypass_reason)
        return out

    diff_idx = tuple(i for i, d in enumerate(diff_mask) if d)
    n_in = len(inputs)

    ok = False
    if key is not None:
        ok, res = _cached_call(key, name, fn, diff_idx, vals)
    if ok:
        out_vals, vjp_partial = res
        wrapped_vjp = _make_cached_vjp(vjp_partial, diff_idx, n_in, multi)
    else:
        out_vals, wrapped_vjp = _slow_vjp(fn, vals, diff_idx, n_in, multi)
        if chk:
            _guardian.observe(name, out_vals if multi else (out_vals,))
    if _guardian._INJECTORS:
        out_vals = _guardian.maybe_inject(name, out_vals, multi)

    if debug:
        _debug_checks(name, out_vals if multi else (out_vals,))
    out_avals = tuple((v.shape, v.dtype) for v in out_vals) if multi \
        else ((out_vals.shape, out_vals.dtype),)
    node = GradNode(name, wrapped_vjp, _make_edges(inputs), out_avals)
    node.fwd_fn = fn
    node.in_vals, node.unpack_hook = _pack_saved(vals, node.edges)
    if multi:
        outs = []
        for j, v in enumerate(out_vals):
            t = Tensor(v, stop_gradient=False)
            t._grad_node = node
            t._out_index = j
            outs.append(t)
        _record_dispatch(fus, ok, debug, name, fn, inputs, num_outputs,
                         key, diff_mask, outs, t0, bypass_reason)
        return outs
    out = Tensor(out_vals, stop_gradient=False)
    out._grad_node = node
    out._out_index = 0
    _record_dispatch(fus, ok, debug, name, fn, inputs, num_outputs,
                     key, diff_mask, (out,), t0, bypass_reason)
    return out


def _record_dispatch(fus, cached_ok, debug, name, fn, inputs, num_outputs,
                     key, diff_mask, outs, t0, bypass_reason=None):
    """Feed the chain detector and the step-cycle recorder after the
    per-op path ran. Only dispatches that went through the executable
    cache are fusion material; an uncached or un-keyable call breaks the
    chain stream and poisons the step cycle (debug calls already reset
    both)."""
    if debug:
        return
    _step_fusion().STEP.record(name, fn, inputs, num_outputs, key,
                               diff_mask, tuple(outs), cached_ok=cached_ok,
                               bypass_reason=bypass_reason)
    if key is None:
        return
    if cached_ok:
        fus.MANAGER.record(name, fn, inputs, num_outputs, key, diff_mask,
                           outs, time.perf_counter_ns() - t0)
    else:
        fus.MANAGER.reset()


def _timed_dispatch(name, fn, inputs, num_outputs):
    t0 = time.perf_counter_ns()
    try:
        return _dispatch(name, fn, inputs, num_outputs)
    finally:
        _STATS.calls += 1
        _STATS.dispatch_time_ns += time.perf_counter_ns() - t0


def call_op(name: str, fn: Callable, inputs: Sequence[Tensor], **_ignored) -> Tensor:
    """Dispatch a single-output op. `fn` maps jax values -> jax value; all
    non-tensor arguments must already be closed over in `fn`."""
    return _timed_dispatch(name, fn, inputs, None)


def call_op_multi(name: str, fn: Callable, inputs: Sequence[Tensor],
                  num_outputs: int) -> list:
    """Dispatch an op whose fn returns a tuple of `num_outputs` jax values."""
    return _timed_dispatch(name, fn, inputs, num_outputs)
