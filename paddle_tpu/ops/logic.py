"""Comparison / logical / bitwise ops.

Reference analog: python/paddle/tensor/logic.py + phi compare/logical kernels.
All comparison outputs are bool tensors and non-differentiable.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import Tensor
from .registry import register_op
from ._helpers import ensure_tensor

__all__ = [
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "logical_and", "logical_or", "logical_not", "logical_xor",
    "bitwise_and", "bitwise_or", "bitwise_not", "bitwise_xor",
    "bitwise_left_shift", "bitwise_right_shift", "is_empty", "is_tensor",
]


def _cmp(name, fn):
    @register_op(name, "logic", differentiable=False)
    def op(x, y=None, name=None, _fn=fn):
        xv = ensure_tensor(x)._value
        if y is None:
            return Tensor(_fn(xv))
        yv = ensure_tensor(y)._value
        return Tensor(_fn(xv, yv))
    op.__name__ = name
    return op


equal = _cmp("equal", jnp.equal)
not_equal = _cmp("not_equal", jnp.not_equal)
greater_than = _cmp("greater_than", jnp.greater)
greater_equal = _cmp("greater_equal", jnp.greater_equal)
less_than = _cmp("less_than", jnp.less)
less_equal = _cmp("less_equal", jnp.less_equal)
logical_and = _cmp("logical_and", jnp.logical_and)
logical_or = _cmp("logical_or", jnp.logical_or)
logical_xor = _cmp("logical_xor", jnp.logical_xor)
logical_not = _cmp("logical_not", jnp.logical_not)
bitwise_and = _cmp("bitwise_and", jnp.bitwise_and)
bitwise_or = _cmp("bitwise_or", jnp.bitwise_or)
bitwise_xor = _cmp("bitwise_xor", jnp.bitwise_xor)
bitwise_not = _cmp("bitwise_not", jnp.bitwise_not)
bitwise_left_shift = _cmp("bitwise_left_shift", jnp.left_shift)
bitwise_right_shift = _cmp("bitwise_right_shift", jnp.right_shift)


@register_op("is_empty", "logic", differentiable=False)
def is_empty(x, name=None):
    return Tensor(jnp.asarray(ensure_tensor(x).size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)
