"""Non-finite step guardian: fused, in-graph numerics safety.

Reference analog: paddle/fluid/framework/details/nan_inf_utils.h
(CheckOpHasNanOrInf) + the dynamic loss-scaling ops
(fluid/operators/amp/check_finite_and_unscale_op.cc,
update_loss_scaling_op.cc) + auto_checkpoint — the machinery that keeps a
multi-day run alive through NaN/Inf blowups and loss-scale collapse.

The strict `FLAGS_check_nan_inf` mode (ops/dispatch._scan_nan_inf) forces
per-op dispatch with a host sync per inexact output and flushes every
chain/step fusion: perfect for LOCALIZING a known blowup, ruinous as an
always-on production check. `FLAGS_check_numerics` — this module — makes
the check a property of the compiled executables instead:

  per-op tier    the cached forward / forward+vjp executable additionally
                 computes ONE all-finite scalar over its inexact outputs
                 (the check flag is part of the cache key, so flipping it
                 re-keys cleanly);
  chain tier     the fused chain executable emits one scalar for the whole
                 chain (ops/fusion.py);
  step tier      the fused whole-step executable computes a global
                 grads-finite predicate, applies the optimizer update as
                 `where(finite, new_state, old_state)` — a poisoned batch
                 becomes a bitwise no-op step — and, when a GradScaler
                 rides the step, folds unscale / found-inf / loss-scale
                 update in as well (ops/step_fusion.py).

The emitted scalars are NOT synced at the op: they land in a small
per-thread queue and are checked lazily at the next `Tensor.backward()` /
`Optimizer.step()` boundary (`flush()`), one batched device→host transfer
per flush. A non-finite FORWARD output raises `FloatingPointError`
(FLAGS_check_numerics_level=0) or warns (>=1); non-finite GRADIENTS never
raise — the step was already skipped in-graph, the flush only attributes
it (`nonfinite_skip` / `scaler_backoff` in the fusion flight recorder,
profiler/events.py) and counts it in `guardian_stats()`.

Fault injection (tools/chaos.py): `inject_fault()` registers hooks the
dispatch funnel consults — poison an op's output with NaNs or raise a
`ChaosFault` mid-step — each firing attributed as `injected_fault` so the
doctor report distinguishes deliberate chaos from organic blowups.
"""
from __future__ import annotations

import threading
import warnings
from collections import deque

import numpy as np
import jax.numpy as jnp

from ..framework.flags import _FLAGS
from ..profiler.events import EVENTS as _EVENTS

__all__ = [
    "enabled", "skip_step_enabled", "finite_all", "finite_all_reduced",
    "flush", "maybe_flush",
    "guardian_stats", "reset_guardian_stats", "update_scaler_state",
    "mark_scaler_active", "inject_fault", "clear_faults", "poll_fault",
    "faults_armed", "ChaosFault", "GUARD_STATS",
]

# queued-but-unflushed scalars are force-flushed past this depth so a
# boundary-less loop (pure inference with the flag on) cannot grow the
# queue or silently drop checks
_MAX_QUEUE = 1024


def enabled() -> bool:
    """The fused guardian is active. FLAGS_check_nan_inf (the strict
    per-op debug mode) takes precedence: it already materializes and
    checks every output synchronously."""
    return bool(_FLAGS.get("FLAGS_check_numerics")) \
        and not bool(_FLAGS.get("FLAGS_check_nan_inf"))


# the skip-step rescue rides the same flag: a non-finite-gradient step is
# turned into a bitwise no-op update (fused and eager paths alike)
skip_step_enabled = enabled


def finite_all(vals):
    """All-finite scalar over the inexact entries of `vals` — traceable
    (used inside the per-op/chain/step executables) and eager-safe. Empty
    or all-integer input yields a constant True."""
    fin = None
    for v in vals:
        if not jnp.issubdtype(v.dtype, jnp.inexact):
            continue
        f = jnp.isfinite(v).all()
        fin = f if fin is None else fin & f
    return jnp.asarray(True) if fin is None else fin


def finite_all_reduced(vals, axis_names):
    """`finite_all` made GLOBALLY consistent inside a shard_map region:
    the scalar is all-reduced (min) over `axis_names`, so every shard of a
    distributed fused step takes the same skip/keep branch — one shard's
    blowup skips the step everywhere, keeping replicated parameters
    bitwise-identical across the mesh (ops/spmd_fusion.py)."""
    import jax
    p = finite_all(vals)
    if not axis_names:
        return p
    return jax.lax.pmin(p.astype(jnp.int32), tuple(axis_names)) > 0


def update_scaler_state(scale, good, bad, found_inf, incr_ratio,
                        decr_ratio, incr_every_n_steps,
                        decr_every_n_nan_or_inf):
    """Dynamic loss-scaling state transition (update_loss_scaling
    semantics) as one pure jnp function — traced into the fused step
    executable AND evaluated eagerly by GradScaler.update(), so the two
    paths cannot drift. All state stays on device; nothing here syncs."""
    found_inf = jnp.asarray(found_inf)
    bad2 = jnp.where(found_inf, bad + 1, 0)
    good2 = jnp.where(found_inf, 0, good + 1)
    shrink = found_inf & (bad2 >= decr_every_n_nan_or_inf)
    grow = (~found_inf) & (good2 >= incr_every_n_steps)
    scale2 = jnp.where(
        shrink, jnp.maximum(scale * decr_ratio, 1.0),
        jnp.where(grow, scale * incr_ratio, scale))
    return (scale2, jnp.where(grow, 0, good2),
            jnp.where(shrink, 0, bad2))


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------

class GuardianStats:
    """Process-wide counters (lock-free best-effort increments, like the
    other profiler counter structs)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.checks_enqueued = 0
        self.flushes = 0
        self.nonfinite_outputs = 0
        self.steps_guarded = 0       # steps that ran with the where() rescue
        self.steps_skipped = 0       # non-finite grads -> bitwise no-op step
        self.scaler_backoffs = 0
        self.faults_injected = 0

    def snapshot(self):
        return {
            "checks_enqueued": self.checks_enqueued,
            "flushes": self.flushes,
            "nonfinite_outputs": self.nonfinite_outputs,
            "steps_guarded": self.steps_guarded,
            "steps_skipped": self.steps_skipped,
            "scaler_backoffs": self.scaler_backoffs,
            "faults_injected": self.faults_injected,
        }


GUARD_STATS = GuardianStats()


def guardian_stats() -> dict:
    """Counters of the non-finite step guardian (FLAGS_check_numerics)."""
    return GUARD_STATS.snapshot()


def reset_guardian_stats():
    GUARD_STATS.reset()


def reset_thread_state():
    """Drop the calling thread's queued checks, in-flight boundary
    batches, and its sticky AMP (scaler-active) marker — test isolation
    hook."""
    _tls.queue.clear()
    _tls.inflight.clear()
    _tls.scaler_active = False


# ---------------------------------------------------------------------------
# the lazy check queue
# ---------------------------------------------------------------------------

# boundary batches allowed in flight before a resolve BLOCKS on the
# device: at depth N, a non-finite finding surfaces at most N boundaries
# after the op ran — the params were already protected in-graph by the
# skip-step rescue, so the delay costs attribution latency, not safety,
# and it keeps the async dispatch pipeline intact (a hard sync per step
# would cost >100% on the smoke loop; see tools/perf_smoke.py)
_PIPELINE_DEPTH = 2


class _TLS(threading.local):
    def __init__(self):
        self.queue = deque()
        # (entries, stacked-scalar) boundary batches awaiting host resolve
        self.inflight = deque()
        # set (sticky) once a live GradScaler touches this thread: fp16
        # AMP routinely overflows forward activations, and the scaler's
        # found-inf/skip-step machinery IS the rescue — so flush() must
        # attribute non-finite forward outputs instead of raising
        self.scaler_active = False


_tls = _TLS()


def mark_scaler_active():
    """Called by an enabled GradScaler (scale/step): switches this thread
    to AMP semantics — non-finite FORWARD outputs no longer raise at
    flush(), they are attributed only (`nonfinite_output`), because the
    loss-scale backoff + skip-step rescue is the designed response."""
    _tls.scaler_active = True


def enqueue_fwd(name, finite_scalar):
    """Queue a forward all-finite scalar (per-op or chain label). Called
    from the dispatch/chain tiers with a device scalar — no sync here.
    A TRACER scalar (the op ran inside an outer jit trace — a serving
    prefill/decode build, jit.TrainStep) is dropped: it could never be
    resolved at a later flush (the trace is gone by then) and the
    enclosing compiled program carries its own checks."""
    import jax
    if isinstance(finite_scalar, jax.core.Tracer):
        return
    GUARD_STATS.checks_enqueued += 1
    q = _tls.queue
    q.append(("fwd", name, finite_scalar))
    if len(q) >= _MAX_QUEUE:
        flush()


def observe(name, out_vals):
    """Eager-path check for dispatches that did not go through a cached
    executable (uncached / un-keyable calls): build the finite scalar with
    plain jnp ops and queue it. Still no host sync."""
    vals = [v for v in out_vals
            if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.inexact)]
    if not vals:
        return
    enqueue_fwd(name, finite_all(vals))


def note_step(label, grads_finite, fwd_finite=None, scale_before=None,
              scale_after=None, step_index=None):
    """Queue a step-level guardian outcome: the skip predicate that drove
    the where() rescue (fused or eager — non-finite update OR non-finite
    new params/slots), the optional forward (loss) finiteness, and the
    loss-scale transition when a GradScaler was folded in. `step_index`
    is the optimizer's step counter at the decision: it rides the queue
    so the flight-recorder events (and the fusion doctor) can say WHICH
    step skipped, not just how many. Step entries never raise at flush —
    the skip already rescued the step; the flush only attributes it."""
    GUARD_STATS.checks_enqueued += 1
    GUARD_STATS.steps_guarded += 1
    q = _tls.queue
    q.append(("step", label, grads_finite, fwd_finite, scale_before,
              scale_after, step_index))
    if len(q) >= _MAX_QUEUE:
        flush()


def _host(v):
    return np.asarray(v)


def maybe_flush():
    """Boundary hook (Tensor.backward, Optimizer.step, GradScaler.step):
    seal the queued scalars into one batch and resolve every in-flight
    batch the device has already finished — WITHOUT blocking on the one
    still computing (up to _PIPELINE_DEPTH boundaries stay in flight, so
    the async dispatch pipeline survives; a finding surfaces at most that
    many boundaries late). A no-op (one truthiness check) when nothing is
    queued — i.e. whenever FLAGS_check_numerics is off."""
    if _tls.queue or _tls.inflight:
        _seal()
        _resolve_ready(block=False)


def flush():
    """Drain the guardian completely: seal the queue and resolve EVERY
    in-flight batch, blocking on the device as needed. Use at loop exit,
    in tests, and in backward-less loops; the per-step boundaries use the
    non-blocking maybe_flush()."""
    _seal()
    _resolve_ready(block=True)


def _seal():
    """Move the queued entries into one in-flight boundary batch together
    with their check scalars. Deliberately NO device work here (stacking
    the scalars would dispatch an op per boundary — measurably worse than
    hosting the handful of ready bool scalars one by one at resolve)."""
    q = _tls.queue
    if not q:
        return
    entries = list(q)
    q.clear()
    GUARD_STATS.flushes += 1
    scalars = []
    for e in entries:
        if e[0] == "fwd":
            scalars.append(e[2])
        elif e[0] == "scaler":
            scalars.append(e[2])   # the no-backoff predicate
        else:
            scalars.append(e[2])
            if e[3] is not None:
                scalars.append(e[3])
    _tls.inflight.append((entries, scalars))


def _resolve_ready(block):
    """Host-resolve in-flight batches: always those the device already
    finished (is_ready), plus — when over _PIPELINE_DEPTH or `block` —
    the ones worth waiting for."""
    inflight = _tls.inflight
    first_error = None
    while inflight:
        entries, scalars = inflight[0]
        if not block and len(inflight) <= _PIPELINE_DEPTH \
                and not _batch_ready(scalars):
            break
        inflight.popleft()
        err = _resolve_batch(entries, scalars)
        if err is not None and first_error is None:
            first_error = err
    if first_error is not None:
        raise first_error


def _batch_ready(scalars):
    for s in scalars:
        ready = getattr(s, "is_ready", None)
        if ready is not None and not ready():
            return False
    return True


def _resolve_batch(entries, scalars):
    """Host the batch's check scalars (tiny, already-computed bools); the
    per-entry walk below only runs when something was non-finite. Returns
    the deferred FloatingPointError (if any) instead of raising so the
    caller can finish resolving the rest of the pipeline first."""
    all_ok = all(bool(_host(s)) for s in scalars)
    if all_ok:
        return None
    first_error = None
    for e in entries:
        if e[0] == "fwd":
            _kind, name, fin = e
            if bool(_host(fin)):
                continue
            GUARD_STATS.nonfinite_outputs += 1
            _EVENTS.emit("step.record", name, reason="nonfinite_output",
                         detail={"kind": "guardian"})
            msg = (f"Operator '{name}' produced a non-finite output "
                   "(FLAGS_check_numerics guardian; re-run with "
                   "FLAGS_check_nan_inf=1 to localize synchronously)")
            if _tls.scaler_active:
                # AMP thread: fp16 overflow in the forward is expected —
                # the GradScaler's found-inf path skips the step and backs
                # the scale off; raising here would make dynamic loss
                # scaling impossible. Attribution only.
                pass
            elif int(_FLAGS.get("FLAGS_check_numerics_level", 0)) == 0:
                if first_error is None:
                    first_error = FloatingPointError(msg)
            else:
                warnings.warn(msg)
        elif e[0] == "scaler":
            _kind, label, no_backoff, s_before, s_after = e
            if bool(_host(no_backoff)):
                continue
            GUARD_STATS.scaler_backoffs += 1
            _EVENTS.emit("step.record", label, reason="scaler_backoff",
                         detail={"kind": "guardian",
                                 "scale": [float(_host(s_before)),
                                           float(_host(s_after))]})
        else:
            _kind, label, grads_fin, fwd_fin, s_before, s_after, step_idx = e
            stamp = {"kind": "guardian"}
            if step_idx is not None:
                stamp["step"] = int(step_idx)
            skipped = not bool(_host(grads_fin))
            if skipped:
                GUARD_STATS.steps_skipped += 1
                _EVENTS.emit("step.record", label, reason="nonfinite_skip",
                             detail=stamp)
            if fwd_fin is not None and not bool(_host(fwd_fin)):
                # the loss itself was non-finite; the skip already rescued
                # the parameters — but the FORWARD contract must match the
                # unfused path: raise at level 0 (attribute-only on AMP
                # threads, where fp16 overflow is the scaler's business)
                GUARD_STATS.nonfinite_outputs += 1
                _EVENTS.emit("step.record", label,
                             reason="nonfinite_output",
                             detail=dict(stamp, rescued=True))
                msg = (f"Fused step '{label}' produced a non-finite loss "
                       "(FLAGS_check_numerics guardian; parameters were "
                       "rescued by the skip-step no-op — re-run with "
                       "FLAGS_check_nan_inf=1 to localize the op)")
                if _tls.scaler_active:
                    pass
                elif int(_FLAGS.get("FLAGS_check_numerics_level", 0)) == 0:
                    if first_error is None:
                        first_error = FloatingPointError(msg)
                else:
                    warnings.warn(msg)
            if s_before is not None and s_after is not None:
                before = float(_host(s_before))
                after = float(_host(s_after))
                if after < before:
                    GUARD_STATS.scaler_backoffs += 1
                    _EVENTS.emit("step.record", label,
                                 reason="scaler_backoff",
                                 detail=dict(stamp,
                                             scale=[before, after]))
    return first_error


def note_scaler(scale_before, scale_after):
    """Queue a loss-scale transition from the EAGER GradScaler.update()
    path so backoffs are attributed without a host sync at the call. The
    no-backoff predicate is computed on device so the resolve fast path
    (all scalars true → no walk) stays correct."""
    GUARD_STATS.checks_enqueued += 1
    q = _tls.queue
    q.append(("scaler", "grad_scaler",
              jnp.asarray(scale_after) >= jnp.asarray(scale_before),
              scale_before, scale_after))
    if len(q) >= _MAX_QUEUE:
        flush()


# ---------------------------------------------------------------------------
# fault injection (the chaos harness's hooks into dispatch)
# ---------------------------------------------------------------------------

class ChaosFault(RuntimeError):
    """Deliberate mid-step failure raised by an injected fault hook."""


class _Injector:
    __slots__ = ("kind", "op", "after", "times", "seen", "fired")

    def __init__(self, kind, op, after, times):
        self.kind = kind
        self.op = op
        self.after = after
        self.times = times
        self.seen = 0
        self.fired = 0

    def remove(self):
        try:
            _INJECTORS.remove(self)
        except ValueError:
            pass


# consulted by ops/dispatch.py only when non-empty (one truthiness check
# on the hot path)
_INJECTORS: list = []


def inject_fault(kind, op=None, after=0, times=1):
    """Register a chaos fault hook (tools/chaos.py / tests).

    kind: "nan_output" — replace the matching dispatch's outputs with NaN;
          "raise"      — raise ChaosFault from inside the dispatch;
          "hang"       — the matching site behaves as if its device work
                         never completed (serving watchdog sites and the
                         fused tiers consult this via `poll_fault`; plain
                         dispatches ignore it — an eager op cannot "hang"
                         without wedging the harness itself). The
                         StepHang is raised WITHOUT burning real time,
                         so recovery-ladder chaos stays fast;
          "stall"      — a hang that DOES burn the real watchdog budget
                         before the StepHang (serving/resilience.py
                         sleeps it out). The wall-clock variant exists
                         for the liveness plane: /healthz
                         (profiler/telemetry_server.py) must flip
                         unhealthy within one watchdog window of a
                         wedged step, which requires the wedge to
                         occupy real time.
    op:   op name to match (None = any dispatched op). Non-dispatch
          sites use reserved names: "serve.decode" / "serve.prefill"
          (engine step futures), "fused_chain" / "fused_step" (the
          fused-tier fires, ops/fusion.py + ops/step_fusion.py).
    after: matching dispatches to let through before firing.
    times: firings before the injector disarms.

    Returns the injector; call .remove() to disarm early.
    """
    if kind not in ("nan_output", "raise", "hang", "stall"):
        raise ValueError(f"unknown fault kind {kind!r}")
    inj = _Injector(kind, op, int(after), int(times))
    _INJECTORS.append(inj)
    return inj


def clear_faults():
    del _INJECTORS[:]


def faults_armed():
    """Any injector registered — the fused-tier fire paths gate their
    poll_fault call on this so chaos costs one truthiness check when
    disarmed (same contract as the dispatch hook)."""
    return bool(_INJECTORS)


def poll_fault(name, kinds):
    """Non-dispatch chaos hook: fire the first armed injector matching
    `name` with a kind in `kinds` and return its kind (or None). Used by
    the serving engine (decode/prefill watchdog + fused-output poison)
    and the fused chain/step fire paths, where outputs are not a flat
    dispatch result `maybe_inject` could transform. The firing is
    attributed `injected_fault` exactly like a dispatch-level one; the
    CALLER implements the fault semantics (simulate a hang, poison its
    outputs, split the replay)."""
    for inj in list(_INJECTORS):
        if inj.fired >= inj.times or inj.kind not in kinds:
            continue
        if inj.op is not None and inj.op != name:
            continue
        inj.seen += 1
        if inj.seen <= inj.after:
            continue
        inj.fired += 1
        GUARD_STATS.faults_injected += 1
        _EVENTS.emit("step.record", name, reason="injected_fault",
                     detail={"kind": "guardian", "fault": inj.kind})
        return inj.kind
    return None


def maybe_inject(name, out_vals, multi):
    """Apply the first matching armed injector to a dispatch's outputs.
    Only called when _INJECTORS is non-empty. Replayed (deferred) chain/
    step ops never reach this hook — chaos poisons their batch inputs
    instead, which exercises the same in-graph detection."""
    for inj in list(_INJECTORS):
        if inj.fired >= inj.times:
            continue
        if inj.kind in ("hang", "stall"):
            # hang/stall faults are only meaningful at monitored-
            # completion sites (poll_fault); a plain dispatch ignores
            # them
            continue
        if inj.op is not None and inj.op != name:
            continue
        inj.seen += 1
        if inj.seen <= inj.after:
            continue
        inj.fired += 1
        GUARD_STATS.faults_injected += 1
        _EVENTS.emit("step.record", name, reason="injected_fault",
                     detail={"kind": "guardian", "fault": inj.kind})
        if inj.kind == "raise":
            raise ChaosFault(
                f"chaos: injected exception at op '{name}' "
                f"(firing {inj.fired}/{inj.times})")
        if multi:
            return tuple(
                jnp.full_like(v, jnp.nan)
                if jnp.issubdtype(v.dtype, jnp.inexact) else v
                for v in out_vals)
        if jnp.issubdtype(out_vals.dtype, jnp.inexact):
            return jnp.full_like(out_vals, jnp.nan)
        return out_vals
    return out_vals
