"""Eager op-chain fusion: one compiled executable per hot op sequence.

The layer above the per-op executable cache (ops/dispatch.py). The per-op
cache (PR 1) removed re-tracing but still pays one XLA launch + one python
dispatch per op; a repeated `matmul→add→gelu`-style sequence pays that N
times per iteration. This module watches the dispatch stream, detects
repeated sequences, and compiles ONE fused executable for the whole chain —
a forward-only variant and a forward+vjp variant whose pullback crosses the
jit boundary as a `tree_util.Partial` and is recorded in the autograd tape
as a single `FusedChainNode` owning every constituent op's outputs.

Keying. A chain key is the tuple of the constituent PR 1 per-op cache keys
plus the dataflow wiring between the ops (`("prev", i, j)` — input comes
from output j of chain op i — vs `("ext",)` — input comes from outside the
chain). Because the per-op keys already carry op name, fn value-token,
input avals, diff mask, AMP state, and the registry generation token, every
invalidation rule of the per-op cache applies to chains for free: a bumped
registry generation or changed AMP state re-keys the ops, the stale chain
stops matching, and it ages out of the chain LRU
(`FLAGS_eager_chain_cache_size`).

Replay is speculative and transactional. Once a sequence crosses the
hotness threshold (`FLAGS_eager_chain_fusion_min_count`), the next time its
first op key arrives the dispatcher stops launching: each matching op is
deferred, its outputs handed back as `_DeferredTensor` placeholders that
know their (shape, dtype) but hold no buffer. When the last op of the chain
arrives, the fused executable fires and every placeholder is filled in one
launch. Any divergence — a key or wiring mismatch, an intermediate escaping
the chain (its value read, its grad node touched, an unrelated consumer), a
mutated `stop_gradient`, an execution fault — SPLITS the chain: the ops
deferred so far replay through the per-op cached path, so numerics are
bitwise-identical to unfused dispatch in every outcome. Chains that keep
failing to replay are deactivated.

Telemetry: profiler/chain_fusion.py (chains detected, fused replays,
fallback splits, escapes, launches saved, estimated wall time saved),
surfaced by `paddle_tpu.profiler.chain_fusion_stats()` and embedded in
bench.py headline records as the `chain_fusion` block.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque

import jax

from ..framework.core import Tensor
from ..framework import core as _core
from ..framework.autograd import FusedChainNode, GradNode, \
    pack_saved_values as _pack_saved
from ..framework.flags import _FLAGS
from ..profiler.chain_fusion import CHAIN_STATS
from ..profiler.events import EVENTS as _EVENTS

__all__ = ["MANAGER", "MISS", "clear_chain_cache", "chain_cache_info"]


def _key_diff_reason(expected, got):
    """Reason code for a replay key mismatch, by diffing the per-op cache
    key components — (name, fn token, avals, diff mask, AMP, registry
    token). Shared with step fusion (ops/step_fusion.py)."""
    try:
        if expected[0] != got[0]:
            return "key_mismatch"        # a different op arrived
        if expected[2] != got[2]:
            return "shape_mismatch"      # same op, different input avals
        if expected[5] != got[5]:
            return "registry_bump"       # kernel override (de)activated
    except (IndexError, TypeError):
        pass
    return "key_mismatch"                # fn token / diff mask / AMP state

MISS = object()          # step() result: "not handled, take the per-op path"
_PENDING = object()      # placeholder _value before its chain fires

_aot_mod = None


def _aot():
    """ops/aot_cache.py, resolved lazily (it back-imports the chain
    builders for its healing fallbacks)."""
    global _aot_mod
    if _aot_mod is None:
        from . import aot_cache
        _aot_mod = aot_cache
    return _aot_mod

# window / max-chain length: long enough to capture fwd sub-expressions of a
# layer, short enough that detection stays O(1)-ish per dispatch
_WINDOW = 8
# detection-table and key-intern caps (cleared wholesale when exceeded:
# hot signatures re-accumulate within a few iterations)
_MAX_COUNTS = 2048
_MAX_INTERN = 4096
# consecutive failed replays before a chain is deactivated
_MAX_FAIL_STREAK = 8
# stitched-chain length cap: two adjacent hot chains are stitched into one
# longer chain (and stitched chains stitch again), so whole transformer
# blocks fuse without growing the _WINDOW detection cost; past this many ops
# the XLA compile time stops amortizing
_STITCH_MAX_OPS = 96

# slot descriptors of the base Tensor: lets _DeferredTensor shadow `_value`
# / `_grad_node` / `_out_index` with escape-detecting properties while still
# storing the materialized state in the ordinary slots
_VALUE_SLOT = Tensor.__dict__["_value"]
_NODE_SLOT = Tensor.__dict__["_grad_node"]
_IDX_SLOT = Tensor.__dict__["_out_index"]


class _DeferredTensor(Tensor):
    """Placeholder for an output of a deferred (not yet launched) chain op.

    Shape/dtype queries answer from the recorded aval without forcing; any
    access that needs the buffer or the grad node forces the owning pending
    chain to resolve (fire if complete, split otherwise) and then behaves
    like a plain Tensor. After materialization the deferred state is
    dropped and the shadowing properties read straight from the slots.
    """

    __slots__ = ("_pending_chain", "_deferred_aval", "_chain_coord")

    def __init__(self, aval, stop_gradient, pending, coord):
        _VALUE_SLOT.__set__(self, _PENDING)
        _NODE_SLOT.__set__(self, None)
        _IDX_SLOT.__set__(self, 0)
        self.stop_gradient = stop_gradient
        self.grad = None
        self.name = _core._auto_name("deferred")
        self.persistable = False
        self._hooks = []
        self._pending_chain = pending
        self._deferred_aval = aval          # (shape, dtype, weak_type)
        self._chain_coord = coord           # (op position, local out index)

    # -- escape detection ---------------------------------------------------
    def _force(self):
        # the pending's OWNER resolves it: the chain manager for chain
        # replays, the step-fusion manager (ops/step_fusion.py) for
        # whole-step replays — placeholders are shared between the layers
        pending = self._pending_chain
        if pending is not None:
            pending.owner.resolve_pending(pending, escape=True)

    @property
    def _value(self):
        v = _VALUE_SLOT.__get__(self)
        if v is _PENDING:
            self._force()
            v = _VALUE_SLOT.__get__(self)
        return v

    @_value.setter
    def _value(self, v):
        # a user value-swap on a still-pending placeholder sticks: the
        # wiring check sees a non-pending tensor (→ split) and
        # materialization never overwrites a user-assigned slot
        _VALUE_SLOT.__set__(self, v)

    @property
    def _grad_node(self):
        if _VALUE_SLOT.__get__(self) is _PENDING:
            self._force()
        return _NODE_SLOT.__get__(self)

    @_grad_node.setter
    def _grad_node(self, node):
        _NODE_SLOT.__set__(self, node)

    @property
    def _out_index(self):
        if _VALUE_SLOT.__get__(self) is _PENDING:
            self._force()
        return _IDX_SLOT.__get__(self)

    @_out_index.setter
    def _out_index(self, idx):
        _IDX_SLOT.__set__(self, idx)

    # -- aval-answerable meta (no forcing) ----------------------------------
    @property
    def _fusion_aval(self):
        """(shape, dtype, weak_type) while pending, else None — read by the
        dispatcher to build cache keys without materializing."""
        if _VALUE_SLOT.__get__(self) is _PENDING \
                and self._pending_chain is not None:
            return self._deferred_aval
        return None

    @property
    def shape(self):
        v = _VALUE_SLOT.__get__(self)
        if v is _PENDING:
            return list(self._deferred_aval[0])
        return list(v.shape)

    @property
    def dtype(self):
        from ..framework import dtype as dtype_mod
        v = _VALUE_SLOT.__get__(self)
        if v is _PENDING:
            return dtype_mod.to_paddle_dtype(self._deferred_aval[1])
        return dtype_mod.to_paddle_dtype(v.dtype)

    @property
    def ndim(self):
        v = _VALUE_SLOT.__get__(self)
        if v is _PENDING:
            return len(self._deferred_aval[0])
        return v.ndim


def _is_pending(t):
    return isinstance(t, _DeferredTensor) \
        and _VALUE_SLOT.__get__(t) is _PENDING and t._pending_chain is not None


class _ChainOp:
    """Template for one op of a registered chain."""

    __slots__ = ("name", "key", "fn", "wiring", "arg_srcs", "diff_mask",
                 "num_outputs", "out_avals", "out_stop_grads")

    def __init__(self, name, key, fn, wiring, diff_mask, num_outputs,
                 out_avals, out_stop_grads):
        self.name = name
        self.key = key                   # the PR 1 per-op cache key
        self.fn = fn
        self.wiring = wiring             # per input: ("ext",) | ("prev",i,j)
        self.diff_mask = diff_mask       # None → op ran without grad
        self.num_outputs = num_outputs   # None → single-output op
        self.out_avals = out_avals       # ((shape, dtype, weak_type), ...)
        self.out_stop_grads = out_stop_grads
        self.arg_srcs = None             # filled by Chain: ("e",slot)|("p",i,j)


class Chain:
    """A registered (hot) op sequence with its fused executables."""

    __slots__ = ("sig", "ops", "label", "n_ext", "ext_of", "diff_ext_idx",
                 "grad_mode", "flat_avals", "flat_node_avals", "owners",
                 "baseline_ns", "pure_fn", "_fwd", "_fwd_vjp", "dead",
                 "fail_streak", "head_kid", "replays", "check",
                 "aot_digest", "aot_stored")

    def __init__(self, sig, ops, baseline_ns):
        self.sig = sig
        self.ops = ops
        self.label = "→".join(op.name for op in ops)
        self.baseline_ns = baseline_ns
        self.dead = False
        self.fail_streak = 0
        self.replays = 0
        # guardian (FLAGS_check_numerics): the per-op keys carry the check
        # flag as their last component, so a chain's check-ness is fixed by
        # its signature — the fused executable emits ONE all-finite scalar
        # for the whole chain and a flag flip simply re-keys the stream
        self.check = bool(ops and ops[0].key[-1])
        # external-slot enumeration: one slot per ("ext",) wiring entry, in
        # (op, input) order; ext_of[i][k] = slot (or None for prev wiring)
        self.ext_of = []
        diff_ext = []
        n = 0
        for op in ops:
            slots = []
            srcs = []
            for k, w in enumerate(op.wiring):
                if w[0] == "ext":
                    slots.append(n)
                    srcs.append(("e", n))
                    if op.diff_mask is not None and op.diff_mask[k]:
                        diff_ext.append(n)
                    n += 1
                else:
                    slots.append(None)
                    srcs.append(("p", w[1], w[2]))
            op.arg_srcs = tuple(srcs)
            self.ext_of.append(tuple(slots))
        self.n_ext = n
        self.diff_ext_idx = tuple(diff_ext)
        self.grad_mode = any(op.diff_mask is not None for op in ops)
        # flattened output catalog: (op position, local index) per flat slot
        owners = []
        flat = []
        for i, op in enumerate(ops):
            for j, av in enumerate(op.out_avals):
                owners.append((i, j))
                flat.append(av)
        self.owners = tuple(owners)
        self.flat_avals = tuple(flat)
        self.flat_node_avals = tuple((av[0], av[1]) for av in flat)
        self.pure_fn = _chain_pure_fn(self)
        self._fwd = None
        self._fwd_vjp = None
        self.aot_digest = 0          # lazily computed (ops/aot_cache.py)
        self.aot_stored = False

    def fwd(self):
        if self._fwd is None:
            if _aot().enabled():
                self._fwd = _aot().load_chain(self, grad=False)
            if self._fwd is None:
                self._fwd = _build_chain_fwd(self)
        return self._fwd

    def fwd_vjp(self):
        if self._fwd_vjp is None:
            if _aot().enabled():
                self._fwd_vjp = _aot().load_chain(self, grad=True)
            if self._fwd_vjp is None:
                self._fwd_vjp = _build_chain_fwd_vjp(self)
        return self._fwd_vjp


def _chain_pure_fn(chain):
    """Pure function (*ext_vals) -> tuple of every op output in chain order.
    `lax.stop_gradient` walls off ops recorded without grad, mirroring the
    tape's missing-edge semantics inside the fused vjp."""
    ops = chain.ops
    grad_mode = chain.grad_mode

    def run(*ext_vals):
        env = {}
        flat = []
        for i, op in enumerate(ops):
            args = [ext_vals[s[1]] if s[0] == "e" else env[(s[1], s[2])]
                    for s in op.arg_srcs]
            res = op.fn(*args)
            outs = res if op.num_outputs is not None else (res,)
            if grad_mode and op.diff_mask is None:
                outs = tuple(jax.lax.stop_gradient(o) for o in outs)
            for j, o in enumerate(outs):
                env[(i, j)] = o
            flat.extend(outs)
        return tuple(flat)
    return run


def _build_chain_fwd(chain):
    run = chain.pure_fn
    check = chain.check

    def traced(*ext_vals):
        CHAIN_STATS.retraces += 1     # side effect: runs only while tracing
        _EVENTS.emit("chain.compile", chain.label,
                     detail={"ops": len(chain.ops)})
        out = run(*ext_vals)
        if check:
            from . import guardian
            return out, guardian.finite_all(out)
        return out
    return jax.jit(traced)


def _build_chain_fwd_vjp(chain):
    """Jitted (all_outputs, vjp) over the chain's differentiable external
    slots; the pullback comes back as a `tree_util.Partial` (residuals as
    leaves) and runs through the chain-specific jitted applier, exactly the
    PR 1 per-op contract scaled to N ops."""
    run = chain.pure_fn
    diff = chain.diff_ext_idx
    check = chain.check

    def traced(*ext_vals):
        CHAIN_STATS.retraces += 1
        _EVENTS.emit("chain.compile", chain.label,
                     detail={"ops": len(chain.ops), "grad": True})
        if len(diff) == len(ext_vals):
            res = jax.vjp(run, *ext_vals)
        else:
            def pf(*dv):
                full = list(ext_vals)
                for i, v in zip(diff, dv):
                    full[i] = v
                return run(*full)
            res = jax.vjp(pf, *(ext_vals[i] for i in diff))
        if check:
            from . import guardian
            return res, guardian.finite_all(res[0])
        return res
    return jax.jit(traced)


def _apply_chain_vjp(vjp_fn, g):
    CHAIN_STATS.retraces += 1
    return vjp_fn(g)


# chain backward runs through its own shared jitted appliers so its traces
# count against chain telemetry, not the per-op dispatch counters
_chain_vjp_applier = jax.jit(_apply_chain_vjp)
_chain_vjp_applier_donate = jax.jit(_apply_chain_vjp, donate_argnums=(0,))


def _make_chain_vjp(vjp_partial, diff_idx, n_ext):
    """Engine-facing pullback for a fused node (cf. dispatch._make_cached_vjp
    — duplicated here only to route through the chain appliers). An
    AOT-restored chain hands back an AotPullback whose stored
    rematerializing backward replaces the applier (ops/aot_cache.py);
    chain cotangents are always tuples, so multi=True."""
    if isinstance(vjp_partial, _aot().AotPullback):
        return vjp_partial.make_wrapped(diff_idx, n_ext, multi=True)

    def wrapped(g, donate=False):
        if not isinstance(g, tuple):
            g = (g,)
        if donate and _FLAGS.get("FLAGS_eager_op_cache_donate"):
            partial = _chain_vjp_applier_donate(vjp_partial, g)
        else:
            partial = _chain_vjp_applier(vjp_partial, g)
        full = [None] * n_ext
        for i, pg in zip(diff_idx, partial):
            full[i] = pg
        return tuple(full)
    wrapped._supports_donate = True
    return wrapped


def replay_ops_per_op(ops, ext_vals, ext_edges, placeholders, upto,
                      skip_materialized=False):
    """Materialize the first `upto` deferred ops through the per-op cached
    dispatch path, filling their placeholders with values and real
    GradNodes — the transactional-fallback core shared by chain splits and
    step-fusion splits/recomputes (ops/step_fusion.py). Results are
    bitwise-identical to what unfused dispatch would have produced.

    `skip_materialized` leaves placeholders that already hold a value AND a
    grad node untouched (post-fire lazy recompute must not overwrite the
    fused root's value or node)."""
    from .dispatch import _cached_call, _slow_vjp, _make_cached_vjp
    for i in range(upto):
        op = ops[i]
        in_vals = []
        in_edges = []
        for k, src in enumerate(op.arg_srcs):
            if src[0] == "e":
                in_vals.append(ext_vals[src[1]])
                in_edges.append(ext_edges[src[1]])
            else:
                prev = placeholders[src[1]][src[2]]
                in_vals.append(_VALUE_SLOT.__get__(prev))
                if op.diff_mask is not None and op.diff_mask[k]:
                    in_edges.append((_NODE_SLOT.__get__(prev),
                                     _IDX_SLOT.__get__(prev)))
                else:
                    in_edges.append(None)
        in_vals = tuple(in_vals)
        multi = op.num_outputs is not None
        if op.diff_mask is None:
            ok, out_vals = _cached_call(op.key, op.name, op.fn,
                                        None, in_vals)
            if not ok:
                out_vals = op.fn(*in_vals)
            outs_flat = out_vals if multi else (out_vals,)
            node = None
        else:
            diff_idx = tuple(k for k, d in enumerate(op.diff_mask) if d)
            ok, res = _cached_call(op.key, op.name, op.fn, diff_idx,
                                   in_vals)
            if ok:
                out_vals, vjp_partial = res
                wrapped = _make_cached_vjp(vjp_partial, diff_idx,
                                           len(in_vals), multi)
            else:
                out_vals, wrapped = _slow_vjp(op.fn, in_vals, diff_idx,
                                              len(in_vals), multi)
            outs_flat = out_vals if multi else (out_vals,)
            node = GradNode(op.name, wrapped, in_edges,
                            tuple((v.shape, v.dtype) for v in outs_flat))
            node.fwd_fn = op.fn
            node.in_vals, node.unpack_hook = _pack_saved(in_vals, in_edges)
        for j, t in enumerate(placeholders[i]):
            if skip_materialized \
                    and _VALUE_SLOT.__get__(t) is not _PENDING \
                    and _NODE_SLOT.__get__(t) is not None:
                t._pending_chain = None
                continue
            if _VALUE_SLOT.__get__(t) is _PENDING:
                _VALUE_SLOT.__set__(t, outs_flat[j])
            if node is not None:
                _NODE_SLOT.__set__(t, node)
                _IDX_SLOT.__set__(t, j)
            t._pending_chain = None


class _PendingChain:
    """Replay in flight: ops deferred so far and their placeholders.

    `lock` serializes the owner thread's mutation (_defer/_fire/_split)
    against a cross-thread escape: a placeholder handed to another thread
    and forced there resolves under the lock, so it either waits out an
    in-flight fire or splits a consistent prefix — never a half-appended
    one."""

    __slots__ = ("chain", "pos", "ext_vals", "ext_edges", "placeholders",
                 "t0", "done", "lock", "owner", "prev_fire", "gap",
                 "gap_outs", "boundary")

    def __init__(self, chain):
        self.chain = chain
        self.pos = 0
        self.ext_vals = []
        self.ext_edges = []
        self.placeholders = []     # per op: tuple of _DeferredTensor
        self.t0 = time.perf_counter_ns()
        self.done = False
        self.lock = threading.RLock()   # reentrant: _fire's fault path splits
        self.owner = MANAGER
        # stitching state: the preceding fired chain replay plus the per-op
        # records dispatched between it and this replay (set when nothing
        # else intervened), and per ext slot the ("A", i, j) / ("G", g, j)
        # coordinate in that fired chain / gap the input came from
        self.prev_fire = None
        self.gap = ()
        self.gap_outs = {}
        self.boundary = []


class _Recorded:
    """One dispatch observed by the rolling window (record mode)."""

    __slots__ = ("key_id", "name", "key", "fn", "wiring_abs", "diff_mask",
                 "num_outputs", "out_avals", "out_stop_grads", "outs",
                 "ins", "abs_pos", "dur_ns")

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


class _TLS(threading.local):
    def __init__(self):
        self.window = deque()
        self.produced = {}     # id(tensor) -> (abs_pos, out_idx)
        self.pending = None
        self.counter = 0       # abs position of the next recorded dispatch
        self.busy = False
        self.serial = 0        # every keyable dispatch this thread has seen
        self.last_fire = None  # (pending, serial of its last deferred op)
        self.stitch_gap = []   # per-op records dispatched since last_fire


class _FusionManager:
    """Detection + registry + replay. Registry state is process-global
    (guarded by a lock, like the per-op LRU); window and pending state are
    per-thread."""

    def __init__(self):
        self._tls = _TLS()
        self._lock = threading.Lock()
        self._counts = {}                  # sig -> occurrence count
        self._chains = OrderedDict()       # sig -> Chain (LRU)
        self._heads = {}                   # first key_id -> [Chain, ...]
        self._intern = {}                  # per-op key -> small int id

    # -- config ------------------------------------------------------------
    @staticmethod
    def enabled():
        return bool(_FLAGS.get("FLAGS_eager_chain_fusion")) \
            and int(_FLAGS.get("FLAGS_eager_chain_cache_size", 128) or 0) > 0

    @staticmethod
    def stitching_enabled():
        return bool(_FLAGS.get("FLAGS_eager_chain_stitching", True))

    # -- key interning -----------------------------------------------------
    def _intern_key(self, key):
        with self._lock:
            kid = self._intern.get(key)
            if kid is None:
                if len(self._intern) >= _MAX_INTERN:
                    self._intern.clear()
                    self._counts.clear()
                kid = self._intern[key] = len(self._intern)
            return kid

    # -- dispatch hooks ----------------------------------------------------
    def step(self, name, fn, inputs, num_outputs, key, diff_mask,
             bypass_reason=None):
        """Called by the dispatcher before it launches anything. Returns the
        op's result (deferred placeholders, materialized on chain
        completion) or MISS → the caller takes the per-op path and reports
        the outcome through record()/reset(). `bypass_reason` attributes a
        key=None split to the dispatch-level cause (rng_rekey, ...)."""
        st = self._tls
        if st.busy:
            return MISS
        if st.pending is not None and st.pending.done:
            st.pending = None       # resolved by another thread's escape
        if not self.enabled():
            self.flush(reason="flag_off")
            if st.window:
                self._reset_window(st)
            return MISS
        if key is None:
            # un-keyable op: chains cannot cross it
            self.flush(reason=bypass_reason or "unkeyable_closure",
                       blocked_op=name)
            self._reset_window(st)
            st.last_fire = None
            st.stitch_gap = []
            return MISS
        kid = self._intern_key(key)
        st.serial += 1

        # resolve placeholders owned by OTHER threads' pending chains (or by
        # a fired step-fusion replay) before taking our own pending lock:
        # _defer reads ext inputs' values, and forcing a foreign placeholder
        # while holding our lock while that thread forces one of ours would
        # be an ABBA deadlock. Pre-forcing is the same escape split, just
        # ordered lock-free. The stitching boundary chain (last_fire) is
        # exempt: its placeholders are already materialized.
        for t in inputs:
            if _is_pending(t) and t._pending_chain is not st.pending:
                t._pending_chain.owner.resolve_pending(t._pending_chain,
                                                       escape=True)

        if st.pending is not None:
            pending = st.pending
            chain = pending.chain
            with pending.lock:
                if pending.done:   # another thread's escape resolved it
                    st.pending = None
                else:
                    op = chain.ops[pending.pos]
                    if kid == self._intern.get(op.key) \
                            and self._replay_wiring_matches(pending, op,
                                                            inputs):
                        return self._defer(st, pending, op, inputs,
                                           num_outputs)
                    if kid != self._intern.get(op.key):
                        reason = _key_diff_reason(op.key, key)
                    else:
                        reason = "wiring_mismatch"
                    self._split(pending, escape=False, reason=reason,
                                blocked_op=name)
            # fall through: this op may start a new chain or be recorded

        chain = self._lookup_start(kid, key)
        if chain is not None:
            pending = st.pending = _PendingChain(chain)
            if st.last_fire is not None and self.stitching_enabled() \
                    and st.last_fire[1] + len(st.stitch_gap) + 1 \
                    == st.serial:
                # this replay follows a fire with only recorded per-op
                # dispatches (the gap) in between: candidate for stitching
                # fire + gap + this chain into one longer chain
                pending.prev_fire = st.last_fire[0]
                pending.gap = tuple(st.stitch_gap)
                pending.gap_outs = {
                    id(t): (g, j)
                    for g, rec in enumerate(pending.gap)
                    for j, t in enumerate(rec.outs)}
            return self._defer(st, pending, chain.ops[0], inputs,
                               num_outputs)
        return MISS

    def record(self, name, fn, inputs, num_outputs, key, diff_mask,
               outs, dur_ns):
        """Feed the detector after a successful per-op cached dispatch."""
        st = self._tls
        if st.busy or not self.enabled() or key is None:
            return
        abs_pos = st.counter
        st.counter += 1
        wiring_abs = tuple(
            ("prev",) + st.produced[id(t)] if id(t) in st.produced
            else ("ext",)
            for t in inputs)
        out_avals = tuple(
            (v._value.shape, v._value.dtype,
             getattr(v._value, "weak_type", False)) for v in outs)
        rec = _Recorded(
            key_id=self._intern_key(key), name=name, key=key, fn=fn,
            wiring_abs=wiring_abs, diff_mask=diff_mask,
            num_outputs=num_outputs, out_avals=out_avals,
            out_stop_grads=tuple(t.stop_gradient for t in outs),
            outs=tuple(outs), ins=tuple(inputs), abs_pos=abs_pos,
            dur_ns=dur_ns)
        if st.last_fire is not None:
            # per-op dispatches between two chain replays are stitch
            # material: they join the two chains as internal ops of the
            # stitched result. A gap longer than the window stops being a
            # plausible single hot sequence — drop the anchor.
            st.stitch_gap.append(rec)
            if len(st.stitch_gap) > _WINDOW:
                st.last_fire = None
                st.stitch_gap = []
        st.window.append(rec)
        for j, t in enumerate(outs):
            st.produced[id(t)] = (abs_pos, j)
        while len(st.window) > _WINDOW:
            old = st.window.popleft()
            for j, t in enumerate(old.outs):
                if st.produced.get(id(t)) == (old.abs_pos, j):
                    del st.produced[id(t)]
        self._detect(st)

    def reset(self):
        """An un-keyable / un-jittable op broke the stream: drop the window
        (chains cannot span it) and the stitch anchor (the broken stream
        does not bump the serial, so adjacency could otherwise lie)."""
        st = self._tls
        self._reset_window(st)
        st.last_fire = None
        st.stitch_gap = []

    def flush(self, reason=None, blocked_op=None):
        """Resolve any pending chain on this thread (split if incomplete)."""
        st = self._tls
        if st.pending is not None:
            pending = st.pending
            with pending.lock:
                if not pending.done:
                    self._split(pending, escape=False, reason=reason,
                                blocked_op=blocked_op)
            st.pending = None

    def _reset_window(self, st):
        st.window.clear()
        st.produced.clear()

    # -- detection ---------------------------------------------------------
    def _detect(self, st):
        win = list(st.window)
        n = len(win)
        if n < 2:
            return
        min_count = int(
            _FLAGS.get("FLAGS_eager_chain_fusion_min_count", 25) or 1)
        to_register = []
        with self._lock:          # one acquisition for all suffix lengths
            for L in range(2, n + 1):
                start = n - L
                start_abs = win[start].abs_pos
                sig = tuple(
                    (rec.key_id, tuple(
                        ("prev", w[1] - start_abs, w[2])
                        if w[0] == "prev" and w[1] >= start_abs else ("ext",)
                        for w in rec.wiring_abs))
                    for rec in win[start:])
                if sig in self._chains:
                    continue
                if len(self._counts) >= _MAX_COUNTS:
                    self._counts.clear()
                c = self._counts.get(sig, 0) + 1
                self._counts[sig] = c
                if c < min_count:
                    continue
                del self._counts[sig]
                to_register.append((sig, win[start:]))
        for sig, recs in to_register:
            self._register(sig, recs)

    # chain labels can repeat across distinct signatures; events carry the
    # label (human attribution) while the sig stays internal

    def _register(self, sig, recs):
        ops = [
            # the per-record rel wiring is sig's second element — no need
            # to re-derive it from wiring_abs
            _ChainOp(rec.name, rec.key, rec.fn, wiring, rec.diff_mask,
                     rec.num_outputs, rec.out_avals, rec.out_stop_grads)
            for rec, (_kid, wiring) in zip(recs, sig)]
        chain = Chain(sig, ops, sum(r.dur_ns for r in recs))
        if self._insert_chain(sig, chain):
            CHAIN_STATS.detected(chain.label)
            _EVENTS.emit("chain.detect", chain.label,
                         detail={"ops": len(chain.ops)})

    def _insert_chain(self, sig, chain):
        """Registry insertion + LRU eviction, shared by window detection and
        stitching. Returns False when `sig` is already registered."""
        with self._lock:
            if sig in self._chains:
                return False
            self._chains[sig] = chain
            self._chains.move_to_end(sig)
            chain.head_kid = self._intern.get(chain.ops[0].key)
            self._heads.setdefault(chain.head_kid, []).append(chain)
            cap = int(_FLAGS.get("FLAGS_eager_chain_cache_size", 128) or 0)
            while len(self._chains) > max(cap, 1):
                # detection registers every hot suffix, so most entries are
                # overlap variants that never replay: evict dead chains
                # first, then the oldest zero-replay one, before touching a
                # chain that has actually fused (the newest entry — the one
                # just registered — is last in iteration order either way)
                victim = None
                for c in self._chains.values():
                    if c.dead:
                        victim = c
                        break
                    if victim is None and c.replays == 0 and c is not chain:
                        victim = c
                if victim is not None:
                    old = self._chains.pop(victim.sig)
                else:
                    _, old = self._chains.popitem(last=False)
                self._drop_head(old)
                CHAIN_STATS.evictions += 1
        return True

    def _register_stitched(self, prev_pending, pending):
        """Window stitching: a fired chain, the per-op dispatches that
        followed it (the gap), and the chain that replayed right after
        become ONE longer chain when their boundary wiring connects.

        `pending.boundary[slot]` maps each ext slot of the second chain to
        its source — ("A", i, j) = previous chain output, ("G", g, j) = gap
        op output, None = genuinely external — and each gap record's inputs
        are resolved the same way at stitch time. The stitched chain keeps
        the first chain's ops 0..nA-1, appends the gap ops rebased by nA and
        the second chain's ops rebased by nA+nG, rewiring every boundary
        edge as an internal `("prev", i, j)`. It is registered like any
        detected chain — `_lookup_start` prefers the longest viable chain
        from a head key, so the next iteration replays the whole stitched
        sequence in one launch (and stitching composes: stitched chains
        stitch again, so whole transformer blocks converge to a single
        launch without growing the rolling-window detection cost). A
        stitched replay counts launches-saved once for the whole sequence;
        the constituent chains stop replaying, so telemetry never
        double-counts."""
        a, b = prev_pending.chain, pending.chain
        gap = pending.gap
        n_a, n_g = len(a.ops), len(gap)
        if a.dead or b.dead \
                or n_a + n_g + len(b.ops) > _STITCH_MAX_OPS:
            return
        # every op of the stitched result must be reachable as one dataflow:
        # require at least one edge from the gap or the second chain back
        # into the fired chain, else the two replays are unrelated streams
        touches_a = any(c is not None and c[0] == "A"
                        for c in pending.boundary)
        ops = []
        for op in a.ops:
            ops.append(_ChainOp(op.name, op.key, op.fn, op.wiring,
                                op.diff_mask, op.num_outputs, op.out_avals,
                                op.out_stop_grads))
        abs_to_g = {rec.abs_pos: g for g, rec in enumerate(gap)}
        for g, rec in enumerate(gap):
            wiring = []
            for k, w in enumerate(rec.wiring_abs):
                if w[0] == "prev" and w[1] in abs_to_g:
                    wiring.append(("prev", n_a + abs_to_g[w[1]], w[2]))
                    continue
                coord = self._fired_coord(prev_pending, rec.ins[k])
                if coord is not None:
                    wiring.append(("prev", coord[0], coord[1]))
                    touches_a = True
                else:
                    wiring.append(("ext",))
            ops.append(_ChainOp(rec.name, rec.key, rec.fn, tuple(wiring),
                                rec.diff_mask, rec.num_outputs,
                                rec.out_avals, rec.out_stop_grads))
        if not touches_a:
            return
        base_b = n_a + n_g
        boundary = pending.boundary
        slot = 0
        for op in b.ops:
            wiring = []
            for w in op.wiring:
                if w[0] == "prev":
                    wiring.append(("prev", w[1] + base_b, w[2]))
                else:
                    coord = boundary[slot]
                    slot += 1
                    if coord is None:
                        wiring.append(("ext",))
                    elif coord[0] == "A":
                        wiring.append(("prev", coord[1], coord[2]))
                    else:
                        wiring.append(("prev", n_a + coord[1], coord[2]))
            ops.append(_ChainOp(op.name, op.key, op.fn, tuple(wiring),
                                op.diff_mask, op.num_outputs, op.out_avals,
                                op.out_stop_grads))
        sig = tuple((self._intern_key(op.key), op.wiring) for op in ops)
        chain = Chain(sig, ops,
                      a.baseline_ns + b.baseline_ns
                      + sum(r.dur_ns for r in gap))
        if self._insert_chain(sig, chain):
            CHAIN_STATS.stitched(chain.label)
            _EVENTS.emit("chain.stitch", chain.label,
                         detail={"ops": len(chain.ops),
                                 "from_ops": [n_a, n_g, len(b.ops)]})

    def _drop_head(self, chain):
        lst = self._heads.get(chain.head_kid)
        if lst is not None:
            try:
                lst.remove(chain)
            except ValueError:
                pass
            if not lst:
                self._heads.pop(chain.head_kid, None)

    def _lookup_start(self, kid, key):
        with self._lock:
            best = None
            for chain in self._heads.get(kid, ()):
                # small-int ids can collide across intern-table resets: the
                # real key tuples must agree before replay starts
                if chain.dead or chain.ops[0].key != key:
                    continue
                # fewest failed replays first, longest chain as tiebreak: a
                # long chain that keeps escaping (e.g. it spans a tape read)
                # stops shadowing a shorter viable one after a single miss
                rank = (chain.fail_streak, -len(chain.ops))
                if best is None or rank < (best.fail_streak, -len(best.ops)):
                    best = chain
            if best is not None:
                self._chains.move_to_end(best.sig)
            return best

    # -- replay ------------------------------------------------------------
    @staticmethod
    def _replay_wiring_matches(pending, op, inputs):
        if len(inputs) != len(op.wiring):
            return False
        for t, w in zip(inputs, op.wiring):
            if _is_pending(t) and t._pending_chain is pending:
                if w[0] != "prev" or t._chain_coord != (w[1], w[2]):
                    return False
            elif w[0] != "ext":
                return False
        return True

    def _defer(self, st, pending, op, inputs, num_outputs):
        # owner thread only, pending.lock held by the caller (step)
        chain = pending.chain
        for k, t in enumerate(inputs):
            if op.wiring[k][0] != "ext":
                continue
            if pending.prev_fire is not None:
                pending.boundary.append(self._boundary_coord(pending, t))
            pending.ext_vals.append(t._value)
            if op.diff_mask is not None and op.diff_mask[k]:
                node = t._grad_node if t._grad_node is not None \
                    else t._ensure_grad_node()
                pending.ext_edges.append((node, t._out_index))
            else:
                pending.ext_edges.append(None)
        outs = tuple(
            _DeferredTensor(av, op.out_stop_grads[j], pending,
                            (pending.pos, j))
            for j, av in enumerate(op.out_avals))
        pending.placeholders.append(outs)
        pending.pos += 1
        if pending.pos == len(chain.ops):
            self._fire(pending)
        if num_outputs is not None:
            return list(outs)
        return outs[0]

    def resolve_pending(self, pending, escape):
        """Escape hatch: a placeholder of `pending` was touched from
        outside the chain. Complete chains just haven't fired yet only
        transiently (never observable), so resolution is always a split.
        May run on a thread other than the chain's owner (a placeholder
        handed across threads): the pending lock serializes against the
        owner's in-flight _defer/_fire, so the split sees a consistent
        prefix — or finds the chain already resolved and does nothing."""
        st = self._tls
        with pending.lock:
            if not pending.done:
                self._split(pending, escape=escape)
        if st.pending is pending:
            st.pending = None

    @staticmethod
    def _fired_coord(prev, t):
        """(op, out) coordinate of `t` in the fired replay `prev`, or None.
        Identity-checked: a materialized placeholder keeps its _chain_coord,
        and membership in the pending's placeholder table proves
        ownership."""
        if not isinstance(t, _DeferredTensor):
            return None
        coord = t._chain_coord
        try:
            if prev.placeholders[coord[0]][coord[1]] is t:
                return coord
        except (IndexError, AttributeError, TypeError):
            pass
        return None

    @classmethod
    def _boundary_coord(cls, pending, t):
        """Where an ext input of a stitch-candidate replay came from:
        ("A", i, j) = output of the fired previous chain, ("G", g, j) =
        output of gap op g, None = genuinely external."""
        coord = cls._fired_coord(pending.prev_fire, t)
        if coord is not None:
            return ("A",) + coord
        gcoord = pending.gap_outs.get(id(t))
        if gcoord is not None:
            return ("G",) + gcoord
        return None

    @staticmethod
    def _materialize(flat_idx, t, value, node):
        if _VALUE_SLOT.__get__(t) is _PENDING:
            _VALUE_SLOT.__set__(t, value)
        if node is not None:
            _NODE_SLOT.__set__(t, node)
            _IDX_SLOT.__set__(t, flat_idx)
        t._pending_chain = None

    def _fire(self, pending):
        """The chain completed: one fused launch fills every placeholder.
        Runs with pending.lock held (via _defer ← step)."""
        st = self._tls
        chain = pending.chain
        st.busy = True
        try:
            ext = tuple(pending.ext_vals)
            if chain.grad_mode:
                res = chain.fwd_vjp()(*ext)
                if chain.check:
                    from . import guardian
                    res, fin = res
                    guardian.enqueue_fwd(chain.label, fin)
                out_vals, vjp_partial = res
                wrapped = _make_chain_vjp(vjp_partial, chain.diff_ext_idx,
                                          chain.n_ext)
                node = FusedChainNode(
                    [op.name for op in chain.ops], wrapped,
                    list(pending.ext_edges), chain.flat_node_avals,
                    chain.owners)
                node.fwd_fn = chain.pure_fn
                node.in_vals, node.unpack_hook = _pack_saved(
                    ext, pending.ext_edges)
            else:
                out_vals = chain.fwd()(*ext)
                if chain.check:
                    from . import guardian
                    out_vals, fin = out_vals
                    guardian.enqueue_fwd(chain.label, fin)
                node = None
        except jax.errors.JaxRuntimeError:
            # transient execution fault: keep the chain, replay per-op
            st.busy = False
            self._split(pending, escape=False, reason="exec_fault")
            if st.pending is pending:
                st.pending = None
            return
        except Exception:
            # the fused trace itself failed (should be impossible for ops
            # the per-op cache accepted, but never let fusion take eager
            # down): kill the chain and fall back
            chain.dead = True
            CHAIN_STATS.deactivated += 1
            st.busy = False
            self._split(pending, escape=False, reason="trace_fail")
            if st.pending is pending:
                st.pending = None
            return
        from . import guardian as _guardian
        if _guardian.faults_armed():
            # fused-tier chaos (tools/chaos.py): "raise" recovers through
            # the transactional per-op split (bitwise-identical values);
            # "nan_output" poisons the FUSED outputs so downstream
            # detection — the step tier's grads-finite predicate, the
            # guardian's forward checks — is exercised against corruption
            # that originates inside a fused region
            fault = _guardian.poll_fault("fused_chain",
                                         ("nan_output", "raise"))
            if fault == "raise":
                st.busy = False
                self._split(pending, escape=False,
                            reason="injected_fault")
                if st.pending is pending:
                    st.pending = None
                return
            if fault == "nan_output":
                import jax.numpy as jnp
                out_vals = tuple(
                    jnp.full_like(v, jnp.nan)
                    if jnp.issubdtype(v.dtype, jnp.inexact) else v
                    for v in out_vals)
                if _guardian.enabled():
                    # the in-graph chain scalar saw the CLEAN outputs;
                    # queue a check on the poisoned ones so the guardian
                    # still attributes the corruption
                    _guardian.observe(chain.label, out_vals)
        try:
            flat = 0
            for i, op in enumerate(chain.ops):
                op_node = node if op.diff_mask is not None else None
                for j, t in enumerate(pending.placeholders[i]):
                    self._materialize(flat, t, out_vals[flat], op_node)
                    flat += 1
            pending.done = True
            chain.fail_streak = 0
            chain.replays += 1
            if not chain.aot_stored and _aot().enabled():
                # persist the proven executable once (store-if-absent:
                # a restored chain never re-exports)
                chain.aot_stored = True
                _aot().store_chain(chain, ext)
            elapsed = time.perf_counter_ns() - pending.t0
            CHAIN_STATS.replay(chain.label, len(chain.ops),
                               chain.baseline_ns - elapsed)
            _EVENTS.emit("chain.fire", chain.label,
                         detail={"ops": len(chain.ops),
                                 "launches_saved": len(chain.ops) - 1})
            if pending.prev_fire is not None \
                    and any(c is not None for c in pending.boundary):
                self._register_stitched(pending.prev_fire, pending)
            # drop the back-links before becoming the new stitch anchor —
            # otherwise fired pendings form an ever-growing linked list
            pending.prev_fire = None
            pending.gap = ()
            pending.gap_outs = {}
            st.last_fire = (pending, st.serial)
            st.stitch_gap = []
            # the detection window predates the fused regime and record()
            # no longer feeds it while ops defer: dropping it releases the
            # last pre-fusion dispatches' output buffers it pins (chains
            # spanning a fired chain could never match anyway — those ops
            # deferred instead of recording)
            self._reset_window(st)
        finally:
            st.busy = False
            if st.pending is pending:
                st.pending = None

    def _split(self, pending, escape, reason=None, blocked_op=None):
        """Replay the deferred prefix through the per-op cached path,
        filling the placeholders with bitwise-identical results. Callers
        hold pending.lock (owner via step/flush, escapees via
        resolve_pending); the guard below makes a second resolution a
        no-op. `reason` is the flight-recorder attribution (a
        REASON_CODES entry); `blocked_op` names the op that broke the
        chain when the split was caused by a specific dispatch."""
        st = self._tls
        chain = pending.chain
        if pending.done:
            return
        owner = st.pending is pending   # escapes run on a foreign thread
        st.busy = True
        try:
            replay_ops_per_op(chain.ops, pending.ext_vals,
                              pending.ext_edges, pending.placeholders,
                              pending.pos)
            pending.done = True
            pending.prev_fire = None
            pending.gap = ()
            pending.gap_outs = {}
            chain.fail_streak += 1
            deactivated = False
            if chain.fail_streak >= _MAX_FAIL_STREAK and not chain.dead:
                chain.dead = True
                deactivated = True
                CHAIN_STATS.deactivated += 1
            CHAIN_STATS.split(chain.label, escape=escape)
            if reason is None:
                reason = "mid_chain_escape" if escape else "key_mismatch"
            detail = {"pos": pending.pos, "ops": len(chain.ops)}
            if blocked_op:
                detail["blocked_op"] = blocked_op
            if deactivated:
                detail["deactivated"] = True
            _EVENTS.emit("chain.split", chain.label, reason=reason,
                         detail=detail)
        finally:
            st.busy = False
            if st.pending is pending:
                st.pending = None
        if owner:
            # only the owner's detection window saw this chain's stream; a
            # foreign escaping thread must not wipe its own unrelated
            # detection progress (nor its stitch anchor)
            self._reset_window(st)
            st.last_fire = None
            st.stitch_gap = []

    # -- maintenance --------------------------------------------------------
    def clear(self):
        self.flush()
        st = self._tls
        self._reset_window(st)
        st.counter = 0
        st.serial = 0
        st.last_fire = None
        st.stitch_gap = []
        with self._lock:
            self._counts.clear()
            self._chains.clear()
            self._heads.clear()
            self._intern.clear()
        for applier in (_chain_vjp_applier, _chain_vjp_applier_donate):
            try:
                applier.clear_cache()
            except Exception:
                pass

    def info(self):
        with self._lock:
            chains = list(self._chains.values())
        return {
            "entries": len(chains),
            "capacity": int(_FLAGS.get("FLAGS_eager_chain_cache_size", 128)),
            "chains": [{"label": c.label, "ops": len(c.ops),
                        "ext_inputs": c.n_ext, "grad": c.grad_mode,
                        "dead": c.dead, "replays": c.replays}
                       for c in chains],
        }


MANAGER = _FusionManager()


def clear_chain_cache():
    """Drop every registered chain, detection count, and pending replay on
    the calling thread (test hook / manual invalidation)."""
    MANAGER.clear()


def chain_cache_info():
    """Entry count + capacity + per-chain summaries of the chain cache."""
    return MANAGER.info()
