"""einsum. Reference analog: python/paddle/tensor/einsum.py (pure-python
planner over matmul); here XLA's native einsum lowering does the planning."""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op
from ._helpers import ensure_tensor, nary

__all__ = ["einsum"]


@register_op("einsum", "math")
def einsum(equation, *operands):
    tensors = [ensure_tensor(o) for o in operands]
    return nary("einsum", lambda *vs: jnp.einsum(equation, *vs), tensors)
