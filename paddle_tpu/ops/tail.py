"""Long-tail tensor API: inplace variants, array ops, and misc utilities.

Reference analog: the `_`-suffixed inplace entries of
python/paddle/tensor/__init__.py (inplace_apis_in_dygraph generated from
ops.yaml `inplace:` rows), fluid LoDTensorArray ops
(create_array/array_read/array_write/array_length), and the scattered
utility ops (frexp, quantile, shard_index, broadcast_shape ...).

TPU-first note on inplace: jax arrays are immutable, so `x.add_(y)` is
value-rebinding — the wrapper Tensor keeps its identity while `_value` (and
the autograd edge) move to the result. That preserves the reference's
aliasing contract at the python level without mutable device buffers.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..framework.core import Tensor
from ._helpers import ensure_tensor, call_op, call_op_multi, const_input
from .registry import register_op

__all__ = [
    "add_", "subtract_", "ceil_", "clip_", "erfinv_", "exp_", "flatten_",
    "floor_", "index_add_", "lerp_", "put_along_axis_", "reciprocal_",
    "remainder_", "round_", "rsqrt_", "scale_", "sqrt_", "tanh_",
    "frexp", "inverse", "quantile", "nanquantile", "numel", "rank", "renorm",
    "broadcast_shape", "reverse", "vsplit", "is_complex",
    "is_floating_point", "is_integer", "set_printoptions", "shard_index",
    "create_array", "array_read", "array_write", "array_length",
    "shape",
]


def _inplace(base_name):
    """Build the `op_` variant: run the out-of-place op, rebind the input
    Tensor's value AND autograd edge to the result."""
    def op_(x, *args, **kwargs):
        from . import _resolve_op
        from ..framework.autograd import is_grad_enabled, AccumulationNode
        if is_grad_enabled() and not x.stop_gradient and \
                (x._grad_node is None
                 or isinstance(x._grad_node, AccumulationNode)):
            # same contract as the reference dygraph check (eager inplace
            # version check): a leaf that requires grad cannot be mutated
            # in place — wrap parameter-style updates in paddle.no_grad()
            raise RuntimeError(
                f"a leaf Tensor that requires grad is used in an in-place "
                f"operation ({base_name}_); wrap the update in "
                "paddle.no_grad()")
        out = _resolve_op(base_name)(x, *args, **kwargs)
        x._value = out._value
        if not out.stop_gradient:
            x._grad_node = out._grad_node
            x._out_index = out._out_index
            x.stop_gradient = False
        return x
    op_.__name__ = base_name + "_"
    op_.__doc__ = f"Inplace variant of `{base_name}` (reference: " \
                  f"ops.yaml inplace row {base_name}_)."
    return op_


add_ = _inplace("add")
subtract_ = _inplace("subtract")
ceil_ = _inplace("ceil")
clip_ = _inplace("clip")
erfinv_ = _inplace("erfinv")
exp_ = _inplace("exp")
flatten_ = _inplace("flatten")
floor_ = _inplace("floor")
index_add_ = _inplace("index_add")
lerp_ = _inplace("lerp")
put_along_axis_ = _inplace("put_along_axis")
reciprocal_ = _inplace("reciprocal")
remainder_ = _inplace("remainder")
round_ = _inplace("round")
rsqrt_ = _inplace("rsqrt")
scale_ = _inplace("scale")
sqrt_ = _inplace("sqrt")
tanh_ = _inplace("tanh")


@register_op("frexp", "math", ref="python/paddle/tensor/math.py frexp")
def frexp(x, name=None):
    x = ensure_tensor(x)
    return call_op_multi("frexp", lambda v: jnp.frexp(v), (x,),
                         num_outputs=2)


@register_op("inverse", "linalg", ref="phi/kernels/inverse_kernel.h")
def inverse(x, name=None):
    return call_op("inverse", jnp.linalg.inv, (ensure_tensor(x),))


@register_op("quantile", "stat", ref="python/paddle/tensor/stat.py quantile")
def quantile(x, q, axis=None, keepdim=False, interpolation="linear",
             name=None):
    x = ensure_tensor(x)
    qt = const_input(q)

    def fn(v, qv):
        return jnp.quantile(v, qv, axis=axis, keepdims=keepdim,
                            method=interpolation)
    return call_op("quantile", fn, (x, qt))


@register_op("nanquantile", "stat",
             ref="python/paddle/tensor/stat.py nanquantile")
def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear",
                name=None):
    x = ensure_tensor(x)
    qt = const_input(q)

    def fn(v, qv):
        return jnp.nanquantile(v, qv, axis=axis, keepdims=keepdim,
                               method=interpolation)
    return call_op("nanquantile", fn, (x, qt))


@register_op("numel", "attribute", differentiable=False,
             ref="phi/kernels/numel_kernel.h")
def numel(x, name=None):
    return Tensor(jnp.asarray(ensure_tensor(x)._value.size, jnp.int64),
                  stop_gradient=True)


@register_op("rank", "attribute", differentiable=False,
             ref="python/paddle/tensor/attribute.py rank")
def rank(x, name=None):
    return Tensor(jnp.asarray(ensure_tensor(x)._value.ndim, jnp.int32),
                  stop_gradient=True)


def broadcast_shape(x_shape, y_shape):
    """Static shape arithmetic (reference: broadcast_shape API)."""
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


@register_op("reverse", "manipulation", ref="phi/kernels/flip_kernel.h")
def reverse(x, axis, name=None):
    from .manipulation import flip
    return flip(x, axis)


def vsplit(x, num_or_indices, name=None):
    """Split along dim 0 (reference: python/paddle/tensor/manipulation.py
    vsplit)."""
    x = ensure_tensor(x)
    if x._value.ndim < 2:
        raise ValueError(
            f"vsplit expects at least a 2-D tensor, got {x._value.ndim}-D")
    from .manipulation import split
    if isinstance(num_or_indices, int):
        return split(x, num_or_indices, axis=0)
    sizes, prev = [], 0
    for ix in list(num_or_indices) + [x.shape[0]]:
        sizes.append(ix - prev)
        prev = ix
    return split(x, sizes, axis=0)


def is_complex(x):
    return bool(jnp.issubdtype(ensure_tensor(x)._value.dtype,
                               jnp.complexfloating))


def is_floating_point(x):
    return bool(jnp.issubdtype(ensure_tensor(x)._value.dtype, jnp.floating))


def is_integer(x):
    return bool(jnp.issubdtype(ensure_tensor(x)._value.dtype, jnp.integer))


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Printing config (reference: python/paddle/tensor/to_string.py);
    tensors print through numpy, so numpy's options are the knobs."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


@register_op("shard_index", "manipulation", differentiable=False,
             ref="fluid/operators/shard_index_op.cc")
def shard_index(input, index_num, nshards, shard_id, ignore_value=-1,
                name=None):
    """Relabel ids for one shard of a row-parallel table: ids owned by
    `shard_id` map to their local row, others to `ignore_value`."""
    if not 0 <= shard_id < nshards:
        raise ValueError(
            f"shard_id {shard_id} out of range for nshards {nshards}")
    x = ensure_tensor(input)
    per = (index_num + nshards - 1) // nshards

    def fn(ids):
        owner = ids // per
        local = ids % per
        return jnp.where(owner == shard_id, local,
                         jnp.asarray(ignore_value, ids.dtype))
    return call_op("shard_index", fn, (x,))


# -- LoDTensorArray shims (reference: fluid control_flow array ops) ----------

def create_array(dtype="float32", initialized_list=None):
    return list(initialized_list) if initialized_list else []


def array_write(x, i, array=None):
    i = int(i.item()) if isinstance(i, Tensor) else int(i)
    if array is None:
        array = []
    while len(array) <= i:
        array.append(None)
    array[i] = ensure_tensor(x)
    return array


def array_read(array, i):
    i = int(i.item()) if isinstance(i, Tensor) else int(i)
    return array[i]


def array_length(array):
    return Tensor(jnp.asarray(len(array), jnp.int64), stop_gradient=True)


@register_op("shape", "attribute", differentiable=False,
             ref="phi/kernels/shape_kernel.h")
def shape(input, name=None):
    """The runtime shape as an int32 tensor (reference: paddle.shape op)."""
    return Tensor(jnp.asarray(ensure_tensor(input)._value.shape, jnp.int32),
                  stop_gradient=True)


@register_op("renorm", "math", ref="python/paddle/tensor/math.py:1997 renorm")
def renorm(x, p, axis, max_norm, name=None):
    """Renormalize slices along `axis` so each slice's p-norm is at most
    `max_norm` (slices already within the bound are unchanged)."""
    x = ensure_tensor(x)
    ndim = x._value.ndim
    ax = axis + ndim if axis < 0 else axis
    other = tuple(d for d in range(ndim) if d != ax)

    def fn(v):
        fv = v.astype(jnp.float32) if v.dtype == jnp.bfloat16 else v
        norms = jnp.sum(jnp.abs(fv) ** p, axis=other, keepdims=True) \
            ** (1.0 / p)
        scale = jnp.where(norms > max_norm,
                          max_norm / jnp.maximum(norms, 1e-12), 1.0)
        return (fv * scale).astype(v.dtype)

    return call_op("renorm", fn, (x,))
