"""Op corpus assembly + Tensor method patching.

Reference analog: python/paddle/tensor/__init__.py (tensor_method_func list)
and pybind/eager_math_op_patch.cc (operator overloads on the eager Tensor).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import Tensor
from . import creation, math, logic, manipulation, linalg, search, random_ops
from . import tail
from . import einsum_op
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .random_ops import *  # noqa: F401,F403
from .tail import *  # noqa: F401,F403
from .einsum_op import einsum  # noqa: F401
from .registry import (  # noqa: F401
    all_ops, get_op, register_op, override_kernel, use_kernel, infer_meta,
    describe,
)
from ._helpers import ensure_tensor, jnp_dtype


# ---------------------------------------------------------------------------
# Tensor operator overloads (eager_math_op_patch.cc analog)
# ---------------------------------------------------------------------------

def _patch_operators():
    T = Tensor
    T.__add__ = lambda self, other: math.add(self, other)
    T.__radd__ = lambda self, other: math.add(other, self)
    T.__sub__ = lambda self, other: math.subtract(self, other)
    T.__rsub__ = lambda self, other: math.subtract(other, self)
    T.__mul__ = lambda self, other: math.multiply(self, other)
    T.__rmul__ = lambda self, other: math.multiply(other, self)
    T.__truediv__ = lambda self, other: math.divide(self, other)
    T.__rtruediv__ = lambda self, other: math.divide(other, self)
    T.__floordiv__ = lambda self, other: math.floor_divide(self, other)
    T.__rfloordiv__ = lambda self, other: math.floor_divide(other, self)
    T.__mod__ = lambda self, other: math.mod(self, other)
    T.__rmod__ = lambda self, other: math.mod(other, self)
    T.__pow__ = lambda self, other: math.pow(self, other)
    T.__rpow__ = lambda self, other: math.pow(other, self)
    T.__matmul__ = lambda self, other: math.matmul(self, other)
    T.__rmatmul__ = lambda self, other: math.matmul(other, self)
    T.__neg__ = lambda self: math.neg(self)
    T.__abs__ = lambda self: math.abs(self)
    T.__invert__ = lambda self: logic.logical_not(self) \
        if jnp_dtype(self) == jnp.bool_.dtype else logic.bitwise_not(self)
    T.__and__ = lambda self, other: logic.logical_and(self, other) \
        if jnp_dtype(self) == jnp.bool_.dtype else logic.bitwise_and(self, other)
    T.__or__ = lambda self, other: logic.logical_or(self, other) \
        if jnp_dtype(self) == jnp.bool_.dtype else logic.bitwise_or(self, other)
    T.__xor__ = lambda self, other: logic.logical_xor(self, other) \
        if jnp_dtype(self) == jnp.bool_.dtype else logic.bitwise_xor(self, other)
    T.__eq__ = lambda self, other: logic.equal(self, other)
    T.__ne__ = lambda self, other: logic.not_equal(self, other)
    T.__lt__ = lambda self, other: logic.less_than(self, other)
    T.__le__ = lambda self, other: logic.less_equal(self, other)
    T.__gt__ = lambda self, other: logic.greater_than(self, other)
    T.__ge__ = lambda self, other: logic.greater_equal(self, other)

    def _getitem(self, item):
        from .dispatch import call_op

        def norm_item(it):
            if isinstance(it, Tensor):
                v = it._value
                return v
            if isinstance(it, (list,)):
                return jnp.asarray(it)
            if isinstance(it, tuple):
                return tuple(norm_item(i) for i in it)
            return it
        nit = norm_item(item)
        return call_op("getitem", lambda v: v[nit], (self,))

    def _setitem(self, item, value):
        def norm_item(it):
            if isinstance(it, Tensor):
                return it._value
            if isinstance(it, list):
                return jnp.asarray(it)
            if isinstance(it, tuple):
                return tuple(norm_item(i) for i in it)
            return it
        nit = norm_item(item)
        val = value._value if isinstance(value, Tensor) else value
        self._value = self._value.at[nit].set(val)

    T.__getitem__ = _getitem
    T.__setitem__ = _setitem

    # -- method attachment (tensor_method_func analog) ----------------------
    method_sources = [creation, math, logic, manipulation, linalg, search,
                      random_ops, tail]
    skip = {"to_tensor", "meshgrid", "zeros", "ones", "full", "arange",
            "linspace", "logspace", "eye", "empty", "rand", "randn", "randint",
            "uniform", "normal", "randperm", "tril_indices", "triu_indices",
            "complex", "vander", "scatter_nd", "einsum",
            "shape", "broadcast_shape", "set_printoptions", "create_array",
            "array_read", "array_write", "array_length"}
    for mod in method_sources:
        for fname in getattr(mod, "__all__", []):
            if fname in skip or hasattr(T, fname):
                continue
            fn = getattr(mod, fname)
            if callable(fn):
                setattr(T, fname, fn)
    # explicit useful aliases
    T.matmul = math.matmul
    T.mm = math.mm
    T.dot = math.dot
    T.norm = linalg.norm


_patch_operators()


def add_n(inputs, name=None):
    """Sum a list of tensors. Reference: paddle.add_n (sum_op)."""
    if isinstance(inputs, Tensor):
        return inputs
    from ._helpers import nary
    import functools
    import operator
    return nary("add_n", lambda *vs: functools.reduce(operator.add, vs),
                list(inputs))


def _resolve_op(name):
    """Look up an op entry point by public name (used by the generated
    inplace variants in tail.py)."""
    import sys
    mod = sys.modules[__name__]
    fn = getattr(mod, name, None)
    if fn is None:
        raise AttributeError(f"no op named {name!r}")
    return fn
