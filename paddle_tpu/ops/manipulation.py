"""Shape/layout manipulation ops.

Reference analog: python/paddle/tensor/manipulation.py backed by phi
reshape/transpose/concat/gather/scatter kernels. TPU-first: gather/scatter use
jax `.at[]` functional updates (XLA scatter), keeping everything static-shaped
where possible; dynamic-shape ops (nonzero/unique/masked_select) are host-sync
points, documented as such.
"""
from __future__ import annotations

import numbers

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework.dtype import to_jax_dtype
from .registry import register_op
from ._helpers import ensure_tensor, unary, binary, nary, call_op, \
    call_op_multi, const_input

__all__ = [
    "reshape", "reshape_", "transpose", "concat", "stack", "split", "chunk",
    "squeeze", "squeeze_", "unsqueeze", "unsqueeze_", "flatten", "expand",
    "expand_as", "broadcast_to", "broadcast_tensors", "tile", "flip", "roll",
    "gather", "gather_nd", "scatter", "scatter_", "scatter_nd",
    "scatter_nd_add", "index_select", "index_sample", "index_add", "index_put",
    "slice", "strided_slice", "take_along_axis", "put_along_axis",
    "masked_select", "masked_fill", "where", "unbind", "unique",
    "unique_consecutive", "pad", "repeat_interleave", "rot90", "moveaxis",
    "swapaxes", "as_complex", "as_real", "cast", "tensordot", "unstack",
    "take", "tolist", "crop", "fill_diagonal_", "view", "view_as", "unfold",
    "atleast_1d", "atleast_2d", "atleast_3d", "select_scatter", "diagonal_scatter",
    "diag_embed",
]


@register_op("reshape", "manipulation", ref="phi/kernels/reshape_kernel.h")
def reshape(x, shape, name=None):
    x = ensure_tensor(x)
    if isinstance(shape, Tensor):
        shape = shape.numpy().tolist()
    shape = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape]
    tgt = tuple(shape)
    xshape = tuple(x.shape)
    if tgt and -1 not in tgt and xshape and all(tgt[1:]) and \
            (tgt[0] == xshape[0]
             or (len(xshape) >= 2 and tgt[0] == xshape[0] * xshape[1])):
        # leading-dim passthrough (or a merge of the two leading dims):
        # infer it with -1 so the recorded op replays on ANY leading-dim
        # size — the SPMD step promoter (ops/spmd_fusion.py) replays
        # recorded ops on per-device batch SHARDS, and a baked global
        # batch size would shape-error inside shard_map. The call-time
        # equality check keeps the inferred dim identical to the explicit
        # one for THIS call, so numerics and error behavior are unchanged.
        tgt = (-1,) + tgt[1:]
    return unary("reshape", lambda v: jnp.reshape(v, tgt), x)


def reshape_(x, shape, name=None):
    out = reshape(x, shape)
    x._value = out._value
    x._grad_node = out._grad_node
    x._out_index = out._out_index
    return x


view = reshape


def view_as(x, other, name=None):
    return reshape(x, ensure_tensor(other).shape)


@register_op("transpose", "manipulation")
def transpose(x, perm, name=None):
    x = ensure_tensor(x)
    perm = [int(p) for p in perm]
    return unary("transpose", lambda v: jnp.transpose(v, perm), x)


@register_op("cast", "manipulation")
def cast(x, dtype):
    return ensure_tensor(x).astype(dtype)


@register_op("concat", "manipulation")
def concat(x, axis=0, name=None):
    tensors = [ensure_tensor(t) for t in x]
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return nary("concat", lambda *vs: jnp.concatenate(vs, axis=axis), tensors)


@register_op("stack", "manipulation")
def stack(x, axis=0, name=None):
    tensors = [ensure_tensor(t) for t in x]
    return nary("stack", lambda *vs: jnp.stack(vs, axis=axis), tensors)


@register_op("split", "manipulation")
def split(x, num_or_sections, axis=0, name=None):
    x = ensure_tensor(x)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        n = num_or_sections
        if dim % n != 0:
            raise ValueError(
                f"paddle.split: axis {axis} size {dim} is not divisible by "
                f"num_or_sections={n}")
        sizes = [dim // n] * n
    else:
        sizes = [int(s) for s in num_or_sections]
        if any(s == -1 for s in sizes):
            rest = dim - builtins_sum(s for s in sizes if s != -1)
            sizes = [rest if s == -1 else s for s in sizes]
    offsets = np.cumsum([0] + sizes[:-1]).tolist()

    def fn(v):
        return tuple(jax.lax.slice_in_dim(v, o, o + s, axis=axis)
                     for o, s in zip(offsets, sizes))
    return call_op_multi("split", fn, (x,), num_outputs=len(sizes))


def builtins_sum(it):
    import builtins
    return builtins.sum(it)


@register_op("chunk", "manipulation")
def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


@register_op("squeeze", "manipulation")
def squeeze(x, axis=None, name=None):
    x = ensure_tensor(x)
    if axis is None:
        ax = None
    else:
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        ax = tuple(a % max(x.ndim, 1) for a in axes if x.shape[a] == 1) or None
        if ax is None:
            return x.clone()
    return unary("squeeze", lambda v: jnp.squeeze(v, axis=ax), x)


def squeeze_(x, axis=None, name=None):
    out = squeeze(x, axis)
    x._value, x._grad_node, x._out_index = out._value, out._grad_node, out._out_index
    return x


@register_op("unsqueeze", "manipulation")
def unsqueeze(x, axis, name=None):
    x = ensure_tensor(x)
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = [int(a.item()) if isinstance(a, Tensor) else int(a) for a in axes]
    def fn(v):
        for a in sorted(axes):
            v = jnp.expand_dims(v, a)
        return v
    return unary("unsqueeze", fn, x)


def unsqueeze_(x, axis, name=None):
    out = unsqueeze(x, axis)
    x._value, x._grad_node, x._out_index = out._value, out._grad_node, out._out_index
    return x


@register_op("flatten", "manipulation")
def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = ensure_tensor(x)
    nd = x.ndim
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0

    def fn(v):
        # target computed from the RUNTIME shape (concrete inside any
        # trace), so the recorded op is shape-polymorphic — an SPMD step
        # replay (ops/spmd_fusion.py) feeds it per-device batch shards.
        # -1 infers the flattened block; a zero-size block (where -1 is
        # ambiguous) falls back to the concrete product.
        block = v.shape[s:e + 1]
        mid = -1 if all(block) else int(np.prod(block))
        return jnp.reshape(v, v.shape[:s] + (mid,) + v.shape[e + 1:])
    return unary("flatten", fn, x)


@register_op("expand", "manipulation")
def expand(x, shape, name=None):
    x = ensure_tensor(x)
    if isinstance(shape, Tensor):
        shape = shape.numpy().tolist()
    shape = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape]
    # -1 means keep the original dim
    xshape = ([1] * (len(shape) - x.ndim)) + x.shape
    tgt = [xs if s == -1 else s for s, xs in zip(shape, xshape)]
    return unary("expand", lambda v: jnp.broadcast_to(v, tgt), x)


@register_op("expand_as", "manipulation")
def expand_as(x, y, name=None):
    return expand(x, ensure_tensor(y).shape)


@register_op("broadcast_to", "manipulation")
def broadcast_to(x, shape, name=None):
    return expand(x, shape)


@register_op("broadcast_tensors", "manipulation")
def broadcast_tensors(input, name=None):
    tensors = [ensure_tensor(t) for t in input]
    shape = jnp.broadcast_shapes(*[tuple(t.shape) for t in tensors])
    return [expand(t, list(shape)) for t in tensors]


@register_op("tile", "manipulation")
def tile(x, repeat_times, name=None):
    x = ensure_tensor(x)
    if isinstance(repeat_times, Tensor):
        repeat_times = repeat_times.numpy().tolist()
    reps = [int(r.item()) if isinstance(r, Tensor) else int(r)
            for r in repeat_times]
    return unary("tile", lambda v: jnp.tile(v, reps), x)


@register_op("flip", "manipulation")
def flip(x, axis, name=None):
    x = ensure_tensor(x)
    ax = axis if isinstance(axis, (list, tuple)) else [axis]
    return unary("flip", lambda v: jnp.flip(v, axis=ax), x)


@register_op("roll", "manipulation")
def roll(x, shifts, axis=None, name=None):
    x = ensure_tensor(x)
    return unary("roll", lambda v: jnp.roll(v, shifts, axis=axis), x)


@register_op("gather", "manipulation", ref="phi/kernels/gather_kernel.h")
def gather(x, index, axis=0, name=None):
    # index rides as a dispatch input (const_input): the op keys on the
    # index aval instead of baking the array into its closure, which
    # bypassed the executable cache on every call and poisoned fusion
    # cycles (`unkeyable_closure` — the PR 3/4 bug class, linted by R1)
    x = ensure_tensor(x)
    idx = const_input(index)
    if isinstance(axis, Tensor):
        axis = int(axis.item())

    def fn(v, iv):
        if iv.ndim > 1:
            iv = iv.reshape(-1)
        return jnp.take(v, iv, axis=axis)
    return call_op("gather", fn, (x, idx))


@register_op("gather_nd", "manipulation")
def gather_nd(x, index, name=None):
    x = ensure_tensor(x)
    idx = const_input(index)

    def fn(v, iv):
        ind = tuple(jnp.moveaxis(iv, -1, 0))
        return v[ind]
    return call_op("gather_nd", fn, (x, idx))


@register_op("scatter", "manipulation")
def scatter(x, index, updates, overwrite=True, name=None):
    x = ensure_tensor(x)
    updates = ensure_tensor(updates)
    idx = const_input(index)

    def fn(v, u, iv):
        iv = iv.reshape(-1)
        if overwrite:
            return v.at[iv].set(u)
        return v.at[iv].set(0).at[iv].add(u)
    return call_op("scatter", fn, (x, updates, idx))


def scatter_(x, index, updates, overwrite=True, name=None):
    out = scatter(x, index, updates, overwrite)
    x._value, x._grad_node, x._out_index = out._value, out._grad_node, out._out_index
    return x


@register_op("scatter_nd", "manipulation")
def scatter_nd(index, updates, shape, name=None):
    updates = ensure_tensor(updates)
    idx = const_input(index)
    shape = [int(s) for s in shape]

    def fn(u, iv):
        z = jnp.zeros(shape, u.dtype)
        ind = tuple(jnp.moveaxis(iv, -1, 0))
        return z.at[ind].add(u)
    return call_op("scatter_nd", fn, (updates, idx))


@register_op("scatter_nd_add", "manipulation")
def scatter_nd_add(x, index, updates, name=None):
    x = ensure_tensor(x)
    updates = ensure_tensor(updates)
    idx = const_input(index)

    def fn(v, u, iv):
        ind = tuple(jnp.moveaxis(iv, -1, 0))
        return v.at[ind].add(u)
    return call_op("scatter_nd_add", fn, (x, updates, idx))


@register_op("index_select", "manipulation")
def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis)


@register_op("index_sample", "manipulation")
def index_sample(x, index, name=None):
    x = ensure_tensor(x)
    idx = const_input(index)

    def fn(v, iv):
        return jnp.take_along_axis(v, iv, axis=1)
    return call_op("index_sample", fn, (x, idx))


@register_op("index_add", "manipulation")
def index_add(x, index, axis, value, name=None):
    x = ensure_tensor(x)
    value = ensure_tensor(value)
    idx = const_input(index)

    def fn(v, u, iv):
        vm = jnp.moveaxis(v, axis, 0)
        um = jnp.moveaxis(u, axis, 0)
        return jnp.moveaxis(vm.at[iv].add(um), 0, axis)
    return call_op("index_add", fn, (x, value, idx))


@register_op("index_put", "manipulation")
def index_put(x, indices, value, accumulate=False, name=None):
    x = ensure_tensor(x)
    value = ensure_tensor(value)
    ind = tuple(const_input(i) for i in indices)

    def fn(v, u, *iv):
        return v.at[iv].add(u) if accumulate else v.at[iv].set(u)
    return call_op("index_put", fn, (x, value) + ind)


@register_op("slice", "manipulation")
def slice(input, axes, starts, ends, name=None):
    x = ensure_tensor(input)
    sl = [jnp.s_[:]] * x.ndim
    import builtins
    for ax, s, e in zip(axes, starts, ends):
        s = int(s.item()) if isinstance(s, Tensor) else int(s)
        e = int(e.item()) if isinstance(e, Tensor) else int(e)
        dim = x.shape[ax]
        s = builtins.max(s + dim, 0) if s < 0 else builtins.min(s, dim)
        e = builtins.max(e + dim, 0) if e < 0 else builtins.min(e, dim)
        sl[ax] = jnp.s_[s:e]
    sl = tuple(sl)
    return unary("slice", lambda v: v[sl], x)


@register_op("strided_slice", "manipulation")
def strided_slice(x, axes, starts, ends, strides, name=None):
    x = ensure_tensor(x)
    sl = [jnp.s_[:]] * x.ndim
    for ax, s, e, st in zip(axes, starts, ends, strides):
        sl[ax] = jnp.s_[s:e:st]
    sl = tuple(sl)
    return unary("strided_slice", lambda v: v[sl], x)


@register_op("take_along_axis", "manipulation")
def take_along_axis(arr, indices, axis, name=None):
    arr = ensure_tensor(arr)
    idx = const_input(indices)
    return call_op("take_along_axis",
                   lambda v, iv: jnp.take_along_axis(v, iv, axis=axis),
                   (arr, idx))


@register_op("put_along_axis", "manipulation")
def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    arr = ensure_tensor(arr)
    values = ensure_tensor(values)
    idx = const_input(indices)

    def fn(v, u, iv):
        grids = jnp.meshgrid(*[jnp.arange(s) for s in iv.shape],
                             indexing="ij")
        full_idx = list(grids)
        full_idx[axis] = iv
        si = tuple(full_idx)
        u2 = jnp.broadcast_to(u, iv.shape).astype(v.dtype)
        if reduce == "assign":
            return v.at[si].set(u2)
        if reduce == "add":
            return v.at[si].add(u2)
        if reduce in ("mul", "multiply"):
            return v.at[si].multiply(u2)
        raise NotImplementedError(f"put_along_axis reduce={reduce!r}")
    return call_op("put_along_axis", fn, (arr, values, idx))


@register_op("masked_select", "manipulation", differentiable=False)
def masked_select(x, mask, name=None):
    x = ensure_tensor(x)
    m = np.asarray(ensure_tensor(mask)._value)
    return Tensor(jnp.asarray(np.asarray(x._value)[np.broadcast_to(m, np.asarray(x._value).shape)]))


@register_op("masked_fill", "manipulation")
def masked_fill(x, mask, value, name=None):
    x = ensure_tensor(x)
    m = const_input(mask)
    if isinstance(value, Tensor):
        return call_op("masked_fill",
                       lambda v, val, mv: jnp.where(mv, val.astype(v.dtype),
                                                    v),
                       (x, value, m))
    return call_op("masked_fill",
                   lambda v, mv: jnp.where(mv, jnp.asarray(value, v.dtype),
                                           v), (x, m))


@register_op("where", "manipulation")
def where(condition, x=None, y=None, name=None):
    ct = ensure_tensor(condition)
    if x is None and y is None:
        # value-dependent output shape: inherently an eager host op
        cond = ct._value
        nz = jnp.nonzero(cond if cond.dtype == jnp.bool_.dtype else cond != 0)
        return tuple(Tensor(i[:, None].astype(jnp.int64)) for i in nz)
    return call_op("where", lambda c, a, b: jnp.where(c, a, b),
                   (const_input(ct), ensure_tensor(x), ensure_tensor(y)))


@register_op("unbind", "manipulation")
def unbind(input, axis=0, name=None):
    x = ensure_tensor(input)
    n = x.shape[axis]

    def fn(v):
        return tuple(jnp.squeeze(jax.lax.slice_in_dim(v, i, i + 1, axis=axis),
                                 axis=axis) for i in range(n))
    return call_op_multi("unbind", fn, (x,), num_outputs=n)


unstack = unbind


@register_op("unique", "manipulation", differentiable=False)
def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)
    res = np.unique(np.asarray(x._value), return_index=return_index,
                    return_inverse=return_inverse, return_counts=return_counts,
                    axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    outs = [Tensor(jnp.asarray(r)) for r in res]
    return tuple(outs)


@register_op("unique_consecutive", "manipulation", differentiable=False)
def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    x = np.asarray(ensure_tensor(x)._value)
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    keep = np.ones(x.shape[axis], dtype=bool)
    sliced = np.moveaxis(x, axis, 0)
    keep[1:] = np.any(sliced[1:] != sliced[:-1],
                      axis=tuple(range(1, sliced.ndim)))
    out = np.moveaxis(sliced[keep], 0, axis)
    outs = [Tensor(jnp.asarray(out))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        outs.append(Tensor(jnp.asarray(inv.astype(np.int64))))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, x.shape[axis] if x.ndim else len(keep)))
        outs.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return outs[0] if len(outs) == 1 else tuple(outs)


@register_op("pad", "manipulation")
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    if isinstance(pad, Tensor):
        pad = pad.numpy().tolist()
    pad = [int(p) for p in pad]
    nd = x.ndim
    if len(pad) == 2 * nd:
        # paddle "all-dim" layout: [d0_l, d0_r, d1_l, d1_r, ...]
        widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # partial spec applies to trailing spatial dims (torch-style from last
        # dim backwards), honoring data_format for 4D/5D NCHW/NHWC
        widths = [(0, 0)] * nd
        npairs = len(pad) // 2
        if data_format.endswith("C") and nd >= 3:  # NHWC / NDHWC
            dims = list(range(1, 1 + npairs))
            dims = dims[::-1]
        else:
            dims = list(range(nd - 1, nd - 1 - npairs, -1))
        for i, d in enumerate(dims):
            widths[d] = (pad[2 * i], pad[2 * i + 1])
    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]
    if jmode == "constant":
        return unary("pad", lambda v: jnp.pad(v, widths, mode="constant",
                                              constant_values=value), x)
    return unary("pad", lambda v: jnp.pad(v, widths, mode=jmode), x)


@register_op("repeat_interleave", "manipulation")
def repeat_interleave(x, repeats, axis=None, name=None):
    x = ensure_tensor(x)
    if isinstance(repeats, Tensor):
        # the output LENGTH is value-dependent (sum of repeats): the one
        # unavoidable host read sizes the result; the repeats themselves
        # then ride as a keyable dispatch input
        total = int(repeats.numpy().sum())
        rt = const_input(repeats)
        return call_op("repeat_interleave",
                       lambda v, rv: jnp.repeat(v, rv, axis=axis,
                                                total_repeat_length=total),
                       (x, rt))
    return unary("repeat_interleave",
                 lambda v: jnp.repeat(v, repeats, axis=axis), x)


@register_op("rot90", "manipulation")
def rot90(x, k=1, axes=(0, 1), name=None):
    return unary("rot90", lambda v: jnp.rot90(v, k=k, axes=tuple(axes)),
                 ensure_tensor(x))


@register_op("moveaxis", "manipulation")
def moveaxis(x, source, destination, name=None):
    return unary("moveaxis", lambda v: jnp.moveaxis(v, source, destination),
                 ensure_tensor(x))


@register_op("swapaxes", "manipulation")
def swapaxes(x, axis0, axis1, name=None):
    return unary("swapaxes", lambda v: jnp.swapaxes(v, axis0, axis1),
                 ensure_tensor(x))


transpose_2 = swapaxes  # alias used by some paddle code as paddle.transpose variants


@register_op("as_complex", "manipulation")
def as_complex(x, name=None):
    return unary("as_complex", lambda v: jax.lax.complex(v[..., 0], v[..., 1]),
                 ensure_tensor(x))


@register_op("as_real", "manipulation")
def as_real(x, name=None):
    return unary("as_real", lambda v: jnp.stack([jnp.real(v), jnp.imag(v)],
                                                axis=-1), ensure_tensor(x))


@register_op("tensordot", "manipulation")
def tensordot(x, y, axes=2, name=None):
    return binary("tensordot", lambda a, b: jnp.tensordot(a, b, axes=axes),
                  ensure_tensor(x), ensure_tensor(y))


@register_op("take", "manipulation")
def take(x, index, mode="raise", name=None):
    x = ensure_tensor(x)
    idx = const_input(index)
    jmode = {"raise": "clip", "wrap": "wrap", "clip": "clip"}[mode]
    return call_op("take",
                   lambda v, iv: jnp.take(v.reshape(-1), iv.reshape(-1),
                                          mode=jmode).reshape(iv.shape),
                   (x, idx))


def tolist(x):
    return ensure_tensor(x).tolist()


@register_op("crop", "manipulation")
def crop(x, shape=None, offsets=None, name=None):
    x = ensure_tensor(x)
    shape = [int(s) for s in (shape or x.shape)]
    offsets = [int(o) for o in (offsets or [0] * x.ndim)]
    sl = tuple(jnp.s_[o:o + s] for o, s in zip(offsets, shape))
    return unary("crop", lambda v: v[sl], x)


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    x = ensure_tensor(x)
    n = min(x.shape[-2], x.shape[-1])
    i = jnp.arange(n - (offset if offset > 0 else 0))
    x._value = x._value.at[..., i, i + offset].set(value) if offset >= 0 else \
        x._value.at[..., i - offset, i].set(value)
    return x


@register_op("unfold", "manipulation")
def unfold(x, axis, size, step, name=None):
    x = ensure_tensor(x)

    def fn(v):
        n = (v.shape[axis] - size) // step + 1
        slices = [jax.lax.slice_in_dim(v, i * step, i * step + size, axis=axis)
                  for i in range(n)]
        return jnp.stack(slices, axis=axis if axis >= 0 else v.ndim + axis)
    return unary("unfold", fn, x)


def _atleast(n):
    def op(*inputs, name=None):
        fn = {1: jnp.atleast_1d, 2: jnp.atleast_2d, 3: jnp.atleast_3d}[n]
        outs = [unary(f"atleast_{n}d", fn, ensure_tensor(t)) for t in inputs]
        return outs[0] if len(outs) == 1 else outs
    return op


atleast_1d = _atleast(1)
atleast_2d = _atleast(2)
atleast_3d = _atleast(3)


@register_op("select_scatter", "manipulation")
def select_scatter(x, values, axis, index, name=None):
    x = ensure_tensor(x)
    values = ensure_tensor(values)

    def fn(v, u):
        vm = jnp.moveaxis(v, axis, 0)
        return jnp.moveaxis(vm.at[index].set(u.astype(v.dtype)), 0, axis)
    return call_op("select_scatter", fn, (x, values))


@register_op("diagonal_scatter", "manipulation")
def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    x = ensure_tensor(x)
    y = ensure_tensor(y)

    def fn(v, u):
        n = u.shape[-1]
        i = jnp.arange(n)
        vm = jnp.moveaxis(v, (axis1, axis2), (-2, -1))
        if offset >= 0:
            vm = vm.at[..., i, i + offset].set(u)
        else:
            vm = vm.at[..., i - offset, i].set(u)
        return jnp.moveaxis(vm, (-2, -1), (axis1, axis2))
    return call_op("diagonal_scatter", fn, (x, y))


@register_op("diag_embed", "manipulation",
             ref="python/paddle/nn/functional/extension.py diag_embed")
def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    """Embed the last dim of `input` as diagonals of new matrices placed on
    (dim1, dim2) of the output (torch/paddle diag_embed semantics)."""
    x = ensure_tensor(input)

    def fn(v):
        n = v.shape[-1] + abs(offset)
        out_ndim = v.ndim + 1
        d1 = dim1 + out_ndim if dim1 < 0 else dim1
        d2 = dim2 + out_ndim if dim2 < 0 else dim2
        base = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
        i = jnp.arange(v.shape[-1])
        if offset >= 0:
            base = base.at[..., i, i + offset].set(v)
        else:
            base = base.at[..., i - offset, i].set(v)
        # diagonals currently live on the last two axes; move to (d1, d2)
        return jnp.moveaxis(base, (-2, -1), (d1, d2))

    return call_op("diag_embed", fn, (x,))
