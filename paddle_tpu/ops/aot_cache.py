"""Persistent AOT executable cache: preemption-proof warm start.

Every process today pays the full trace+compile warmup before any fusion
tier fires — the per-op executables (ops/dispatch.py), the fused chains
(ops/fusion.py), the promoted whole-step program (ops/step_fusion.py), and
the serving decode step (serving/engine.py) are all built from scratch. A
preempted or kill-9'd worker restarting under traffic therefore loses the
entire fusion stack exactly when latency matters most. This module is the
fix: a content-addressed on-disk store of `jax.export`-serialized
executables, so a restarting worker deserializes yesterday's programs and
re-promotes its fused train step on the FIRST training cycle with zero
fresh traces. Reference analog: Paddle's save/load_inference_model +
Predictor serialized-program path, scaled down to individual fused
executables and up to the whole training step.

Keying. Artifacts are addressed by a SHA-256 digest of the existing cache
keys — the per-op dispatch key (op name, fn value-token, input avals, diff
mask, AMP state, registry override, guardian flag), the chain signature
(per-op keys + wiring), the step cycle signature (op entries + backward/
optimizer events + optimizer binding constants) — canonicalized so only
process-local identities (object ids, interned ints, registry generation
counters) are erased and everything semantic survives: code objects digest
by their bytecode + consts + names, module-level functions by
module:qualname, scalars by value. Anything that cannot be canonicalized
safely simply opts out of the store (the live compiled path is untouched).
The filename additionally carries an ENVIRONMENT FINGERPRINT digest
(jax/jaxlib/numpy versions, backend platform, device kind, the PRNG-key
export form, kernel-routing flags), so version skew invalidates by
construction instead of deserializing garbage — a mismatched artifact is
reported (`aot.version_skew`) and recompiled, never trusted.

Durability. Writes go tmp + fsync + atomic rename with the same CRC-32
trailer the crash-safe checkpoint writer uses (framework/io.py), so a
crash mid-store can never leave a torn artifact under a live name, and
concurrent multi-process writers are safe by construction: content
addressing means same key -> same bytes, and the last rename wins. Loads
verify the trailer and the pickle envelope; any corruption quarantines the
file (renamed to *.corrupt for the doctor) and falls back to a transparent
recompile — `aot.corrupt` in the flight recorder, never a crash. The store
is size- and age-bounded (FLAGS_aot_cache_max_bytes / _max_age_s), evicted
oldest-mtime-first (loads refresh mtime); `fusion_doctor --cache [--gc]`
lists and collects it manually.

Grad-path decomposition. jax.export can only serialize array-in/array-out
programs, but the live fwd+vjp executables return their pullback as a
`tree_util.Partial` (residual buffers + a closure) that cannot cross a
process boundary. Stored grad artifacts therefore ship as TWO programs:
the primal forward, and a rematerializing backward `(inputs, cotangent) ->
input grads` that recomputes the forward inside the backward. The warm
process pays one extra forward FLOP per op during its single observation
cycle — after which the whole step replays as the ONE restored fused-step
program and the per-op path is idle — in exchange for zero Python-level
retraces at restart. Telemetry: profiler/aot.py counters (`aot_cache`
block in bench.py) + `aot.{hit,miss,store,corrupt,version_skew,evict}`
flight-recorder events.
"""
from __future__ import annotations

import enum
import hashlib
import os
import pickle
import socket
import threading
import time
import types

import numpy as np
import jax

from ..framework.flags import _FLAGS
from ..framework.io import (CheckpointCorruptError, _write_atomic,
                            read_verified_payload)
from ..profiler.aot import STATS as _STATS
from ..profiler.events import EVENTS as _EVENTS

__all__ = ["enabled", "cache_dir", "env_fingerprint", "fingerprint_digest",
           "op_key_digest", "store_entries", "gc_store", "AotPullback"]

_SCHEMA = 1                 # bump to orphan every existing artifact
_DIGEST_CHARS = 40          # hex chars of the key digest in the filename
_EVICT_EVERY = 16           # opportunistic eviction cadence (stores)


class Undigestable(Exception):
    """A cache-key component has no stable cross-process canonical form;
    the entry opts out of the AOT store (the live path is unaffected)."""


# ---------------------------------------------------------------------------
# canonicalization: erase process-local identity, keep semantics
# ---------------------------------------------------------------------------

def _canon_code(code, depth):
    return ("code", code.co_name, code.co_argcount,
            code.co_kwonlyargcount, code.co_flags, code.co_code,
            _canon(code.co_consts, depth + 1), code.co_names,
            code.co_varnames, code.co_freevars, code.co_cellvars)


def _canon_callable(v):
    """Module-level functions/classes/ufuncs token by module:qualname —
    the same stability contract dispatch's identity keying relies on (a
    module-level def cannot change under the key within one code
    version; cross-version drift is accepted and documented)."""
    mod = getattr(v, "__module__", None)
    qual = getattr(v, "__qualname__", None) or getattr(v, "__name__", None)
    if not mod or not qual:
        raise Undigestable(f"anonymous callable {type(v).__name__}")
    return ("fn", mod, qual)


def _canon(v, depth=0):
    """Canonical (picklable, cross-process-stable) form of a cache-key
    component. Raises Undigestable for anything identity-bound."""
    if depth > 10:
        raise Undigestable("nesting too deep")
    if v is None or isinstance(v, (bool, int, float, complex, str, bytes)):
        return v
    if v is Ellipsis or v is NotImplemented:
        # stable interpreter singletons (Ellipsis rides in the bytecode
        # consts of any fn using `...` indexing — the embedding kernel)
        return ("singleton", repr(v))
    if isinstance(v, types.CodeType):
        return _canon_code(v, depth)
    if isinstance(v, np.dtype):
        return ("npdtype", str(v))
    if isinstance(v, np.generic):
        return ("npscalar", str(v.dtype), v.tobytes())
    if isinstance(v, enum.Enum):
        return ("enum", type(v).__module__, type(v).__qualname__, v.name)
    if isinstance(v, type):
        return ("type", v.__module__, v.__qualname__)
    if isinstance(v, (tuple, list)):
        return (type(v).__name__,) + tuple(_canon(i, depth + 1) for i in v)
    if isinstance(v, dict):
        # keys canonicalize too (they could carry code objects or other
        # unpicklables); sort by the canonical repr so ordering never
        # depends on cross-type comparability
        items = [(_canon(k, depth + 1), _canon(i, depth + 1))
                 for k, i in v.items()]
        return ("dict",) + tuple(sorted(items, key=repr))
    if callable(v):
        return _canon_callable(v)
    # jax dtype-like objects (extended dtypes) stringify stably
    if hasattr(v, "dtype") and not hasattr(v, "shape"):
        return ("dtypelike", str(v))
    raise Undigestable(type(v).__name__)


def _digest_of(canonical) -> str:
    try:
        payload = pickle.dumps((canonical, _SCHEMA), protocol=4)
    except Exception as e:
        # a canonical form that still fails to pickle (an exotic scalar
        # subtype, a recursive structure) opts the key out — the store
        # must degrade, never crash a training boundary
        raise Undigestable(f"unpicklable canonical form: {e}")
    return hashlib.sha256(payload).hexdigest()


def op_key_digest(key):
    """Stable digest of a PR 1 per-op cache key, or None when the key has
    no cross-process canonical form. The registry token (component 5) is
    canonicalized to the active override NAME only: the generation counter
    is a process-local invalidation serial (the override's own fn token
    already keys the implementation by value)."""
    if key is None:
        return None
    try:
        name, ftok, avals, diff_mask, amp, reg, check = key
        canonical = ("op", name, _canon(ftok), _canon(avals), diff_mask,
                     _canon(amp), ("reg", reg[0] if reg else None),
                     bool(check))
        return _digest_of(canonical)
    except (Undigestable, ValueError, TypeError):
        return None


def op_key_canonical(key):
    """The canonical structure itself (for embedding into chain/step
    digests without double-hashing). Raises Undigestable."""
    name, ftok, avals, diff_mask, amp, reg, check = key
    return ("op", name, _canon(ftok), _canon(avals), diff_mask,
            _canon(amp), ("reg", reg[0] if reg else None), bool(check))


# ---------------------------------------------------------------------------
# environment fingerprint: version skew invalidates by construction
# ---------------------------------------------------------------------------

_fp_cache = None
_fp_generation = (-1, -1)  # (flags._GENERATION, mesh generation) of the memo
_fp_lock = threading.Lock()

# Flags read on the compiled-op path that are DELIBERATELY absent from
# `env_fingerprint` (lint rule R7 requires every FLAGS_* used under ops/
# or nn/ to be fingerprinted here or declared below). Two families only:
#   * cache-shape knobs (eager_* tier gates/sizes, aot_cache_* storage
#     limits): they decide WHETHER a cache/fusion tier engages, never the
#     lowered program for a given cache key — each program is keyed by
#     its own op/avals key, so flipping these cannot alias artifacts;
#   * host-side validation/debug toggles (check_nan_inf*,
#     check_numerics*, benchmark): they run on host values around the
#     dispatch, outside the compiled program.
# A flag that changes which kernel an op lowers to does NOT belong here —
# it goes into the fingerprint's flags tuple.
FUSION_NEUTRAL_FLAGS = frozenset({
    "FLAGS_aot_cache",
    "FLAGS_aot_cache_dir",
    "FLAGS_aot_cache_max_age_s",
    "FLAGS_aot_cache_max_bytes",
    "FLAGS_benchmark",
    "FLAGS_check_nan_inf",
    "FLAGS_check_nan_inf_level",
    "FLAGS_check_numerics",
    "FLAGS_check_numerics_level",
    "FLAGS_eager_chain_cache_size",
    "FLAGS_eager_chain_fusion",
    "FLAGS_eager_chain_fusion_min_count",
    "FLAGS_eager_chain_stitching",
    "FLAGS_eager_op_cache",
    "FLAGS_eager_op_cache_donate",
    "FLAGS_eager_op_cache_size",
    "FLAGS_eager_step_fusion",
    "FLAGS_eager_step_fusion_cache_size",
    "FLAGS_eager_step_fusion_donate_params",
    "FLAGS_eager_step_fusion_min_count",
    "FLAGS_eager_step_fusion_spmd",
})


def env_fingerprint() -> dict:
    """What must match for a stored executable to be trusted: serializer
    schema, jax/jaxlib/numpy versions, backend platform, device kind, the
    PRNG-key export form, the kernel-routing flags that steer which
    implementation an op dispatches to, AND the mesh topology (global
    device count + axis layout of the global mesh) — a single-chip
    artifact must never deserialize into a sharded process, and a dp=8
    artifact must never deserialize into a dp=2×sharding=4 one. Memoized
    against the flag-store AND mesh-generation counters, so a mid-run
    set_flags/set_global_mesh re-fingerprints instead of stamping new
    artifacts with stale state."""
    global _fp_cache, _fp_digest_cache, _fp_generation
    from ..framework import flags as _flags_mod
    from ..distributed import mesh as _mesh_mod
    gen = (_flags_mod._GENERATION, _mesh_mod.mesh_generation())
    if _fp_cache is not None and gen == _fp_generation:
        return _fp_cache
    with _fp_lock:
        if _fp_cache is not None and gen == _fp_generation:
            return _fp_cache
        _fp_digest_cache = None
        _fp_generation = gen
        try:
            import jaxlib
            jaxlib_v = getattr(jaxlib, "__version__", "?")
        except Exception:
            jaxlib_v = "?"
        try:
            dev = jax.devices()[0]
            platform, kind = dev.platform, getattr(dev, "device_kind", "?")
        except Exception:
            platform, kind = "?", "?"
        from ..framework.jax_compat import export_key_form
        fp = {
            "schema": _SCHEMA,
            "jax": jax.__version__,
            "jaxlib": jaxlib_v,
            "numpy": np.__version__,
            "platform": platform,
            "device_kind": kind,
            "key_form": export_key_form(),
            "mesh": _mesh_mod.topology_token(),
            "flags": tuple(sorted(
                [(k, bool(_FLAGS.get(k)))
                 for k in ("FLAGS_use_flash_attention",
                           "FLAGS_use_fused_layer_norm",
                           "FLAGS_use_fused_cross_entropy")]
                # the serving kernel tier is a string-valued routing flag:
                # a blockwise artifact must never deserialize into a
                # pallas (or reference) process
                + [("FLAGS_serve_attention_kernel",
                    str(_FLAGS.get("FLAGS_serve_attention_kernel")))])),
        }
        _fp_cache = fp
        return fp


_fp_digest_cache = None


def fingerprint_digest() -> str:
    """Memoized: the digest sits on the hot path (every artifact path
    construction, including the per-boundary has_step probe). The
    env_fingerprint() call comes first — it invalidates this memo when
    the flag store mutated."""
    global _fp_digest_cache
    fp = env_fingerprint()
    if _fp_digest_cache is None:
        _fp_digest_cache = hashlib.sha256(
            pickle.dumps(fp, protocol=4)).hexdigest()[:12]
    return _fp_digest_cache


def _reset_fingerprint_cache():
    """Test hook: kernel-routing flag flips re-fingerprint."""
    global _fp_cache, _fp_digest_cache
    _fp_cache = None
    _fp_digest_cache = None


# ---------------------------------------------------------------------------
# the store: content-addressed files, atomic writes, quarantine on corrupt
# ---------------------------------------------------------------------------

def enabled() -> bool:
    return bool(_FLAGS.get("FLAGS_aot_cache")) and _export_available()


_export_ok = None


def _export_available():
    global _export_ok
    if _export_ok is None:
        try:
            from jax import export as _  # noqa: F401
            _export_ok = True
        except Exception:
            _export_ok = False
    return _export_ok


def cache_dir() -> str:
    d = _FLAGS.get("FLAGS_aot_cache_dir") or ""
    if d:
        return os.fspath(d)
    root = os.environ.get("PADDLE_TPU_CACHE_DIR")
    if root:
        return os.path.join(root, "aot")
    return "/tmp/paddle_tpu_cache/aot"


def _artifact_path(kind, digest, root=None):
    return os.path.join(root or cache_dir(),
                        f"{kind}-{digest[:_DIGEST_CHARS]}-"
                        f"{fingerprint_digest()}.aot")


def has_artifact(kind, digest) -> bool:
    return digest is not None and os.path.exists(_artifact_path(kind,
                                                                digest))


def _quarantine(path):
    """Move a failed artifact aside (kept as *.corrupt for the doctor;
    eviction removes quarantined files). Best-effort: a concurrent writer
    may have already replaced or removed it."""
    try:
        os.replace(path, path + ".corrupt")
    except OSError:
        pass


_store_count = 0
_evict_lock = threading.Lock()


def store_artifact(kind, digest, label, blobs, meta=None) -> bool:
    """Serialize `blobs` (already-exported program bytes) under the
    content address. Atomic (tmp+fsync+rename with the shared CRC-32
    trailer): concurrent writers of the same key race to an identical
    result, disjoint keys never interfere. Returns True on a write."""
    global _store_count
    path = _artifact_path(kind, digest)
    payload = pickle.dumps({
        "v": 1, "kind": kind, "digest": digest, "label": label,
        "fingerprint": env_fingerprint(), "created": time.time(),
        # which fleet host exported this: on a shared store the doctor's
        # provenance column — who paid the compile the others reuse
        "host": socket.gethostname(),
        "meta": meta or {}, "blobs": list(blobs),
    }, protocol=4)
    try:
        _write_atomic(path, payload)
    except OSError:
        _STATS.store_failures += 1
        return False
    _STATS.stores += 1
    _STATS.bytes_written += len(payload)
    _EVENTS.emit("aot.store", label,
                 detail={"kind": kind, "bytes": len(payload),
                         "digest": digest[:12]})
    _store_count += 1
    if _store_count % _EVICT_EVERY == 1:
        _maybe_evict()
    return True


def load_artifact(kind, digest, label):
    """Read + verify + unpickle an artifact. Returns the payload dict, or
    None on a miss / version skew / corruption — the latter two with the
    file quarantined and the decision attributed in the flight recorder,
    so the caller's only job is a transparent recompile."""
    if digest is None:
        return None
    path = _artifact_path(kind, digest)
    try:
        payload = read_verified_payload(path, require_trailer=True)
        art = pickle.loads(payload)
        if not isinstance(art, dict) or "blobs" not in art:
            raise CheckpointCorruptError(f"{path}: not an AOT artifact")
    except FileNotFoundError:
        _STATS.misses += 1
        _EVENTS.emit("aot.miss", label, detail={"kind": kind,
                                                "digest": digest[:12]})
        _note_skew(kind, digest, label)
        return None
    except Exception as e:
        # CRC mismatch, truncation, an unreadable pickle stream, a stale
        # class in the envelope — all the same outcome: quarantine and
        # recompile, never trust the bytes
        _STATS.corrupt += 1
        _EVENTS.emit("aot.corrupt", label, reason="artifact_corrupt",
                     detail={"kind": kind, "error": repr(e)[:200]})
        _quarantine(path)
        return None
    if art.get("fingerprint") != env_fingerprint():
        # filename collisions on the fingerprint digest are astronomically
        # unlikely but the full check is one dict compare — never
        # deserialize a program built for a different environment
        _STATS.version_skew += 1
        _EVENTS.emit("aot.version_skew", label, reason="version_skew",
                     detail={"kind": kind,
                             "theirs": art.get("fingerprint")})
        return None
    try:
        os.utime(path)          # refresh mtime: eviction is LRU-ish
    except OSError:
        pass
    _STATS.bytes_loaded += sum(len(b) for b in art["blobs"])
    return art


_skew_scan = (0.0, None, frozenset())    # (ts, root, names)
_SKEW_SCAN_TTL_S = 60.0


def _store_names():
    """Directory listing for the skew probe, cached with a short TTL: a
    cold warmup misses once per key, and an O(store) listdir per miss is
    real money on a shared NFS/GCS store. Staleness only delays a
    diagnostic event, never a load decision."""
    global _skew_scan
    ts, root, names = _skew_scan
    now = time.time()
    cur = cache_dir()
    if root != cur or now - ts > _SKEW_SCAN_TTL_S:
        try:
            names = frozenset(os.listdir(cur))
        except OSError:
            names = frozenset()
        _skew_scan = (now, cur, names)
    return names


def _note_skew(kind, digest, label):
    """An exact-fingerprint miss where artifacts for the same key exist
    under OTHER fingerprints is version skew worth reporting (the worker
    fleet is running mixed versions, or an upgrade just orphaned the
    store)."""
    prefix = f"{kind}-{digest[:_DIGEST_CHARS]}-"
    for fn in _store_names():
        if fn.startswith(prefix) and fn.endswith(".aot"):
            _STATS.version_skew += 1
            _EVENTS.emit("aot.version_skew", label,
                         reason="version_skew",
                         detail={"kind": kind, "file": fn})
            return


# ---------------------------------------------------------------------------
# eviction: size/mtime bounded, quarantined files first
# ---------------------------------------------------------------------------

# a tmp file this old can only be the leftover of a writer that died
# between open() and rename() — exactly the preemption this store exists
# to survive; sweep it so kill-9'd fleets don't leak disk
_STALE_TMP_S = 3600.0


def gc_store(root=None, max_bytes=None, max_age_s=None,
             purge_quarantine=False):
    """Evict over-age and over-budget artifacts (oldest mtime first),
    stale `*.tmp.*` leftovers of killed writers, and — past the age bound
    or with `purge_quarantine` (the explicit `fusion_doctor --cache
    --gc` path) — quarantined `*.corrupt` files. Fresh quarantines
    survive the automatic post-store sweep so the doctor can still list
    and explain them. Returns the removed file names."""
    root = root or cache_dir()
    if max_bytes is None:
        max_bytes = int(_FLAGS.get("FLAGS_aot_cache_max_bytes", 1 << 30)
                        or 0)
    if max_age_s is None:
        max_age_s = float(_FLAGS.get("FLAGS_aot_cache_max_age_s",
                                     14 * 86400) or 0)
    removed = []
    try:
        names = os.listdir(root)
    except OSError:
        return removed
    now = time.time()
    rows = []
    for fn in names:
        p = os.path.join(root, fn)
        try:
            st = os.stat(p)
        except OSError:
            continue
        if ".aot.tmp." in fn:
            if now - st.st_mtime > _STALE_TMP_S:
                rows.append((fn, p, st.st_size, st.st_mtime, "tmp"))
            continue
        if fn.endswith(".corrupt"):
            rows.append((fn, p, st.st_size, st.st_mtime, "corrupt"))
        elif fn.endswith(".aot"):
            rows.append((fn, p, st.st_size, st.st_mtime, "aot"))

    def _drop(fn, p, size, why, age):
        try:
            os.unlink(p)
        except OSError:
            return
        removed.append(fn)
        _STATS.evictions += 1
        _EVENTS.emit("aot.evict", fn,
                     detail={"bytes": size, "age_s": round(age, 1),
                             "why": why})

    live = []
    for fn, p, size, mtime, kind in rows:
        age = now - mtime
        if kind == "tmp":
            _drop(fn, p, size, "stale_tmp", age)
        elif kind == "corrupt":
            if purge_quarantine or (max_age_s and age > max_age_s):
                _drop(fn, p, size, "quarantined", age)
            else:
                # fresh quarantines survive for the doctor, but they DO
                # count against (and yield to) the size budget — a flaky
                # disk must not grow the store past its bound
                live.append((mtime, fn, p, size))
        elif max_age_s and age > max_age_s:
            _drop(fn, p, size, "age", age)
        else:
            live.append((mtime, fn, p, size))
    if max_bytes:
        total = sum(size for _, _, _, size in live)
        for mtime, fn, p, size in sorted(live):
            if total <= max_bytes:
                break
            _drop(fn, p, size, "size", now - mtime)
            total -= size
    return removed


def _maybe_evict():
    if not _evict_lock.acquire(blocking=False):
        return
    try:
        gc_store()
    finally:
        _evict_lock.release()


def store_entries(root=None, verify=True):
    """Doctor listing: one dict per artifact file (kind, digest,
    fingerprint match, label, size, age, corrupt flag). With `verify`,
    each file's CRC trailer and envelope are checked so torn writes show
    up as corrupt instead of as healthy rows."""
    root = root or cache_dir()
    out = []
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return out
    now = time.time()
    my_fp = fingerprint_digest()
    for fn in names:
        if not (fn.endswith(".aot") or fn.endswith(".corrupt")):
            continue
        p = os.path.join(root, fn)
        try:
            st = os.stat(p)
        except OSError:
            continue
        row = {"file": fn, "bytes": st.st_size,
               "age_s": round(now - st.st_mtime, 1),
               "quarantined": fn.endswith(".corrupt"),
               "kind": fn.split("-", 1)[0] if "-" in fn else "?",
               "label": None, "host": None,
               "fingerprint_match": None, "corrupt": None}
        stem = fn[:-len(".aot")] if fn.endswith(".aot") else fn
        parts = stem.split("-")
        if len(parts) >= 3:
            row["digest"] = parts[1]
            row["fingerprint_match"] = parts[2].split(".")[0] == my_fp
        if verify and not row["quarantined"]:
            try:
                art = pickle.loads(
                    read_verified_payload(p, require_trailer=True))
                row["label"] = art.get("label")
                row["host"] = art.get("host")
                row["corrupt"] = False
                row["fingerprint_match"] = \
                    art.get("fingerprint") == env_fingerprint()
            except Exception:
                row["corrupt"] = True
        out.append(row)
    return out


# ---------------------------------------------------------------------------
# export / import of executables
# ---------------------------------------------------------------------------

def _spec_of(v):
    return jax.ShapeDtypeStruct(v.shape, v.dtype,
                                weak_type=getattr(v, "weak_type", False))


def _specs_of(vals):
    return jax.tree_util.tree_map(_spec_of, vals)


def export_bytes(jitted, specs) -> bytes:
    """Trace+lower `jitted` at `specs` via jax.export and serialize. The
    export IS a trace (any compile-counting side effects inside the traced
    fn run once more) — honest accounting, paid only in processes that
    write the store."""
    from jax import export as jexport
    return jexport.export(jitted)(*specs).serialize()


def _deserialize_callable(blob, donate_argnums=()):
    from jax import export as jexport
    exported = jexport.deserialize(bytes(blob))
    # jit around the opaque call: the wrapper traces once (trivially — the
    # body is one pre-lowered module) and the XLA compile of the stablehlo
    # shares the persistent compilation cache; donation re-applies at the
    # wrapper so TPU buffer reuse survives the round trip
    if donate_argnums:
        return jax.jit(exported.call, donate_argnums=tuple(donate_argnums))
    return jax.jit(exported.call)


class _Healing:
    """A deserialized executable that can never take the process down: any
    non-runtime failure (argument/signature mismatch from a hash
    collision, a stale module, a deserializer edge) quarantines the
    artifact, rebuilds the REAL executable via the fallback builder, and
    replays the call — transparent recompile, identical contract. Genuine
    XLA runtime faults propagate unchanged so the callers' existing
    exec_fault handling stays truthful."""

    __slots__ = ("_impl", "_fallback", "_path", "_label", "healed")

    def __init__(self, impl, fallback, path, label):
        self._impl = impl
        self._fallback = fallback
        self._path = path
        self._label = label
        self.healed = False

    def __call__(self, *args):
        try:
            return self._impl(*args)
        except jax.errors.JaxRuntimeError:
            raise
        except Exception as e:
            if self.healed:
                raise
            _STATS.corrupt += 1
            _EVENTS.emit("aot.corrupt", self._label,
                         reason="artifact_corrupt",
                         detail={"stage": "call",
                                 "error": repr(e)[:200]})
            _quarantine(self._path)
            self._impl = self._fallback()
            self.healed = True
            return self._impl(*args)


def load_callable(kind, digest, label, fallback, donate_argnums=(),
                  accept=None):
    """One-program artifact -> a healing callable, or None (miss / skew /
    corrupt — all attributed; the caller builds live). `accept` is an
    optional predicate over the artifact meta: a False verdict is a miss
    (the stored program has an incompatible calling convention — e.g. a
    plain-jit lowering where the live program wants shard_map), never a
    quarantine."""
    art = load_artifact(kind, digest, label)
    if art is None:
        return None
    if accept is not None and not accept(art.get("meta") or {}):
        _STATS.misses += 1
        _EVENTS.emit("aot.miss", label,
                     detail={"kind": kind, "digest": digest[:12],
                             "why": "lowering_mismatch"})
        return None
    try:
        impl = _deserialize_callable(art["blobs"][0], donate_argnums)
    except Exception as e:
        _STATS.corrupt += 1
        _EVENTS.emit("aot.corrupt", label, reason="artifact_corrupt",
                     detail={"kind": kind, "stage": "deserialize",
                             "error": repr(e)[:200]})
        _quarantine(_artifact_path(kind, digest))
        return None
    _STATS.hits += 1
    _EVENTS.emit("aot.hit", label, detail={"kind": kind,
                                           "digest": digest[:12]})
    return _Healing(impl, fallback, _artifact_path(kind, digest), label)


# ---------------------------------------------------------------------------
# grad-path artifacts: primal + rematerializing backward
# ---------------------------------------------------------------------------

def _live_vjp(fn, vals, diff_idx):
    """The uncached pullback over the differentiable subset (the
    _slow_vjp partial-fn contract) — the healing fallback for a stored
    backward program."""
    if len(diff_idx) == len(vals):
        return jax.vjp(fn, *vals)[1]

    def pf(*dv):
        full = list(vals)
        for i, v in zip(diff_idx, dv):
            full[i] = v
        return fn(*full)
    return jax.vjp(pf, *(vals[i] for i in diff_idx))[1]


class AotPullback:
    """Per-call pullback handle produced by a restored grad executable.

    Recognized by dispatch._make_cached_vjp / fusion._make_chain_vjp in
    place of the live `tree_util.Partial`: `make_wrapped` yields the same
    engine-facing pullback contract, backed by the stored rematerializing
    backward program instead of the in-process residual applier. On any
    non-runtime failure it falls back to a live jax.vjp over the captured
    inputs (memoized — a retained-graph double backward pays one trace,
    not one per call) AND tells the owning executable to quarantine the
    artifact and heal, so future forwards — and future restarts — take
    the live compiled path instead of re-failing forever."""

    __slots__ = ("_bwd", "_vals", "_fn", "_diff_idx", "_label", "_owner",
                 "_live")

    def __init__(self, bwd, vals, fn, diff_idx, label, owner=None):
        self._bwd = bwd
        self._vals = vals
        self._fn = fn
        self._diff_idx = diff_idx
        self._label = label
        self._owner = owner
        self._live = None

    def make_wrapped(self, diff_idx, n_in, multi):
        pb = self

        def wrapped(g, donate=False):
            # donation of residuals does not apply: the stored backward
            # rematerializes from the (still live) inputs
            if multi and not isinstance(g, tuple):
                g = (g,)
            if pb._live is not None:
                partial = pb._live(g)
            else:
                try:
                    partial = pb._bwd(pb._vals, g)
                except jax.errors.JaxRuntimeError:
                    raise
                except Exception as e:
                    _STATS.corrupt += 1
                    _EVENTS.emit("aot.corrupt", pb._label,
                                 reason="artifact_corrupt",
                                 detail={"stage": "backward",
                                         "error": repr(e)[:200]})
                    if pb._owner is not None:
                        pb._owner.mark_bwd_broken()
                    pb._live = _live_vjp(pb._fn, pb._vals, pb._diff_idx)
                    partial = pb._live(g)
            full = [None] * n_in
            for i, pg in zip(diff_idx, partial):
                full[i] = pg
            return tuple(full)
        wrapped._supports_donate = True
        return wrapped


class _AotGradExe:
    """Restored grad-path executable with the `_build_fwd_vjp` call
    contract: exe(*vals) -> (out, pullback) — or ((out, pullback), fin)
    under the guardian — where the pullback is an AotPullback over the
    stored backward. Self-healing: a failing primal swaps in the real
    compiled executable (whose Partial pullback then takes the normal
    applier path)."""

    __slots__ = ("_primal", "_bwd", "_fn", "_diff_idx", "_check", "_label",
                 "_path", "_fallback", "_healed")

    def __init__(self, primal, bwd, fn, diff_idx, check, label, path,
                 fallback):
        self._primal = primal
        self._bwd = bwd
        self._fn = fn
        self._diff_idx = diff_idx
        self._check = check
        self._label = label
        self._path = path
        self._fallback = fallback
        self._healed = None

    def __call__(self, *vals):
        if self._healed is not None:
            return self._healed(*vals)
        try:
            res = self._primal(*vals)
        except jax.errors.JaxRuntimeError:
            raise
        except Exception as e:
            _STATS.corrupt += 1
            _EVENTS.emit("aot.corrupt", self._label,
                         reason="artifact_corrupt",
                         detail={"stage": "primal",
                                 "error": repr(e)[:200]})
            _quarantine(self._path)
            self._healed = self._fallback()
            return self._healed(*vals)
        if self._check:
            out, fin = res
        else:
            out = res
        pb = AotPullback(self._bwd, vals, self._fn, self._diff_idx,
                         self._label, owner=self)
        return ((out, pb), fin) if self._check else (out, pb)

    def mark_bwd_broken(self):
        """A pullback's stored backward failed: quarantine the artifact
        and swap in the real compiled executable so every FUTURE forward
        (and restart) takes the live path."""
        if self._healed is None:
            _quarantine(self._path)
            try:
                self._healed = self._fallback()
            except Exception:
                pass


def _wrap_check_primal(fn, check):
    """The forward program to export: `fn` itself, or — under the
    guardian — `fn` plus the ONE fused all-finite scalar, mirroring the
    live `_build_fwd[_vjp]` / chain-build output contract. One helper so
    the op/chain/grad variants cannot drift."""
    if not check:
        return fn
    from . import guardian

    def primal(*xs):
        out = fn(*xs)
        outs = out if isinstance(out, tuple) else (out,)
        return out, guardian.finite_all(outs)
    return primal


def _export_primal_bwd(fn, diff_idx, check, in_specs, label):
    """Export the (primal, remat-backward) program pair for a grad-path
    fn. The cotangent signature comes from an abstract eval of `fn` — no
    concrete execution, no device work."""
    primal = _wrap_check_primal(fn, check)

    def bwd(xs, g):
        return _live_vjp(fn, xs, diff_idx)(g)

    out_specs = jax.eval_shape(fn, *in_specs)
    return [export_bytes(jax.jit(primal), in_specs),
            export_bytes(jax.jit(bwd), (tuple(in_specs), out_specs))]


# ---------------------------------------------------------------------------
# per-op tier (ops/dispatch.py hooks)
# ---------------------------------------------------------------------------

def store_op(key, name, fn, diff_idx, check, vals):
    """Persist a freshly built per-op executable. Store-if-absent: the
    export (a re-trace) is only paid when the artifact does not already
    exist — a warm process that loaded the artifact never re-exports."""
    digest = op_key_digest(key)
    if digest is None or has_artifact("op", digest):
        return
    in_specs = tuple(_spec_of(v) for v in vals)
    try:
        if diff_idx is None:
            blobs = [export_bytes(jax.jit(_wrap_check_primal(fn, check)),
                                  in_specs)]
        else:
            blobs = _export_primal_bwd(fn, diff_idx, check, in_specs, name)
    except Exception as e:
        _STATS.store_failures += 1
        _EVENTS.emit("aot.store", name,
                     detail={"kind": "op", "failed": repr(e)[:200]})
        return
    store_artifact("op", digest, name, blobs,
                   meta={"grad": diff_idx is not None, "check": check})


def load_op(key, name, fn, diff_idx, check):
    """Restore a per-op executable with the exact `_cached_call` value
    contract, or None. The returned object drops into the dispatch LRU
    like a live jitted executable."""
    digest = op_key_digest(key)
    art = load_artifact("op", digest, name)
    if art is None:
        return None
    path = _artifact_path("op", digest)
    try:
        if diff_idx is None:
            impl = _deserialize_callable(art["blobs"][0])
        else:
            primal = _deserialize_callable(art["blobs"][0])
            bwd = _deserialize_callable(art["blobs"][1])
    except Exception as e:
        _STATS.corrupt += 1
        _EVENTS.emit("aot.corrupt", name, reason="artifact_corrupt",
                     detail={"kind": "op", "stage": "deserialize",
                             "error": repr(e)[:200]})
        _quarantine(path)
        return None
    _STATS.hits += 1
    _EVENTS.emit("aot.hit", name, detail={"kind": "op",
                                          "grad": diff_idx is not None,
                                          "digest": digest[:12]})
    from .dispatch import _build_fwd, _build_fwd_vjp
    if diff_idx is None:
        return _Healing(impl, lambda: _build_fwd(name, fn, check), path,
                        name)
    return _AotGradExe(primal, bwd, fn, diff_idx, check, name, path,
                       lambda: _build_fwd_vjp(name, fn, diff_idx, check))


# ---------------------------------------------------------------------------
# chain tier (ops/fusion.py hooks)
# ---------------------------------------------------------------------------

def chain_digest(chain):
    """Digest of a chain's signature — per-op canonical keys + wiring —
    memoized on the Chain (None = opted out)."""
    if chain.aot_digest != 0:
        return chain.aot_digest
    try:
        canonical = ("chain",
                     tuple((op_key_canonical(op.key), op.wiring,
                            op.diff_mask, op.num_outputs)
                           for op in chain.ops),
                     chain.grad_mode, chain.check)
        chain.aot_digest = _digest_of(canonical)
    except (Undigestable, ValueError, TypeError):
        chain.aot_digest = None
    return chain.aot_digest


def store_chain(chain, ext_vals):
    digest = chain_digest(chain)
    if digest is None or has_artifact("chain", digest):
        return
    in_specs = tuple(_spec_of(v) for v in ext_vals)
    run = chain.pure_fn
    try:
        if chain.grad_mode:
            blobs = _export_primal_bwd(run, chain.diff_ext_idx,
                                       chain.check, in_specs, chain.label)
        else:
            blobs = [export_bytes(
                jax.jit(_wrap_check_primal(run, chain.check)), in_specs)]
    except Exception as e:
        _STATS.store_failures += 1
        _EVENTS.emit("aot.store", chain.label,
                     detail={"kind": "chain", "failed": repr(e)[:200]})
        return
    store_artifact("chain", digest, chain.label, blobs,
                   meta={"ops": len(chain.ops), "grad": chain.grad_mode,
                         "check": chain.check})


def load_chain(chain, grad):
    """Restore a chain executable in the `_build_chain_fwd[_vjp]` call
    contract, or None. The variant (fwd vs fwd+vjp) rides the same
    artifact: grad chains store the primal+backward pair, and the
    forward-only variant just uses the primal program."""
    digest = chain_digest(chain)
    art = load_artifact("chain", digest, chain.label)
    if art is None:
        return None
    path = _artifact_path("chain", digest)
    try:
        primal = _deserialize_callable(art["blobs"][0])
        bwd = _deserialize_callable(art["blobs"][1]) \
            if grad and len(art["blobs"]) > 1 else None
    except Exception as e:
        _STATS.corrupt += 1
        _EVENTS.emit("aot.corrupt", chain.label,
                     reason="artifact_corrupt",
                     detail={"kind": "chain", "stage": "deserialize",
                             "error": repr(e)[:200]})
        _quarantine(path)
        return None
    if grad and bwd is None:
        return None          # stored forward-only, caller wants the vjp
    _STATS.hits += 1
    _EVENTS.emit("aot.hit", chain.label,
                 detail={"kind": "chain", "grad": grad,
                         "digest": digest[:12]})
    from .fusion import _build_chain_fwd, _build_chain_fwd_vjp
    if not grad:
        return _Healing(primal, lambda: _build_chain_fwd(chain), path,
                        chain.label)
    return _AotGradExe(primal, bwd, chain.pure_fn, chain.diff_ext_idx,
                       chain.check, chain.label, path,
                       lambda: _build_chain_fwd_vjp(chain))


# ---------------------------------------------------------------------------
# whole-step tier (ops/step_fusion.py hooks)
# ---------------------------------------------------------------------------

def _canon_cycle_entries(sig):
    entries = []
    for e in sig:
        if e[0] == "op":
            # trailing components past the canonical five are stable
            # value tuples (hoisted-RNG stream marks): digest as-is
            entries.append(("op", op_key_canonical(e[1]), e[2], e[3],
                            e[4]) + tuple(e[5:]))
        elif e[0] == "bwd":
            entries.append(("bwd", e[1]))
        elif e[0] == "cg":
            entries.append(("cg",))
        elif e[0] == "scaler":
            entries.append(("scaler", _canon(e[2], 1)))
        elif e[0] == "step":
            entries.append(("step", len(e[2])))
        else:
            raise Undigestable(f"cycle entry {e[0]!r}")
    return tuple(entries)


def step_digest(sig, opt, updated):
    """Digest of a promoted-step identity: the cycle signature (op keys +
    wiring + backward/clear_grad/scaler/step events, process-local ids
    erased) plus every constant `_build` bakes into the traced program —
    optimizer type and hyper-param key, accumulator structure, clip/
    regularizer snapshots, parameter binding, donation flag. A canonical
    super-cycle signature (ops/step_fusion._super_sig) digests its ONE
    segment plus the event frame — k-independent, like the programs it
    addresses. Returns None when any component has no stable form (the
    step opts out)."""
    from .step_fusion import _snapshot_obj
    try:
        if sig and sig[0] == "super":
            _tag, cg_e, seg_entries, scaler_e, step_e = sig[:5]
            entries = ("super", _canon_cycle_entries(tuple(seg_entries)),
                       cg_e is not None,
                       None if scaler_e is None
                       else ("scaler", _canon(scaler_e[2], 1)),
                       ("step", len(step_e[2])))
            if len(sig) > 5:
                # ragged tail: the tail segment joins the digest so a
                # ragged program never aliases its uniform twin (the main
                # sub/update pair restores from the store; the tail sub
                # compiles live)
                entries += (("tail",
                             _canon_cycle_entries(tuple(sig[5]))),)
        else:
            entries = _canon_cycle_entries(sig)
        accs = tuple(sorted(getattr(opt, "_accumulators", {}).keys()))
        canonical = (
            "step", tuple(entries),
            ("params", tuple(p.name for p in updated),
             tuple(bool(getattr(p, "need_clip", True)) for p in updated),
             tuple(_canon(_snapshot_obj(getattr(p, "regularizer", None)),
                          1) for p in updated)),
            ("opt", type(opt).__qualname__,
             _canon(tuple(opt._extra_cache_key()), 1), accs),
            ("clip", _canon(_snapshot_obj(opt._grad_clip), 1)),
            ("reg", _canon(_snapshot_obj(opt.regularization), 1)),
            ("donate",
             bool(_FLAGS.get("FLAGS_eager_step_fusion_donate_params"))),
        )
        return _digest_of(canonical)
    except (Undigestable, ValueError, TypeError, AttributeError):
        return None


def has_step(digest) -> bool:
    return has_artifact("step", digest)


def store_step(program, args):
    """Persist the ONE fused whole-step executable right after its first
    successful fire (`args` are the concrete fire arguments — shapes are
    readable even off donated buffers). Skipped when the executable was
    itself restored from the store."""
    digest = program.aot_digest
    if digest is None or has_artifact("step", digest):
        return
    exe = program._exe
    if exe is None or isinstance(exe, _Healing):
        return
    try:
        specs = tuple(_specs_of(a) for a in args)
        blobs = [export_bytes(exe, specs)]
    except Exception as e:
        _STATS.store_failures += 1
        _EVENTS.emit("aot.store", program.label,
                     detail={"kind": "step", "failed": repr(e)[:200]})
        return
    store_artifact("step", digest, program.label, blobs,
                   meta={"ops": len(program.chain.ops),
                         "params": len(program.param_names),
                         "check": program.check,
                         "scaler": program.scaler_consts is not None,
                         "spmd": program.spmd_plan is not None})


def load_step(program, fallback, donate_argnums):
    """Restore the fused whole-step executable (healing; donation
    re-applied at the wrapper), or None. The artifact must match the live
    program's LOWERING: a plain-jit export (stored by a process whose
    probation demoted the mesh plan) cannot serve a shard_map caller —
    the arg conventions differ — so a spmd-ness mismatch is a miss."""
    want_spmd = program.spmd_plan is not None
    return load_callable(
        "step", program.aot_digest, program.label, fallback,
        donate_argnums,
        accept=lambda meta: bool(meta.get("spmd")) == want_spmd)


def store_super_step(program, sub_args, upd_args):
    """Persist a super-cycle program's executable PAIR — the micro-batch
    sub-executable and the boundary update executable — as one two-blob
    artifact, right after the first successful boundary fire. A restarting
    worker then replays its accumulation loop with zero fresh compiles at
    any k."""
    digest = program.aot_digest
    if digest is None or has_artifact("step", digest):
        return
    sub, upd = program._sub_exe, program._upd_exe
    if sub is None or upd is None \
            or isinstance(sub, _Healing) or isinstance(upd, _Healing):
        return
    try:
        blobs = [export_bytes(sub, tuple(_specs_of(a) for a in sub_args)),
                 export_bytes(upd, tuple(_specs_of(a) for a in upd_args))]
    except Exception as e:
        _STATS.store_failures += 1
        _EVENTS.emit("aot.store", program.label,
                     detail={"kind": "step", "super": True,
                             "failed": repr(e)[:200]})
        return
    store_artifact("step", digest, program.label, blobs,
                   meta={"super": True, "ops": len(program.chain.ops),
                         "params": len(program.param_names),
                         "check": program.check,
                         "scaler": program.scaler_consts is not None,
                         "spmd": program.spmd_plan is not None})


def load_super_step(program, sub_fallback, upd_fallback, upd_donate):
    """Restore the (sub, update) executable pair of a super-cycle
    program as healing callables, or (None, None)."""
    art = load_artifact("step", program.aot_digest, program.label)
    if art is None or len(art.get("blobs", ())) != 2 \
            or not (art.get("meta") or {}).get("super"):
        return None, None
    if bool((art.get("meta") or {}).get("spmd")) \
            != (program.spmd_plan is not None):
        # lowering mismatch (plain-jit pair vs shard_map caller or vice
        # versa): the arg conventions differ — a miss, not corruption
        _STATS.misses += 1
        _EVENTS.emit("aot.miss", program.label,
                     detail={"kind": "step",
                             "digest": program.aot_digest[:12],
                             "why": "lowering_mismatch"})
        return None, None
    path = _artifact_path("step", program.aot_digest)
    try:
        sub = _deserialize_callable(art["blobs"][0])
        upd = _deserialize_callable(art["blobs"][1], upd_donate)
    except Exception as e:
        _STATS.corrupt += 1
        _EVENTS.emit("aot.corrupt", program.label,
                     reason="artifact_corrupt",
                     detail={"kind": "step", "stage": "deserialize",
                             "error": repr(e)[:200]})
        _quarantine(path)
        return None, None
    _STATS.hits += 1
    _EVENTS.emit("aot.hit", program.label,
                 detail={"kind": "step", "digest": program.aot_digest[:12],
                         "super": True})
    return (_Healing(sub, sub_fallback, path, program.label),
            _Healing(upd, upd_fallback, path, program.label))
