"""Search / sort ops. Reference analog: python/paddle/tensor/search.py."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework.dtype import to_jax_dtype
from .registry import register_op
from ._helpers import ensure_tensor, unary, call_op, call_op_multi

__all__ = ["argmax", "argmin", "argsort", "sort", "topk", "searchsorted",
           "nonzero", "kthvalue", "mode", "index_sample", "bucketize"]


@register_op("argmax", "search", differentiable=False)
def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = ensure_tensor(x)
    v = x._value
    out = jnp.argmax(v if axis is not None else v.reshape(-1), axis=axis)
    if keepdim and axis is not None:
        out = jnp.expand_dims(out, axis)
    return Tensor(out.astype(to_jax_dtype(dtype)))


@register_op("argmin", "search", differentiable=False)
def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = ensure_tensor(x)
    v = x._value
    out = jnp.argmin(v if axis is not None else v.reshape(-1), axis=axis)
    if keepdim and axis is not None:
        out = jnp.expand_dims(out, axis)
    return Tensor(out.astype(to_jax_dtype(dtype)))


@register_op("argsort", "search", differentiable=False)
def argsort(x, axis=-1, descending=False, name=None):
    x = ensure_tensor(x)
    v = x._value
    idx = jnp.argsort(v, axis=axis, descending=descending)
    return Tensor(idx.astype(jnp.int64))


@register_op("sort", "search")
def sort(x, axis=-1, descending=False, name=None):
    x = ensure_tensor(x)
    return unary("sort", lambda v: jnp.sort(v, axis=axis,
                                            descending=descending), x)


@register_op("topk", "search")
def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    x = ensure_tensor(x)
    if isinstance(k, Tensor):
        k = int(k.item())
    ax = -1 if axis is None else axis

    def fn(v):
        vm = jnp.moveaxis(v, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(vm, k)
        else:
            vals, idx = jax.lax.top_k(-vm, k)
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax)

    # indices are non-differentiable; dispatch values through autograd and
    # compute indices alongside
    vals, idx = fn(x._value)
    if x.stop_gradient:
        return Tensor(vals), Tensor(idx.astype(jnp.int64))
    out_vals = call_op("topk", lambda v: fn(v)[0], (x,))
    return out_vals, Tensor(idx.astype(jnp.int64))


@register_op("searchsorted", "search", differentiable=False)
def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    s = ensure_tensor(sorted_sequence)._value
    v = ensure_tensor(values)._value
    side = "right" if right else "left"
    if s.ndim == 1:
        out = jnp.searchsorted(s, v, side=side)
    else:
        flat_s = s.reshape(-1, s.shape[-1])
        flat_v = v.reshape(-1, v.shape[-1])
        out = jax.vmap(lambda a, b: jnp.searchsorted(a, b, side=side))(
            flat_s, flat_v).reshape(v.shape)
    return Tensor(out.astype(jnp.int32 if out_int32 else jnp.int64))


bucketize = searchsorted


@register_op("nonzero", "search", differentiable=False)
def nonzero(x, as_tuple=False, name=None):
    x = ensure_tensor(x)
    nz = np.nonzero(np.asarray(x._value))
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i.astype(np.int64))) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


@register_op("kthvalue", "search")
def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = ensure_tensor(x)

    def fn(v):
        sv = jnp.sort(v, axis=axis)
        out = jnp.take(sv, k - 1, axis=axis)
        return jnp.expand_dims(out, axis) if keepdim else out
    vals = call_op("kthvalue", fn, (x,))
    idx_v = jnp.take(jnp.argsort(x._value, axis=axis), k - 1, axis=axis)
    if keepdim:
        idx_v = jnp.expand_dims(idx_v, axis)
    return vals, Tensor(idx_v.astype(jnp.int64))


@register_op("mode", "search", differentiable=False)
def mode(x, axis=-1, keepdim=False, name=None):
    xv = np.asarray(ensure_tensor(x)._value)
    xm = np.moveaxis(xv, axis, -1)
    flat = xm.reshape(-1, xm.shape[-1])
    vals = np.empty(flat.shape[0], xv.dtype)
    inds = np.empty(flat.shape[0], np.int64)
    for i, row in enumerate(flat):
        uniq, counts = np.unique(row, return_counts=True)
        best = uniq[np.argmax(counts)]
        vals[i] = best
        inds[i] = np.where(row == best)[0][-1]
    out_shape = xm.shape[:-1]
    vals = vals.reshape(out_shape)
    inds = inds.reshape(out_shape)
    if keepdim:
        vals = np.expand_dims(vals, axis)
        inds = np.expand_dims(inds, axis)
    return Tensor(jnp.asarray(vals)), Tensor(jnp.asarray(inds))


@register_op("index_sample_search", "search")
def index_sample(x, index):
    from .manipulation import index_sample as _is
    return _is(x, index)
