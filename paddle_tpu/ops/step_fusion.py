"""Whole-step eager fusion: auto-TrainStep promotion.

The layer above chain fusion (ops/fusion.py). Chain fusion collapses hot
forward op *sequences* into single launches, but every chain stops at a
tape read: `loss.backward()` forces the pending chain, and the backward
walk plus the optimizer update still launch per-node. `jit.TrainStep`
proves the fast path is ONE executable for the whole step — this module
gets eager loops there automatically, without the user rewriting their
loop.

How it works:

  OBSERVE   Every dispatched op, `Tensor.backward()` call, and optimizer
            `step()`/`clear_grad()` call is recorded into the current
            *cycle* (one training iteration, delimited by `opt.step()`
            entries). A cycle's signature is the ordered tuple of per-op
            cache keys + dataflow wiring + the backward/optimizer events —
            the same keying discipline as chain fusion scaled to a step,
            so every per-op invalidation rule (registry generation, AMP
            state, avals, diff masks) applies for free.

  PROMOTE   After FLAGS_eager_step_fusion_min_count consecutive identical
            cycles, the cycle is compiled into one fused executable:
            forward (rebuilt as a pure function from the recorded ops, the
            re-trace contract of framework/autograd.replay_pure), backward
            (jax.vjp w.r.t. the parameter slots), grad regularization +
            clipping (the optimizer's own clip/regularizer objects traced
            over shims), and the optimizer update (`_single_update`, with
            decay flags baked by jit/train_step.bake_decay_flags).
            Optimizer-slot buffers are donated exactly as the eager
            optimizer's fused update donates them; parameter donation is
            opt-in (FLAGS_eager_step_fusion_donate_params), sharing
            jit/train_step.donation_argnums.

  REPLAY    Speculative and transactional, like chain replay: each
            dispatch is matched against the promoted program and deferred
            as a `_DeferredTensor`; `loss.backward()` is consumed as an
            event (p.grad becomes a pending placeholder); `opt.step()`
            fires the ONE fused launch, updates parameters/slots in place,
            and fills the loss + grad placeholders from the fused outputs.
            The LR-schedule value and the step count are hoisted to scalar
            arguments, so schedulers never split. ANY divergence — an op
            or event mismatch, a mid-step value peek (a `loss.numpy()`
            between backward and step; after the step it is served from
            the fused outputs), a changed optimizer/clip/param set, an
            in-place param mutation, an RNG-key advance (random ops re-key
            every call), an execution fault — SPLITS: the deferred prefix
            replays through the chain/per-op cached path and, if the
            backward event was already consumed, the real tape backward
            runs, so numerics are bitwise-identical to unfused dispatch in
            every outcome. Steps that keep failing to replay are
            deactivated.

Telemetry: profiler/step_fusion.py, surfaced by
`paddle_tpu.profiler.step_fusion_stats()` and embedded in bench.py
headline records as the `step_fusion` block.
"""
from __future__ import annotations

import threading
import time
import weakref
from collections import OrderedDict

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework import autograd as _autograd
from ..framework.autograd import FusedStepNode, run_backward
from ..framework.flags import _FLAGS
from ..profiler.step_fusion import STEP_STATS
from ..profiler.events import EVENTS as _EVENTS
from .fusion import (MANAGER as _CHAIN_MANAGER, Chain, _ChainOp,
                     _DeferredTensor, _PENDING, _VALUE_SLOT, _NODE_SLOT,
                     _IDX_SLOT, _is_pending, _key_diff_reason,
                     replay_ops_per_op)

__all__ = ["STEP", "MISS", "clear_step_cache", "step_cache_info"]

MISS = object()

# consecutive failed replays before a promoted step is deactivated
_MAX_FAIL_STREAK = 4
# recording cap per cycle: a cycle longer than this cannot promote (the
# compile would not amortize) and recording details stop to bound memory
_MAX_CYCLE_OPS = 2048

_UNBUILDABLE = object()     # library sentinel: this sig cannot promote


def _out_aval(t):
    """(shape, dtype, weak_type) without forcing a pending placeholder."""
    av = getattr(t, "_fusion_aval", None)
    if av is not None:
        return av
    v = t._value
    return (v.shape, v.dtype, getattr(v, "weak_type", False))


def _snapshot_obj(obj):
    """Value snapshot of a clip/regularizer object's scalar attributes:
    these are baked into the traced step as constants, so a mutation must
    un-verify the promoted program."""
    if obj is None:
        return None
    attrs = tuple(sorted(
        (k, v) for k, v in vars(obj).items()
        if isinstance(v, (int, float, bool, str))))
    return (type(obj).__name__, attrs)


class _OpRec:
    """One dispatch recorded into the current observation cycle. `ins` and
    `outs` hold strong refs for the cycle's lifetime: the produced-map is
    keyed by id(), so every recorded tensor must stay alive or a freed
    id's reuse would mis-wire a later fresh input as ("prev", i, j)."""

    __slots__ = ("name", "key", "fn", "wiring", "diff_mask", "num_outputs",
                 "out_avals", "out_stop_grads", "ins", "outs")

    def __init__(self, name, key, fn, wiring, diff_mask, num_outputs,
                 out_avals, out_stop_grads, ins, outs):
        self.name = name
        self.key = key
        self.fn = fn
        self.wiring = wiring
        self.diff_mask = diff_mask
        self.num_outputs = num_outputs
        self.out_avals = out_avals
        self.out_stop_grads = out_stop_grads
        self.ins = ins
        self.outs = outs


class _Cycle:
    """Observation state for one training iteration."""

    __slots__ = ("entries", "ops", "produced", "dirty", "t0", "n_backward",
                 "scaler")

    def __init__(self):
        self.entries = []
        self.ops = []
        self.produced = {}     # id(tensor) -> (op index, out index)
        self.dirty = False
        self.t0 = time.perf_counter_ns()
        self.n_backward = 0
        self.scaler = None     # GradScaler seen by on_scaler_step, if any

    def poison(self):
        """The cycle cannot promote: drop every recorded detail NOW so a
        dirty (or boundary-less, e.g. pure-inference) stream pins no
        tensors — after this, record() is a cheap early return until the
        next optimizer-step boundary."""
        self.dirty = True
        self.entries.clear()
        self.ops.clear()
        self.produced.clear()
        self.scaler = None


class _ParamShim:
    """Minimal stand-in for a Parameter inside the traced grad transform:
    the optimizer's clip/regularizer objects only read `_value`,
    `need_clip`, `name`, and `regularizer`."""

    __slots__ = ("_value", "name", "need_clip", "regularizer")


class _StepProgram:
    """A promoted cycle: the forward chain, the event schedule, the
    optimizer binding, and (lazily) the one fused executable."""

    __slots__ = ("sig", "chain", "entries", "root_coord", "root_flat",
                 "param_refs", "param_names", "param_regs", "need_clip",
                 "param_slots", "ext_order", "opt_ref", "clip_ref",
                 "clip_snapshot", "reg_ref", "reg_snapshot", "extra_key",
                 "acc_names", "label", "n_launches", "baseline_ns",
                 "fail_streak", "dead", "_exe", "_shims", "donate_params",
                 "check", "scaler_ref", "scaler_consts", "aot_digest",
                 "aot_stored", "spmd_plan", "spmd_ok")

    def __init__(self):
        self.fail_streak = 0
        self.dead = False
        self._exe = None
        self._shims = None
        self.aot_digest = None   # ops/aot_cache.py warm-start address
        self.aot_stored = False
        # guardian (FLAGS_check_numerics, ops/guardian.py): check-ness is
        # fixed by the signature (the per-op keys carry the flag), and the
        # executable then folds the skip-step where()-rescue in; a fused
        # GradScaler additionally folds unscale/found-inf/scale-update
        self.check = False
        self.scaler_ref = None
        self.scaler_consts = None
        # distributed lowering (ops/spmd_fusion.py): a MeshPlan makes
        # _compile wrap the step in shard_map over the plan's mesh (grad
        # psum + sharded update + all-reduced predicates fused in); the
        # first fire then runs under PROBATION (spmd_ok False → eager
        # results commit, fused-vs-eager compared; a divergence demotes the
        # program to the plain jit lowering)
        self.spmd_plan = None
        self.spmd_ok = True

    def release_heavy(self):
        """A deactivated program stays in the library as a tombstone (so
        the same cycle is not re-promoted just to fail again) but must not
        pin its compiled executable or trace shims. The op templates
        (chain) stay: already-fired pendings still lazily recompute
        through them."""
        self._exe = None
        self._shims = None

    # -- the fused executable ----------------------------------------------
    def _grad_transform(self, pvals, grads):
        """Regularization + grad clip exactly as Optimizer.step applies
        them, traced over param shims so the user's own clip/regularizer
        objects run unmodified."""
        reg = self.reg_ref
        clip = self.clip_ref
        if reg is None and clip is None:
            return grads
        shims = self._shims
        pgs = []
        for shim, pv, gv in zip(shims, pvals, grads):
            shim._value = pv
            g = Tensor(gv, stop_gradient=True)
            if reg is not None:
                g = reg.apply(shim, g)
            pgs.append((shim, g))
        if clip is not None:
            pgs = clip(pgs)
        return [g._value for _, g in pgs]

    def exe(self):
        if self._exe is not None:
            return self._exe
        from ..jit.train_step import donation_argnums
        from . import aot_cache as _aot
        if _aot.enabled() and self.aot_digest is not None:
            # warm start: deserialize the stored whole-step program (zero
            # fresh traces); a corrupt/mismatched artifact heals through
            # _compile transparently
            self._exe = _aot.load_step(
                self, self._compile,
                donation_argnums(self.donate_params, 0, 2))
            if self._exe is not None:
                return self._exe
        self._exe = self._compile()
        return self._exe

    def _compile(self):
        from ..jit.train_step import donation_argnums
        from . import guardian
        from . import spmd_fusion as _spmd
        plan = self.spmd_plan
        chain = self.chain
        pure = chain.pure_fn
        root = self.root_flat
        seed_shape, seed_dtype = chain.flat_avals[root][:2]
        param_slots = tuple(sorted(self.param_slots.items()))
        ext_order = self.ext_order
        n_ext = chain.n_ext
        # the closure holds the WEAKREF, not the optimizer: jit retains the
        # traced fn for the program's lifetime, and a strong capture would
        # pin the optimizer (and through _parameter_list the whole model)
        # even after the user discards both. The deref only runs at trace
        # time, when the firing hook has the optimizer live in hand.
        opt_ref = self.opt_ref
        acc_names = self.acc_names
        check = self.check
        scaler_consts = self.scaler_consts
        if self._shims is None:
            shims = []
            for nm, nc, pr in zip(self.param_names, self.need_clip,
                                  self.param_regs):
                s = _ParamShim()
                s.name = nm
                s.need_clip = nc
                s.regularizer = pr
                shims.append(s)
            self._shims = shims

        def step_body(pvals, ext, accs, lr, step_count, scaler_state):
            STEP_STATS.retraces += 1   # side effect: runs only while tracing
            full = [None] * n_ext
            for pos, slot in enumerate(ext_order):
                full[slot] = ext[pos]

            def fwd(pv):
                env = list(full)
                for slot, k in param_slots:
                    env[slot] = pv[k]
                return pure(*env)[root]

            # stored-sharded (ZeRO) params all-gather to full for the
            # forward; grads come back full so p.grad parity holds
            pvals_full = pvals if plan is None \
                else _spmd.gather_params(plan, pvals)
            root_val, vjp = jax.vjp(fwd, list(pvals_full))
            (grads,) = vjp(jnp.ones(seed_shape, seed_dtype))
            if plan is not None:
                # the gradient all-reduce + loss sync of the distributed
                # lowering (ops/spmd_fusion.py): every grad rides ONE
                # fused pmean region over the batch axes
                root_val, grads = _spmd.sync_root_and_grads(
                    plan, root_val, grads)
            finite_of = guardian.finite_all if plan is None \
                else (lambda vals: _spmd.global_finite(plan, vals))
            extras = ()
            if scaler_state is not None:
                # check_finite_and_unscale + update_loss_scaling, folded
                # in: grads leave the executable UNSCALED (exactly what
                # the eager path leaves in p.grad after scaler.step), and
                # the loss-scale transition is the same pure function the
                # eager GradScaler.update() evaluates. Under a mesh plan
                # found-inf is all-reduced, so the backoff is globally
                # consistent even when one shard saw the blowup.
                scale, good, bad = scaler_state
                inv = jnp.asarray(1.0, jnp.float32) / scale
                grads = [g * inv.astype(g.dtype) for g in grads]
                found_inf = jnp.logical_not(finite_of(grads))
                (_en, _dyn, incr_ratio, decr_ratio,
                 incr_n, decr_n) = scaler_consts
                scale2, good2, bad2 = guardian.update_scaler_state(
                    scale, good, bad, found_inf, incr_ratio, decr_ratio,
                    incr_n, decr_n)
                extras = (found_inf, scale2, good2, bad2)
            upd = self._grad_transform(pvals_full, grads)
            opt = opt_ref()   # trace-time only; firing keeps it alive
            new_p, new_accs = [], []
            for k, (pv, gv, ac) in enumerate(zip(pvals, upd, accs)):
                acc_dict = dict(zip(acc_names, ac))
                if plan is not None and plan.param_shard[k] is not None:
                    # ZeRO-sharded slots: slice-update-allgather
                    np_, na_ = _spmd.sharded_single_update(
                        plan, k, opt, pv, gv, acc_dict, lr, step_count)
                else:
                    np_, na_ = opt._single_update(pv, gv, acc_dict, lr,
                                                  step_count)
                new_p.append(np_)
                new_accs.append([na_.get(n) for n in acc_names])
            if check:
                # skip-step rescue: non-finite grads OR a non-finite
                # updated state make the whole update a bitwise no-op on
                # params AND optimizer slots — ONE fused scalar
                # predicate, zero extra launches. The new params/slots
                # are part of the predicate because finite grads can
                # still blow up the state (an LR spike overflowing
                # `p - lr*g`, a momentum buffer saturating): gating on
                # grads alone would wave the blowup through the gate.
                # Under a mesh plan the predicate is ALL-REDUCED first:
                # sharded slots make it device-varying, and every shard
                # must take the same skip/keep branch.
                new_state = list(new_p) + [v for row in new_accs
                                           for v in row if v is not None]
                upd_finite = finite_of(list(upd) + new_state)
                fwd_finite = finite_of([root_val])
                new_p = [jnp.where(upd_finite, nv, pv)
                         for nv, pv in zip(new_p, pvals)]
                new_accs = [
                    [None if nv is None else jnp.where(upd_finite, nv, ov)
                     for nv, ov in zip(row, ac)]
                    for row, ac in zip(new_accs, accs)]
                extras = (upd_finite, fwd_finite) + extras
            return (root_val, grads, new_p, new_accs) + extras

        if scaler_consts is not None:
            def step_fn(pvals, ext, accs, lr, step_count, scale, good, bad):
                return step_body(pvals, ext, accs, lr, step_count,
                                 (scale, good, bad))
        else:
            def step_fn(pvals, ext, accs, lr, step_count):
                return step_body(pvals, ext, accs, lr, step_count, None)

        donate = donation_argnums(self.donate_params, 0, 2)
        if plan is not None:
            # the distributed lowering: shard_map over the plan's mesh,
            # same outer signature and donation argnums as the plain path
            n_scaler = 3 if scaler_consts is not None else 0
            n_extras = (2 if check else 0) \
                + (4 if scaler_consts is not None else 0)
            self._exe = _spmd.compile_step(
                plan, step_fn, len(self.param_refs), n_scaler, n_extras,
                donate)
            return self._exe
        self._exe = jax.jit(step_fn, donate_argnums=donate)
        return self._exe


class _PendingStep:
    """A speculative whole-step replay in flight."""

    __slots__ = ("program", "owner", "entry_pos", "op_pos", "ext_vals",
                 "ext_edges", "placeholders", "params", "grad_phs",
                 "backward_done", "fired", "done", "lock", "t0")

    def __init__(self, program, params, owner):
        self.program = program
        self.owner = owner
        self.entry_pos = 0
        self.op_pos = 0
        self.ext_vals = []
        self.ext_edges = []
        self.placeholders = []
        self.params = params
        self.grad_phs = None
        self.backward_done = False
        self.fired = False
        self.done = False
        self.lock = threading.RLock()
        self.t0 = time.perf_counter_ns()


class _TLS(threading.local):
    def __init__(self):
        self.recording = None      # _Cycle or None
        self.prev_sig = None
        self.streak = 0
        self.library = OrderedDict()   # sig -> _StepProgram | _UNBUILDABLE
        self.active = None         # armed program
        self.replay_arm = False    # next cycle's first entry may start replay
        self.pending = None
        self.busy = False
        self.aot_probe = {}        # sig -> AOT step digest (or None)


class _StepFusionManager:
    """Cycle recorder + promotion + whole-step replay. All state is
    per-thread (a training loop is one thread); cross-thread escapes of
    pending placeholders resolve through the shared owner protocol of
    ops/fusion.py."""

    def __init__(self):
        self._tls = _TLS()

    # -- config ------------------------------------------------------------
    @staticmethod
    def enabled():
        return bool(_FLAGS.get("FLAGS_eager_step_fusion")) \
            and int(_FLAGS.get("FLAGS_eager_step_fusion_cache_size", 8)
                    or 0) > 0 \
            and bool(_FLAGS.get("FLAGS_eager_op_cache")) \
            and int(_FLAGS.get("FLAGS_eager_op_cache_size", 512) or 0) > 0

    # -- dispatch hooks ----------------------------------------------------
    def step(self, name, fn, inputs, num_outputs, key, diff_mask,
             bypass_reason=None):
        """First crack at every non-debug dispatch (before chain fusion).
        Returns deferred placeholders while a whole-step replay is
        matching, else MISS (the dispatcher proceeds and later feeds
        record()). `bypass_reason` attributes a key=None poison/split to
        the dispatch-level cause (rng_rekey, unkeyable_closure, ...)."""
        st = self._tls
        if st.busy:
            return MISS
        if not self.enabled():
            if st.pending is not None or st.recording is not None \
                    or st.active is not None:
                self._disable(st)
            return MISS
        arm = st.replay_arm
        st.replay_arm = False
        if key is None:
            # un-jittable/un-keyable op: the cycle cannot promote
            self._poison(st, bypass_reason or "unkeyable_closure", op=name)
            pending = st.pending
            if pending is not None and not pending.fired:
                with pending.lock:
                    if not pending.done:
                        self._split(pending, escape=False,
                                    reason=bypass_reason
                                    or "unkeyable_closure",
                                    blocked_op=name)
                st.pending = None
            return MISS

        pending = st.pending
        if pending is not None or (arm and st.active is not None):
            # replay matching is about to read input state: genuinely
            # foreign pendings (another thread's chain, a fired step) must
            # be resolved lock-free first, mirroring chain fusion. This
            # thread's own in-flight CHAIN pending is NOT foreign — the
            # chain manager handles it in its own step() — and while step
            # fusion merely observes, no pre-forcing happens at all.
            own_chain = _CHAIN_MANAGER._tls.pending
            for t in inputs:
                if _is_pending(t) and t._pending_chain is not st.pending \
                        and t._pending_chain is not own_chain:
                    t._pending_chain.owner.resolve_pending(
                        t._pending_chain, escape=True)
        if pending is not None and not pending.fired:
            program = pending.program
            with pending.lock:
                if pending.done:
                    st.pending = None
                else:
                    entry = program.entries[pending.entry_pos]
                    if entry[0] != "op":
                        self._split(pending, escape=False,
                                    reason="event_mismatch", blocked_op=name)
                        return MISS
                    mismatch = self._op_mismatch_reason(
                        program, pending, key, inputs, diff_mask,
                        num_outputs)
                    if mismatch is None:
                        return self._defer(st, pending, inputs, num_outputs)
                    self._split(pending, escape=False, reason=mismatch,
                                blocked_op=name)
            return MISS
        if arm and st.active is not None:
            program = st.active
            if program.entries and program.entries[0][0] == "op":
                pending = self._start_pending(st, program)
                if pending is not None:
                    with pending.lock:
                        mismatch = self._op_mismatch_reason(
                            program, pending, key, inputs, diff_mask,
                            num_outputs)
                        if mismatch is None:
                            return self._defer(st, pending, inputs,
                                               num_outputs)
                        self._split(pending, escape=False, reason=mismatch,
                                    blocked_op=name)
        return MISS

    def record(self, name, fn, inputs, num_outputs, key, diff_mask, outs,
               cached_ok, bypass_reason=None):
        """Feed the cycle recorder after a dispatch ran (per-op cached,
        per-op uncached, or deferred into a chain replay)."""
        st = self._tls
        if st.busy or not self.enabled():
            return
        cyc = st.recording
        if cyc is None:
            cyc = st.recording = _Cycle()
        if cyc.dirty:
            return
        if key is None or not cached_ok or len(cyc.ops) >= _MAX_CYCLE_OPS:
            if key is None:
                reason = bypass_reason or "unkeyable_closure"
            elif not cached_ok:
                reason = "uncached_dispatch"
            else:
                reason = "cycle_too_long"
            self._poison(st, reason, op=name)
            return
        wiring = tuple(
            ("prev",) + cyc.produced[id(t)] if id(t) in cyc.produced
            else ("ext",)
            for t in inputs)
        try:
            out_avals = tuple(_out_aval(t) for t in outs)
        except Exception:
            self._poison(st, "tracer_input", op=name)
            return
        cyc.entries.append(("op", key, wiring, diff_mask, num_outputs))
        cyc.ops.append(_OpRec(
            name, key, fn, wiring, diff_mask, num_outputs, out_avals,
            tuple(t.stop_gradient for t in outs), tuple(inputs),
            tuple(outs)))
        i = len(cyc.ops) - 1
        for j, t in enumerate(outs):
            cyc.produced[id(t)] = (i, j)

    def interrupt(self):
        """Debug mode (NaN scan / benchmark sync) needs per-op results:
        resolve any pending replay and poison the cycle."""
        st = self._tls
        if st.busy:
            return
        if st.pending is not None and not st.pending.fired:
            with st.pending.lock:
                if not st.pending.done:
                    self._split(st.pending, escape=False,
                                reason="debug_interrupt")
            st.pending = None
        self._poison(st, "debug_interrupt")

    # -- backward / optimizer hooks ----------------------------------------
    def on_backward(self, tensor, grad_tensor, retain_graph):
        """Called at the top of Tensor.backward. Returns True when the
        backward was consumed by a pending whole-step replay (the caller
        must return immediately)."""
        st = self._tls
        if st.busy or not self.enabled():
            return False
        st.replay_arm = False
        pending = st.pending
        if pending is not None and not pending.fired:
            program = pending.program
            with pending.lock:
                if pending.done:
                    st.pending = None
                    return False
                entry = program.entries[pending.entry_pos]
                if entry[0] == "bwd" and grad_tensor is None \
                        and not retain_graph \
                        and not _autograd._saved_tensor_hooks \
                        and self._is_root(pending, tensor) \
                        and all(p.grad is None and not p._hooks
                                for p in pending.params):
                    pending.entry_pos += 1
                    pending.backward_done = True
                    self._install_grad_placeholders(pending)
                    return True
                if entry[0] != "bwd" or not self._is_root(pending, tensor):
                    reason = "event_mismatch"
                else:
                    # retain_graph / explicit grad seed / saved-tensor or
                    # param hooks / stale grads: semantics a fused replay
                    # cannot honor
                    reason = "hook_present"
                self._split(pending, escape=False, reason=reason,
                            blocked_op="backward")
            return False
        # observation
        cyc = st.recording
        if cyc is None:
            cyc = st.recording = _Cycle()
        if cyc.dirty:
            return False
        cyc.n_backward += 1
        coord = cyc.produced.get(id(tensor))
        if coord is None or grad_tensor is not None or retain_graph \
                or _autograd._saved_tensor_hooks or cyc.n_backward > 1:
            if cyc.n_backward > 1:
                reason = "multi_backward"
            elif coord is None:
                reason = "event_mismatch"   # root not in the recorded cycle
            else:
                reason = "hook_present"
            self._poison(st, reason, op="backward")
            return False
        cyc.entries.append(("bwd", coord))
        _EVENTS.emit("step.record", "backward",
                     detail={"kind": "bwd", "pos": len(cyc.ops)})
        return False

    def on_clear_grad(self, opt):
        """Called at the top of Optimizer.clear_grad; the caller always
        proceeds to clear the grads."""
        st = self._tls
        if st.busy or not self.enabled():
            return
        arm = st.replay_arm
        st.replay_arm = False
        pending = st.pending
        if pending is not None and not pending.fired:
            program = pending.program
            with pending.lock:
                if pending.done:
                    st.pending = None
                else:
                    entry = program.entries[pending.entry_pos]
                    if entry[0] == "cg" and opt is program.opt_ref():
                        pending.entry_pos += 1
                    else:
                        self._split(pending, escape=False,
                                    reason="event_mismatch",
                                    blocked_op="clear_grad")
            return
        if arm and st.active is not None:
            program = st.active
            if program.entries and program.entries[0][0] == "cg" \
                    and opt is program.opt_ref():
                pending = self._start_pending(st, program)
                if pending is not None:
                    pending.entry_pos = 1
                    return
        cyc = st.recording
        if cyc is None:
            cyc = st.recording = _Cycle()
        if not cyc.dirty:
            cyc.entries.append(("cg", id(opt)))

    def on_optimizer_step(self, opt):
        """Called at the top of Optimizer.step. Returns True when the
        fused executable performed the whole update (the caller must
        return immediately); always delimits the observation cycle."""
        st = self._tls
        if st.busy or not self.enabled():
            return False
        st.replay_arm = False
        pending = st.pending
        if pending is not None and not pending.fired:
            program = pending.program
            with pending.lock:
                if pending.done:
                    st.pending = None
                else:
                    entry = program.entries[pending.entry_pos]
                    split_reason = "event_mismatch"
                    if entry[0] == "step" \
                            and pending.entry_pos \
                            == len(program.entries) - 1 \
                            and pending.backward_done \
                            and pending.op_pos == len(program.chain.ops):
                        verify_fail = self._verify_fire(program, pending,
                                                        opt)
                        if verify_fail is None:
                            if program.spmd_plan is not None \
                                    and not program.spmd_ok:
                                # SPMD probation: this step commits EAGER
                                # results (the caller proceeds); the fused
                                # lowering is validated on the side
                                self._probation(st, pending, opt)
                                st.pending = None
                                self._after_boundary(st)
                                return False
                            if self._fire(st, pending, opt):
                                self._after_boundary(st)
                                return True
                            split_reason = None   # _fire already split
                        else:
                            split_reason = verify_fail
                    if not pending.done and split_reason is not None:
                        self._split(pending, escape=False,
                                    reason=split_reason,
                                    blocked_op="optimizer_step")
                    elif not pending.done:
                        self._split(pending, escape=False,
                                    reason="exec_fault",
                                    blocked_op="optimizer_step")
            st.pending = None
            self._boundary(st, opt, dirty=True)
            return False
        self._boundary(st, opt, dirty=False)
        return False

    def on_scaler_step(self, scaler, opt):
        """Called at the top of GradScaler.step (an ENABLED scaler), before
        its eager unscale/step path. Returns True when a pending
        whole-step replay matched through the scaler event and the ONE
        fused executable performed unscale + finite-check + the
        where()-rescued update + the loss-scale transition (the caller
        must skip its eager path and let update() commit the transition).
        During observation it records the scaler into the cycle — only
        under the guardian (FLAGS_check_numerics), whose in-graph
        skip-step semantics make the fold legal — and returns False."""
        from . import guardian
        st = self._tls
        if st.busy or not self.enabled():
            return False
        st.replay_arm = False
        pending = st.pending
        if pending is not None and not pending.fired:
            program = pending.program
            fired = False
            with pending.lock:
                if pending.done:
                    st.pending = None
                    return False
                entry = program.entries[pending.entry_pos]
                if entry[0] != "scaler":
                    # the program was recorded without this scaler (legacy
                    # mode / changed loop): let the eager path run — its
                    # grad reads split the replay as mid_step_peek
                    return False
                split_reason = "event_mismatch"
                if program.scaler_ref() is not scaler \
                        or scaler._consts() != program.scaler_consts:
                    # the scale hyper-parameters are baked into the traced
                    # loss-scale transition: a change is stale for good
                    self._kill(program)
                    split_reason = "optimizer_state_change"
                elif pending.entry_pos == len(program.entries) - 2 \
                        and pending.backward_done \
                        and pending.op_pos == len(program.chain.ops):
                    pending.entry_pos += 1
                    verify_fail = self._verify_fire(program, pending, opt)
                    if verify_fail is None:
                        if program.spmd_plan is not None \
                                and not program.spmd_ok:
                            # SPMD probation: eager scaler path proceeds
                            self._probation(st, pending, opt,
                                            scaler=scaler)
                            st.pending = None
                            self._after_boundary(st)
                            return False
                        if self._fire(st, pending, opt, scaler=scaler):
                            fired = True
                            self._after_boundary(st)
                        else:
                            split_reason = None   # _fire already split
                    else:
                        split_reason = verify_fail
                if not fired and not pending.done \
                        and split_reason is not None:
                    self._split(pending, escape=False, reason=split_reason,
                                blocked_op="scaler_step")
            if fired:
                return True
            st.pending = None
            self._boundary(st, opt, dirty=True)
            return False
        # observation: the scaler joins the cycle signature so _build folds
        # it into the fused step (guardian mode only — without the in-graph
        # skip the eager scaler syncs found_inf per step and cannot fuse)
        if guardian.skip_step_enabled():
            cyc = st.recording
            if cyc is None:
                cyc = st.recording = _Cycle()
            if not cyc.dirty:
                cyc.entries.append(("scaler", id(scaler), scaler._consts()))
                cyc.scaler = scaler
        return False

    # -- replay internals --------------------------------------------------
    @staticmethod
    def _is_root(pending, tensor):
        i, j = pending.program.root_coord
        try:
            return pending.placeholders[i][j] is tensor
        except IndexError:
            return False

    def _start_pending(self, st, program):
        if program.dead:
            st.active = None
            return None
        params = [r() for r in program.param_refs]
        if any(p is None for p in params):
            program.dead = True
            _EVENTS.emit("step.deactivate", program.label,
                         reason="param_mismatch",
                         detail={"why": "parameter_gc"})
            st.active = None
            return None
        # the chain layer must not be mid-replay under a step replay
        _CHAIN_MANAGER.flush()
        _CHAIN_MANAGER.reset()
        pending = _PendingStep(program, params, self)
        st.pending = pending
        return pending

    def _op_mismatch_reason(self, program, pending, key, inputs, diff_mask,
                            num_outputs):
        """None when the incoming dispatch matches the program's next op
        template; else the reason code the split should carry."""
        op = program.chain.ops[pending.op_pos]
        if key != op.key:
            return _key_diff_reason(op.key, key)
        if diff_mask != op.diff_mask or num_outputs != op.num_outputs \
                or len(inputs) != len(op.wiring):
            return "key_mismatch"
        slots = program.chain.ext_of[pending.op_pos]
        for k, (t, w) in enumerate(zip(inputs, op.wiring)):
            if _is_pending(t) and t._pending_chain is pending:
                if w[0] != "prev" or t._chain_coord != (w[1], w[2]):
                    return "wiring_mismatch"
            elif w[0] != "ext":
                return "wiring_mismatch"
            else:
                pk = program.param_slots.get(slots[k])
                if pk is not None and t is not pending.params[pk]:
                    # the slot must be fed by the SAME parameter object the
                    # program was built against — identity is the binding
                    return "param_mismatch"
        return None

    def _defer(self, st, pending, inputs, num_outputs):
        program = pending.program
        op = program.chain.ops[pending.op_pos]
        for k, t in enumerate(inputs):
            if op.wiring[k][0] != "ext":
                continue
            pending.ext_vals.append(t._value)
            if op.diff_mask is not None and op.diff_mask[k]:
                node = t._grad_node if t._grad_node is not None \
                    else t._ensure_grad_node()
                pending.ext_edges.append((node, t._out_index))
            else:
                pending.ext_edges.append(None)
        outs = tuple(
            _DeferredTensor(av, op.out_stop_grads[j], pending,
                            (pending.op_pos, j))
            for j, av in enumerate(op.out_avals))
        pending.placeholders.append(outs)
        pending.op_pos += 1
        pending.entry_pos += 1
        if num_outputs is not None:
            return list(outs)
        return outs[0]

    def _install_grad_placeholders(self, pending):
        program = pending.program
        phs = []
        for k, p in enumerate(pending.params):
            v = p._value
            ph = _DeferredTensor((v.shape, v.dtype, False), True, pending,
                                 ("grad", k))
            ph.name = (p.name + "@GRAD") if p.name else "grad"
            p.grad = ph
            phs.append(ph)
        pending.grad_phs = phs

    def _verify_fire(self, program, pending, opt):
        """None when the fused fire may proceed; else the reason code the
        split should carry (optimizer-state changes also kill the
        program: the baked constants are stale for good)."""
        from ..jit.train_step import bake_decay_flags
        if opt is not program.opt_ref():
            return "param_mismatch"
        params = pending.params
        if program.spmd_plan is not None:
            from . import spmd_fusion as _spmd
            mm = _spmd.fire_mismatch(program.spmd_plan, pending.ext_vals,
                                     params)
            if mm is not None:
                # the batch moved to another mesh/layout (or a parameter
                # got sharded): the compiled collectives would run over
                # the wrong axes — kill and let the loop re-promote with
                # a fresh plan
                self._kill(program, reason="mesh_mismatch")
                return "mesh_mismatch"
        slot_items = program.param_slots.items()
        if any(pending.ext_vals[s] is not params[k]._value
               for s, k in slot_items):
            # a parameter buffer was swapped mid-cycle (in-place mutation):
            # the forward consumed the captured value, the update would use
            # the new one — not fusable
            return "param_mismatch"
        for p, nm, nc, pr in zip(params, program.param_names,
                                 program.need_clip, program.param_regs):
            if p._hooks:
                return "hook_present"
            if p.stop_gradient or p.name != nm:
                return "param_mismatch"
            if getattr(p, "need_clip", True) != nc:
                return "optimizer_state_change"
            if getattr(p, "regularizer", None) is not pr:
                return "optimizer_state_change"
            node = p._grad_node
            if node is not None and node.out_hooks:
                return "hook_present"
        own = {id(p) for p in params}
        for p in opt._parameter_list:
            if id(p) not in own and p.grad is not None:
                # an outside gradient would be updated by the eager step
                # but not by the fused one
                return "param_mismatch"
        if opt._grad_clip is not program.clip_ref \
                or _snapshot_obj(opt._grad_clip) != program.clip_snapshot:
            self._kill(program)
            return "optimizer_state_change"
        if opt.regularization is not program.reg_ref \
                or _snapshot_obj(opt.regularization) != program.reg_snapshot:
            self._kill(program)
            return "optimizer_state_change"
        bake_decay_flags(opt, params)
        if tuple(opt._extra_cache_key()) != program.extra_key:
            self._kill(program)
            return "optimizer_state_change"
        opt._create_accumulators(params)
        if tuple(sorted(opt._accumulators.keys())) != program.acc_names:
            self._kill(program)
            return "optimizer_state_change"
        return None

    def _kill(self, program, reason="optimizer_state_change"):
        """A baked-in constant (clip/regularizer attrs, optimizer hyper
        params, accumulator structure) changed: the compiled executable is
        stale for good. Drop it so a re-stabilized loop rebuilds."""
        st = self._tls
        if not program.dead:
            program.dead = True
            program.release_heavy()
            STEP_STATS.deactivated += 1
            _EVENTS.emit("step.deactivate", program.label, reason=reason)
        if st.active is program:
            st.active = None
        st.library.pop(program.sig, None)

    def _fire(self, st, pending, opt, scaler=None):
        """All entries matched and the optimizer is verified: run the ONE
        fused executable and commit. Returns False (after splitting) on a
        fault so the caller falls back to the eager step. `scaler` is the
        verified GradScaler of a scaler-folded program (on_scaler_step):
        its state rides as hoisted scalar args and the computed transition
        lands in `scaler._fused_next` for update() to commit."""
        from ..jit.train_step import bake_decay_flags
        from . import guardian as _guardian
        program = pending.program
        params = pending.params
        acc_names = program.acc_names
        check = program.check
        upd_finite = fwd_finite = scale_before = scale_after = None
        if _guardian.faults_armed() and _guardian.poll_fault(
                "fused_step", ("raise", "nan_output")) is not None:
            # fused-tier chaos: ANY untrusted fused-step output means the
            # whole transaction is suspect — recover through the
            # transactional per-op split (bitwise-identical params/grads),
            # exactly the path a real mid-fire fault takes
            self._split(pending, escape=False, reason="injected_fault",
                        blocked_op="chaos")
            return False
        st.busy = True
        if not hasattr(opt, "_step_count"):
            opt._step_count = 0
        opt._step_count += 1
        try:
            bake_decay_flags(opt, params)
            pvals = [p._value for p in params]
            ext = [pending.ext_vals[s] for s in program.ext_order]
            accs = [[opt._accumulators[n].get(p.name) for n in acc_names]
                    for p in params]
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            step_count = jnp.asarray(opt._step_count, jnp.int32)
            if scaler is not None:
                scale_before, good, bad = scaler._state_arrays()
                fire_args = (pvals, ext, accs, lr, step_count,
                             scale_before, good, bad)
                (root_val, grads, new_p, new_accs, upd_finite, fwd_finite,
                 found_inf, scale_after, good2, bad2) = \
                    program.exe()(*fire_args)
            elif check:
                fire_args = (pvals, ext, accs, lr, step_count)
                (root_val, grads, new_p, new_accs, upd_finite,
                 fwd_finite) = program.exe()(*fire_args)
            else:
                fire_args = (pvals, ext, accs, lr, step_count)
                root_val, grads, new_p, new_accs = program.exe()(
                    *fire_args)
        except jax.errors.JaxRuntimeError:
            # transient execution fault: keep the program and replay
            # eagerly — UNLESS the launch already consumed the donated
            # accumulator (or param) buffers, in which case a transparent
            # fallback is impossible and the fault must surface (the
            # eager optimizer's own donating update has the same contract)
            opt._step_count -= 1
            consumed = any(
                getattr(a, "is_deleted", lambda: False)()
                for row in accs for a in row if a is not None)
            if program.donate_params and not consumed:
                consumed = any(
                    getattr(v, "is_deleted", lambda: False)()
                    for v in pvals)
            if consumed:
                st.busy = False
                st.pending = None   # placeholders resolve via escape-split
                self._kill(program, reason="exec_fault")
                raise
            st.busy = False
            self._split(pending, escape=False, reason="exec_fault")
            return False
        except Exception:
            # the fused trace failed: never let fusion take eager down
            opt._step_count -= 1
            st.busy = False
            self._kill(program, reason="trace_fail")
            self._split(pending, escape=False, reason="trace_fail")
            return False
        try:
            for p, v in zip(params, new_p):
                p._value = v
            for p, ac in zip(params, new_accs):
                for n, v in zip(acc_names, ac):
                    if v is not None:
                        opt._accumulators[n][p.name] = v
            # the loss: served from the fused outputs, tape-marked consumed
            i, j = program.root_coord
            root_ph = pending.placeholders[i][j]
            if _VALUE_SLOT.__get__(root_ph) is _PENDING:
                _VALUE_SLOT.__set__(root_ph, root_val)
            node = FusedStepNode(program.label,
                                 (root_val.shape, root_val.dtype))
            _NODE_SLOT.__set__(root_ph, node)
            _IDX_SLOT.__set__(root_ph, 0)
            root_ph._pending_chain = None
            # raw grads land in the placeholders installed at backward
            # (scaler programs emit them UNSCALED, like the eager path)
            for ph, g in zip(pending.grad_phs, grads):
                if _VALUE_SLOT.__get__(ph) is _PENDING:
                    _VALUE_SLOT.__set__(ph, g)
                ph._pending_chain = None
            if scaler is not None:
                # update() commits this instead of re-running the
                # transition (the backoff, if any, is attributed by the
                # note_step flush below — never twice)
                scaler._found_inf = found_inf
                scaler._fused_next = (found_inf, scale_after, good2, bad2)
            if check:
                from . import guardian
                guardian.note_step(program.label, upd_finite, fwd_finite,
                                   scale_before, scale_after,
                                   step_index=opt._step_count)
            pending.fired = True
            program.fail_streak = 0
            if not program.aot_stored:
                from . import aot_cache as _aot
                if _aot.enabled():
                    # persist the ONE fused step right after it proved
                    # itself (store-if-absent; restored programs and
                    # donated-buffer shapes are both handled there)
                    program.aot_stored = True
                    _aot.store_step(program, fire_args)
            elapsed = time.perf_counter_ns() - pending.t0
            STEP_STATS.replay(program.label, program.n_launches,
                              program.baseline_ns - elapsed)
            # telemetry plane (profiler/goodput.py): per-mesh SPMD step
            # labeling + cycle-derived analytic FLOPs/step; one flag
            # check when FLAGS_metrics is off
            from ..profiler import goodput as _goodput
            _goodput.on_fused_fire(program)
            _EVENTS.emit("step.fire", program.label,
                         detail={"ops": len(program.chain.ops),
                                 "launches_saved": program.n_launches - 1})
            self._demote(pending)
        finally:
            st.busy = False
            st.pending = None
        return True

    @staticmethod
    def _demote(pending):
        """Release the fired step's retention (ROADMAP item 4(c)): swap
        the placeholder store to weakrefs, breaking the strong
        pending↔placeholder cycle that used to keep `ext_vals` — the
        PRE-UPDATE parameter buffers and the batch arrays among them —
        alive into the next step (until a gc pass, in the worst case).
        Post-demote the pending survives only through placeholders the
        CALLER still references (each holds `_pending_chain` strongly),
        so in the common loop — where mid-step intermediates are
        temporaries — everything, ext store included, is refcount-freed
        before `optimizer.step()` returns. A caller that kept an
        intermediate keeps exactly the state its post-fire lazy
        recompute needs, no more."""
        pending.placeholders = [[weakref.ref(t) for t in row]
                                for row in pending.placeholders]
        # grads were committed to p.grad and the loss to its own handle;
        # the pending's strong duplicates would pin those buffers past
        # clear_grad()
        pending.grad_phs = None
        pending.params = ()

    def _probation(self, st, pending, opt, scaler=None):
        """First fire of an SPMD-lowered program (ops/spmd_fusion.py): run
        the shard_map executable on scratch copies of the donated buffers,
        then replay the step EAGERLY through the transactional core — this
        step's numerics stay bitwise-identical to unfused dispatch — and
        compare loss + grads. A match validates the distributed lowering
        (the next fire commits fused results); a divergence (a sum-reduced
        loss, a batch-coupled op — anything outside the data-parallel
        pmean contract) demotes the program to the plain jit lowering,
        attributed as `spmd_divergence`. Callers hold pending.lock; the
        caller must let the eager optimizer step proceed."""
        import numpy as np
        from ..jit.train_step import bake_decay_flags
        from ..profiler import goodput as _goodput
        from . import spmd_fusion as _spmd
        # goodput: this interval is a probation replay (fused + bitwise
        # eager both run), not a normal productive step
        _goodput.mark("probation")

        def scratch(v):
            # a DISTINCT buffer with the same value and placement, so the
            # executable's donation can never consume live state
            return v + jnp.zeros((), v.dtype)

        program = pending.program
        params = pending.params
        acc_names = program.acc_names
        fused = None
        st.busy = True
        try:
            bake_decay_flags(opt, params)
            pvals = [p._value for p in params]
            if program.donate_params:
                pvals = [scratch(v) for v in pvals]
            ext = [pending.ext_vals[s] for s in program.ext_order]
            accs = [[None if opt._accumulators[n].get(p.name) is None
                     else scratch(opt._accumulators[n][p.name])
                     for n in acc_names] for p in params]
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            step_count = jnp.asarray(
                getattr(opt, "_step_count", 0) + 1, jnp.int32)
            if scaler is not None:
                scale, good, bad = scaler._state_arrays()
                fused = program.exe()(pvals, ext, accs, lr, step_count,
                                      scratch(scale), scratch(good),
                                      scratch(bad))
            else:
                fused = program.exe()(pvals, ext, accs, lr, step_count)
        except Exception:
            # the distributed lowering failed to trace/execute (a baked
            # global shape, an op the manual mapping rejects): demote to
            # the plain jit lowering — still ONE executable — and replay
            # this step eagerly
            fused = None
        finally:
            st.busy = False
        self._replay_pending(pending)
        ok = fused is not None
        why = "trace_fail" if fused is None else None
        if ok:
            i, j = program.root_coord
            root_ph = pending.placeholders[i][j]
            eager_loss = np.asarray(_VALUE_SLOT.__get__(root_ph))
            rtol, atol = _spmd.probation_tolerance(eager_loss.dtype)
            ok = bool(np.allclose(np.asarray(fused[0]), eager_loss,
                                  rtol=rtol, atol=atol, equal_nan=True))
            scale_np = None
            if ok and scaler is not None:
                # fused grads are UNSCALED; the eager tape's (pre-
                # scaler.step) grads still carry the loss scale
                scale_np = np.asarray(scaler._state_arrays()[0])
            if ok:
                for ph, g in zip(pending.grad_phs, fused[1]):
                    ev = _VALUE_SLOT.__get__(ph)
                    if ev is _PENDING:
                        continue
                    ev = np.asarray(ev)
                    gv = np.asarray(g)
                    if scale_np is not None:
                        gv = gv * scale_np.astype(gv.dtype)
                    rt, at = _spmd.probation_tolerance(ev.dtype)
                    if not np.allclose(gv, ev, rtol=rt, atol=at,
                                       equal_nan=True):
                        ok = False
                        break
            if not ok and why is None:
                why = "numeric_divergence"
        if ok:
            program.spmd_ok = True
            _EVENTS.emit("step.record", program.label,
                         detail={"kind": "spmd_probation", "ok": True})
        else:
            program.spmd_plan = None
            program.spmd_ok = True
            program._exe = None
            _EVENTS.emit("step.record", program.label,
                         reason="spmd_divergence",
                         detail={"kind": "spmd_probation", "ok": False,
                                 "why": why})

    def resolve_pending(self, pending, escape):
        """Owner-protocol escape hatch (ops/fusion._DeferredTensor._force).
        Pre-fire: any touch of a pending placeholder splits the replay.
        Post-fire: intermediates are lazily recomputed through the per-op
        path (the fused step only materialized the loss and the grads)."""
        st = self._tls
        with pending.lock:
            if pending.done:
                pass
            elif pending.fired:
                self._recompute(pending)
            else:
                self._split(pending, escape=escape)
        if st.pending is pending:
            st.pending = None

    def _recompute(self, pending):
        """A placeholder of a FIRED step was read: materialize every
        intermediate via the per-op cached path from the captured external
        inputs (the pre-update parameter values among them). The store
        was demoted to weakrefs at the fire (`_demote`); the reader that
        triggered this keeps its own chain of placeholders alive, and
        rows that died anyway are replayed through throwaway carriers —
        their values exist only long enough to feed downstream ops."""
        st = self._tls
        st.busy = True
        try:
            rows = []
            for row in pending.placeholders:
                live = []
                for ref in row:
                    t = ref()
                    if t is None:
                        t = _DeferredTensor(None, True, None, None)
                    live.append(t)
                rows.append(live)
            replay_ops_per_op(pending.program.chain.ops, pending.ext_vals,
                              pending.ext_edges, rows,
                              pending.op_pos, skip_materialized=True)
            pending.done = True
        finally:
            st.busy = False

    def _replay_pending(self, pending):
        """The bitwise transactional core: replay the deferred prefix
        per-op and, if the backward event was already consumed, run the
        real tape backward so p.grad holds exactly what unfused dispatch
        would have produced. Shared by `_split` (failure fallback) and
        `_probation` (the SPMD first-fire validation, which is not a
        failure). Callers hold pending.lock."""
        st = self._tls
        program = pending.program
        st.busy = True
        try:
            replay_ops_per_op(program.chain.ops, pending.ext_vals,
                              pending.ext_edges, pending.placeholders,
                              pending.op_pos)
            if pending.backward_done:
                for p in pending.params:
                    p.grad = None
                i, j = program.root_coord
                root = pending.placeholders[i][j]
                node = _NODE_SLOT.__get__(root)
                if node is not None:
                    seed = _autograd._one_cotangent(
                        _VALUE_SLOT.__get__(root).shape,
                        _VALUE_SLOT.__get__(root).dtype)
                    run_backward(node, _IDX_SLOT.__get__(root), seed)
                for p, ph in zip(pending.params, pending.grad_phs):
                    real = p.grad
                    if real is not None:
                        if _VALUE_SLOT.__get__(ph) is _PENDING:
                            _VALUE_SLOT.__set__(ph, real._value)
                        ph._pending_chain = None
                        p.grad = ph
                    else:
                        ph._pending_chain = None
            pending.done = True
        finally:
            st.busy = False

    def _split(self, pending, escape, reason=None, blocked_op=None):
        """Transactional fallback: the deferred prefix replays per-op; if
        the backward event was already consumed, the real tape backward
        runs so p.grad holds exactly what unfused dispatch would have
        produced. Callers hold pending.lock. `reason` is the
        flight-recorder attribution (a REASON_CODES entry); `blocked_op`
        names the dispatch/event that broke the replay."""
        st = self._tls
        program = pending.program
        if pending.done:
            return
        try:
            self._replay_pending(pending)
            program.fail_streak += 1
            deactivated = False
            if program.fail_streak >= _MAX_FAIL_STREAK \
                    and not program.dead:
                program.dead = True
                deactivated = True
                program.release_heavy()
                STEP_STATS.deactivated += 1
                if st.active is program:
                    st.active = None
            STEP_STATS.split(program.label, escape=escape)
            if reason is None:
                reason = "mid_step_peek" if escape else "key_mismatch"
            detail = {"entry_pos": pending.entry_pos,
                      "op_pos": pending.op_pos,
                      "ops": len(program.chain.ops)}
            if blocked_op:
                detail["blocked_op"] = blocked_op
            if deactivated:
                detail["deactivated"] = True
            _EVENTS.emit("step.split", program.label, reason=reason,
                         detail=detail)
            if deactivated:
                _EVENTS.emit("step.deactivate", program.label,
                             reason="fail_streak")
            self._mark_dirty(st)
        finally:
            if st.pending is pending:
                st.pending = None

    # -- cycle boundary / promotion ----------------------------------------
    def _mark_dirty(self, st):
        if st.recording is None:
            st.recording = _Cycle()
        st.recording.poison()

    def _poison(self, st, reason, op=""):
        """Mark the observation cycle un-promotable AND record why in the
        flight recorder. The (reason, op) pairs emitted here are exactly
        what the fusion doctor aggregates into "step never promoted:
        <op> <reason> ×N" — every poison call emits (not just the first
        of a cycle) so per-cycle multiplicity survives into the report."""
        if st.recording is None:
            st.recording = _Cycle()
        cyc = st.recording
        _EVENTS.emit("step.record", op, reason=reason,
                     detail={"kind": "poison", "pos": len(cyc.ops),
                             "first": not cyc.dirty})
        cyc.poison()

    def _after_boundary(self, st):
        st.recording = _Cycle()
        st.replay_arm = st.active is not None

    def _boundary(self, st, opt, dirty):
        cyc = st.recording
        if cyc is None or dirty or cyc.dirty:
            _EVENTS.emit("step.record", "optimizer_step",
                         detail={"kind": "cycle", "clean": False})
            st.prev_sig, st.streak = None, 0
            self._after_boundary(st)
            return
        updated = [p for p in opt._parameter_list if p.grad is not None]
        cyc.entries.append(("step", id(opt), tuple(id(p) for p in updated)))
        sig = tuple(cyc.entries)
        if sig == st.prev_sig:
            st.streak += 1
        else:
            st.prev_sig, st.streak = sig, 1
        _EVENTS.emit("step.record", "optimizer_step",
                     detail={"kind": "cycle", "clean": True,
                             "ops": len(cyc.ops), "streak": st.streak})
        min_count = int(
            _FLAGS.get("FLAGS_eager_step_fusion_min_count", 40) or 1)
        promote = st.streak >= min_count
        warm = False
        if not promote and sig not in st.library:
            # AOT warm start (ops/aot_cache.py): when the store already
            # holds this cycle's compiled step, the stability threshold is
            # moot — a restarting worker promotes on its FIRST clean cycle
            # and fires the restored executable on the next one
            warm = self._aot_step_digest(st, sig, opt, updated) is not None
            promote = warm
        if promote:
            program = st.library.get(sig)
            if program is None and sig not in st.library:
                program = self._build(st, cyc, sig, opt, updated,
                                      warm=warm)
                st.library[sig] = program if program is not None \
                    else _UNBUILDABLE
                cap = int(_FLAGS.get("FLAGS_eager_step_fusion_cache_size",
                                     8) or 0)
                while len(st.library) > max(cap, 1):
                    st.library.popitem(last=False)
            if isinstance(program, _StepProgram) and not program.dead:
                st.library.move_to_end(sig)
                st.active = program
        self._after_boundary(st)

    def _aot_step_digest(self, st, sig, opt, updated):
        """The warm-start probe: this cycle's AOT step digest when the
        store holds a matching artifact, else None. The digest computation
        (canonicalizing every op key) is memoized per sig; the existence
        check re-runs each boundary — another worker may populate the
        shared store at any time."""
        from . import aot_cache as _aot
        if not _aot.enabled():
            return None
        dg = st.aot_probe.get(sig, 0)
        if dg == 0:
            dg = _aot.step_digest(sig, opt, updated)
            if len(st.aot_probe) > 64:
                st.aot_probe.clear()
            st.aot_probe[sig] = dg
        if dg is not None and _aot.has_step(dg):
            return dg
        return None

    def _build(self, st, cyc, sig, opt, updated, warm=False):
        """Compile-time qualification + program construction from the last
        observed cycle. Returns None when the cycle cannot promote — every
        None is attributed in the flight recorder (`unpromotable_cycle`
        with a `why` detail) so a loop that records clean cycles but never
        promotes still explains itself."""
        from ..jit.train_step import bake_decay_flags

        def unbuildable(why, op=""):
            _EVENTS.emit("step.record", op, reason="unpromotable_cycle",
                         detail={"kind": "build_fail", "why": why})
            return None

        entries = []
        bwd_entries = [e for e in cyc.entries if e[0] == "bwd"]
        if len(bwd_entries) != 1 or bwd_entries[0][1] is None \
                or not cyc.ops or not updated:
            return unbuildable("no_backward_or_params")
        if any(p._hooks or p.stop_gradient for p in updated):
            return unbuildable("param_hooks")
        for p in updated:
            node = p._grad_node
            if node is not None and node.out_hooks:
                return unbuildable("param_hooks")
        ops = [
            _ChainOp(r.name, r.key, r.fn, r.wiring, r.diff_mask,
                     r.num_outputs, r.out_avals, r.out_stop_grads)
            for r in cyc.ops]
        chain = Chain(sig, ops, 0)
        if not chain.grad_mode:
            return unbuildable("no_grad_ops")
        # GradScaler folding (on_scaler_step): requires the guardian —
        # the in-graph where() skip is what makes an unconditional fused
        # update legal — and the scaler event must follow the backward
        # (unscale consumes its grads)
        scaler_es = [e for e in cyc.entries if e[0] == "scaler"]
        scaler_obj = cyc.scaler
        if len(scaler_es) > 1:
            return unbuildable("multi_scaler")
        if scaler_es:
            if scaler_obj is None or id(scaler_obj) != scaler_es[0][1]:
                return unbuildable("scaler_gone")
            if not chain.check:
                return unbuildable("scaler_without_guardian")
            order = [e[0] for e in cyc.entries]
            if order.index("scaler") < order.index("bwd"):
                return unbuildable("scaler_before_backward")
        else:
            scaler_obj = None
        # flat index of the backward root in the chain's output catalog
        root_coord = bwd_entries[0][1]
        root_flat = None
        for flat, owner in enumerate(chain.owners):
            if owner == root_coord:
                root_flat = flat
                break
        if root_flat is None:
            return unbuildable("root_not_in_chain")
        # classify external slots: every differentiable ext input must be
        # one of the optimizer's updated params, every updated param must
        # appear (otherwise the eager step and the fused step would update
        # different sets)
        param_idx = {id(p): k for k, p in enumerate(updated)}
        slot_inputs = {}
        for i, rec in enumerate(cyc.ops):
            slots = chain.ext_of[i]
            for k, s in enumerate(slots):
                if s is not None:
                    slot_inputs[s] = rec.ins[k]
        param_slots = {}
        for s in chain.diff_ext_idx:
            k = param_idx.get(id(slot_inputs[s]))
            if k is None:
                # a differentiable external input that is not an updated
                # parameter (e.g. a float mask with stop_gradient=False)
                return unbuildable("nonparam_diff_input")
            param_slots[s] = k
        if {k for k in param_slots.values()} != set(range(len(updated))):
            return unbuildable("param_set_mismatch")
        # events with per-op entries collapsed to ("op",) markers, in order
        # (the trailing ("step", ...) sig entry becomes the terminal event)
        op_iter = 0
        for e in cyc.entries:
            if e[0] == "op":
                entries.append(("op", op_iter))
                op_iter += 1
            elif e[0] != "step":
                entries.append(e)
        entries.append(("step",))
        program = _StepProgram()
        program.sig = sig
        program.chain = chain
        program.entries = tuple(entries)
        program.root_coord = root_coord
        program.root_flat = root_flat
        program.param_refs = tuple(weakref.ref(p) for p in updated)
        program.param_names = tuple(p.name for p in updated)
        program.param_regs = tuple(
            getattr(p, "regularizer", None) for p in updated)
        program.need_clip = tuple(
            getattr(p, "need_clip", True) for p in updated)
        program.param_slots = param_slots
        program.ext_order = tuple(
            s for s in range(chain.n_ext) if s not in param_slots)
        program.opt_ref = weakref.ref(opt)
        program.clip_ref = opt._grad_clip
        program.clip_snapshot = _snapshot_obj(opt._grad_clip)
        program.reg_ref = opt.regularization
        program.reg_snapshot = _snapshot_obj(opt.regularization)
        bake_decay_flags(opt, updated)
        program.extra_key = tuple(opt._extra_cache_key())
        program.acc_names = tuple(sorted(opt._accumulators.keys()))
        program.check = chain.check
        if scaler_obj is not None:
            program.scaler_ref = weakref.ref(scaler_obj)
            program.scaler_consts = scaler_es[0][2]
        # distributed lowering (ops/spmd_fusion.py): when the cycle's
        # inputs live sharded on a mesh, the step compiles through
        # shard_map with the collectives fused in — validated by a
        # probation fire before any fused result commits
        from . import spmd_fusion as _spmd
        plan, plan_reason = _spmd.plan_program(
            chain, slot_inputs, program.ext_order, updated, opt,
            program.acc_names, root_flat)
        if plan_reason is not None:
            # a mesh-level contradiction (inputs spanning meshes) is a
            # first-class reason code, not an anonymous build detail
            _EVENTS.emit("step.record", "", reason=plan_reason,
                         detail={"kind": "build_fail"})
        if plan is not None:
            program.spmd_plan = plan
            program.spmd_ok = False
        names = [op.name for op in ops]
        head = "→".join(names[:3]) + ("→…" if len(names) > 3 else "")
        program.label = (f"{head}[{len(ops)}ops]"
                         f"+{type(opt).__name__}"
                         + ("+GradScaler" if scaler_obj is not None else "")
                         + (f"@mesh[{plan.axes_label}]"
                            if plan is not None else ""))
        program.n_launches = len(ops) + sum(
            1 for op in ops if op.diff_mask is not None) + 1 \
            + (2 if scaler_obj is not None else 0)
        program.baseline_ns = time.perf_counter_ns() - cyc.t0
        program.donate_params = bool(
            _FLAGS.get("FLAGS_eager_step_fusion_donate_params"))
        from . import aot_cache as _aot
        if _aot.enabled() and plan is None:
            # SPMD programs opt out of the AOT store for now: jax.export
            # of manual-mesh programs is not round-trip-safe on every
            # supported jax, and the mesh topology fingerprint already
            # guards cross-topology reuse (ROADMAP follow-on)
            dg = st.aot_probe.get(sig, 0)
            program.aot_digest = dg if dg != 0 \
                else _aot.step_digest(sig, opt, updated)
        elif plan is not None:
            program.aot_stored = True
        STEP_STATS.promoted(program.label)
        _EVENTS.emit("step.promote", program.label,
                     detail={"ops": len(ops), "params": len(updated),
                             "launches_estimate": program.n_launches,
                             "warm_start": warm,
                             "spmd": plan is not None,
                             "mesh": plan.axes_label if plan is not None
                             else None})
        return program

    def _disable(self, st):
        """Flag flipped off mid-run: resolve and forget everything."""
        if st.pending is not None and not st.pending.fired:
            with st.pending.lock:
                if not st.pending.done:
                    self._split(st.pending, escape=False,
                                reason="flag_off")
        st.pending = None
        st.recording = None
        st.prev_sig, st.streak = None, 0
        st.active = None
        st.replay_arm = False

    # -- maintenance --------------------------------------------------------
    def clear(self):
        """Drop the calling thread's promoted steps, observation state, and
        any pending replay (test hook / clear_dispatch_cache)."""
        st = self._tls
        self._disable(st)
        st.library.clear()
        st.aot_probe.clear()

    def info(self):
        st = self._tls
        return {
            "library": len(st.library),
            "active": st.active.label if st.active is not None else None,
            "streak": st.streak,
            "programs": [
                {"label": p.label, "ops": len(p.chain.ops),
                 "params": len(p.param_refs), "dead": p.dead,
                 "launches_estimate": p.n_launches,
                 "spmd": (p.spmd_plan.axes_label
                          if p.spmd_plan is not None else None)}
                for p in st.library.values()
                if isinstance(p, _StepProgram)],
        }


STEP = _StepFusionManager()


def clear_step_cache():
    """Drop every promoted whole-step program and observation state on the
    calling thread (test hook / manual invalidation)."""
    STEP.clear()


def step_cache_info():
    """Promoted-step library summary for the calling thread."""
    return STEP.info()
