"""Whole-step eager fusion: auto-TrainStep promotion.

The layer above chain fusion (ops/fusion.py). Chain fusion collapses hot
forward op *sequences* into single launches, but every chain stops at a
tape read: `loss.backward()` forces the pending chain, and the backward
walk plus the optimizer update still launch per-node. `jit.TrainStep`
proves the fast path is ONE executable for the whole step — this module
gets eager loops there automatically, without the user rewriting their
loop.

How it works:

  OBSERVE   Every dispatched op, `Tensor.backward()` call, and optimizer
            `step()`/`clear_grad()` call is recorded into the current
            *cycle* (one training iteration, delimited by `opt.step()`
            entries). A cycle's signature is the ordered tuple of per-op
            cache keys + dataflow wiring + the backward/optimizer events —
            the same keying discipline as chain fusion scaled to a step,
            so every per-op invalidation rule (registry generation, AMP
            state, avals, diff masks) applies for free.

  PROMOTE   After FLAGS_eager_step_fusion_min_count consecutive identical
            cycles, the cycle is compiled into one fused executable:
            forward (rebuilt as a pure function from the recorded ops, the
            re-trace contract of framework/autograd.replay_pure), backward
            (jax.vjp w.r.t. the parameter slots), grad regularization +
            clipping (the optimizer's own clip/regularizer objects traced
            over shims), and the optimizer update (`_single_update`, with
            decay flags baked by jit/train_step.bake_decay_flags).
            Optimizer-slot buffers are donated exactly as the eager
            optimizer's fused update donates them; parameter donation is
            opt-in (FLAGS_eager_step_fusion_donate_params), sharing
            jit/train_step.donation_argnums.

  REPLAY    Speculative and transactional, like chain replay: each
            dispatch is matched against the promoted program and deferred
            as a `_DeferredTensor`; `loss.backward()` is consumed as an
            event (p.grad becomes a pending placeholder); `opt.step()`
            fires the ONE fused launch, updates parameters/slots in place,
            and fills the loss + grad placeholders from the fused outputs.
            The LR-schedule value and the step count are hoisted to scalar
            arguments, so schedulers never split. ANY divergence — an op
            or event mismatch, a mid-step value peek (a `loss.numpy()`
            between backward and step; after the step it is served from
            the fused outputs), a changed optimizer/clip/param set, an
            in-place param mutation, an RNG-key advance (random ops re-key
            every call), an execution fault — SPLITS: the deferred prefix
            replays through the chain/per-op cached path and, if the
            backward event was already consumed, the real tape backward
            runs, so numerics are bitwise-identical to unfused dispatch in
            every outcome. Steps that keep failing to replay are
            deactivated.

Telemetry: profiler/step_fusion.py, surfaced by
`paddle_tpu.profiler.step_fusion_stats()` and embedded in bench.py
headline records as the `step_fusion` block.
"""
from __future__ import annotations

import threading
import time
import weakref
from collections import OrderedDict

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework import autograd as _autograd
from ..framework.autograd import FusedStepNode, run_backward
from ..framework.flags import _FLAGS
from ..profiler.step_fusion import STEP_STATS
from ..profiler.events import EVENTS as _EVENTS
from .fusion import (MANAGER as _CHAIN_MANAGER, Chain, _ChainOp,
                     _DeferredTensor, _PENDING, _VALUE_SLOT, _NODE_SLOT,
                     _IDX_SLOT, _is_pending, _key_diff_reason,
                     replay_ops_per_op)

__all__ = ["STEP", "MISS", "clear_step_cache", "step_cache_info"]

MISS = object()

# consecutive failed replays before a promoted step is deactivated
_MAX_FAIL_STREAK = 4
# recording cap per cycle: a cycle longer than this cannot promote (the
# compile would not amortize) and recording details stop to bound memory
_MAX_CYCLE_OPS = 2048

_UNBUILDABLE = object()     # library sentinel: this sig cannot promote


def _out_aval(t):
    """(shape, dtype, weak_type) without forcing a pending placeholder."""
    av = getattr(t, "_fusion_aval", None)
    if av is not None:
        return av
    v = t._value
    return (v.shape, v.dtype, getattr(v, "weak_type", False))


def _snapshot_obj(obj):
    """Value snapshot of a clip/regularizer object's scalar attributes:
    these are baked into the traced step as constants, so a mutation must
    un-verify the promoted program."""
    if obj is None:
        return None
    attrs = tuple(sorted(
        (k, v) for k, v in vars(obj).items()
        if isinstance(v, (int, float, bool, str))))
    return (type(obj).__name__, attrs)


class _OpRec:
    """One dispatch recorded into the current observation cycle. `ins` and
    `outs` hold strong refs for the cycle's lifetime: the produced-map is
    keyed by id(), so every recorded tensor must stay alive or a freed
    id's reuse would mis-wire a later fresh input as ("prev", i, j)."""

    __slots__ = ("name", "key", "fn", "wiring", "diff_mask", "num_outputs",
                 "out_avals", "out_stop_grads", "ins", "outs")

    def __init__(self, name, key, fn, wiring, diff_mask, num_outputs,
                 out_avals, out_stop_grads, ins, outs):
        self.name = name
        self.key = key
        self.fn = fn
        self.wiring = wiring
        self.diff_mask = diff_mask
        self.num_outputs = num_outputs
        self.out_avals = out_avals
        self.out_stop_grads = out_stop_grads
        self.ins = ins
        self.outs = outs


class _Cycle:
    """Observation state for one training iteration."""

    __slots__ = ("entries", "ops", "produced", "dirty", "t0", "n_backward",
                 "scaler", "rng_epoch0")

    def __init__(self):
        self.entries = []
        self.ops = []
        self.produced = {}     # id(tensor) -> (op index, out index)
        self.dirty = False
        self.t0 = time.perf_counter_ns()
        self.n_backward = 0
        self.scaler = None     # GradScaler seen by on_scaler_step, if any
        # absolute stream position of the cycle's FIRST hoisted RNG input
        # (framework/random.rng_key_input): per-input positions enter the
        # signature as DELTAS from it, so a loop whose randomness advances
        # every step still records the identical structural signature
        self.rng_epoch0 = None

    def poison(self):
        """The cycle cannot promote: drop every recorded detail NOW so a
        dirty (or boundary-less, e.g. pure-inference) stream pins no
        tensors — after this, record() is a cheap early return until the
        next optimizer-step boundary."""
        self.dirty = True
        self.entries.clear()
        self.ops.clear()
        self.produced.clear()
        self.scaler = None
        self.rng_epoch0 = None


class _ParamShim:
    """Minimal stand-in for a Parameter inside the traced grad transform:
    the optimizer's clip/regularizer objects only read `_value`,
    `need_clip`, `name`, and `regularizer`."""

    __slots__ = ("_value", "name", "need_clip", "regularizer")


class _StepProgram:
    """A promoted cycle: the forward chain, the event schedule, the
    optimizer binding, and (lazily) the one fused executable."""

    __slots__ = ("sig", "chain", "entries", "root_coord", "root_flat",
                 "param_refs", "param_names", "param_regs", "need_clip",
                 "param_slots", "ext_order", "opt_ref", "clip_ref",
                 "clip_snapshot", "reg_ref", "reg_snapshot", "extra_key",
                 "acc_names", "label", "n_launches", "baseline_ns",
                 "fail_streak", "dead", "_exe", "_shims", "donate_params",
                 "check", "scaler_ref", "scaler_consts", "aot_digest",
                 "aot_stored", "spmd_plan", "spmd_ok", "rng_slots",
                 "super", "seg_start", "_sub_exe", "_upd_exe", "_zero_acc",
                 "tail_chain", "tail_root_flat", "tail_rng_slots",
                 "_tail_sub_exe")

    def __init__(self):
        self.fail_streak = 0
        self.dead = False
        self._exe = None
        self._shims = None
        self.aot_digest = None   # ops/aot_cache.py warm-start address
        self.aot_stored = False
        # guardian (FLAGS_check_numerics, ops/guardian.py): check-ness is
        # fixed by the signature (the per-op keys carry the flag), and the
        # executable then folds the skip-step where()-rescue in; a fused
        # GradScaler additionally folds unscale/found-inf/scale-update
        self.check = False
        self.scaler_ref = None
        self.scaler_consts = None
        # distributed lowering (ops/spmd_fusion.py): a MeshPlan makes
        # _compile wrap the step in shard_map over the plan's mesh (grad
        # psum + sharded update + all-reduced predicates fused in); the
        # first fire then runs under PROBATION (spmd_ok False → eager
        # results commit, fused-vs-eager compared; a divergence demotes the
        # program to the plain jit lowering)
        self.spmd_plan = None
        self.spmd_ok = True
        # hoisted RNG consumption: ((ext slot, stream delta), ...) — these
        # ext slots are DERIVED in-graph from the hoisted (base key data,
        # first position) device args instead of being fed values
        self.rng_slots = ()
        # super-cycle (grad accumulation): the program's chain is ONE
        # micro-batch segment; replay loops it k times, firing the SUB
        # executable (fwd+vjp, grads added into a device accumulator) at
        # each backward and the UPDATE executable (clip/reg + optimizer +
        # guardian/scaler on the ACCUMULATED grads) at the step boundary —
        # ≤2 executables and zero retraces at ANY k
        self.super = False
        self.seg_start = 0      # entry index of the segment's first entry
        self._sub_exe = None
        self._upd_exe = None
        self._zero_acc = None
        # ragged tail (epoch-boundary batches): a SECOND op template +
        # sub-executable for the one smaller micro-batch closing the
        # accumulation loop — k−1 full rounds fire the main sub, the tail
        # round fires this one into the SAME accumulator (grads share the
        # param avals, so the shapes agree). ≤3 executables total.
        self.tail_chain = None
        self.tail_root_flat = None
        self.tail_rng_slots = ()
        self._tail_sub_exe = None   # (zero grad accumulators, True scalar)

    def release_heavy(self):
        """A deactivated program stays in the library as a tombstone (so
        the same cycle is not re-promoted just to fail again) but must not
        pin its compiled executable or trace shims. The op templates
        (chain) stay: already-fired pendings still lazily recompute
        through them."""
        self._exe = None
        self._shims = None
        self._sub_exe = None
        self._upd_exe = None
        self._zero_acc = None
        self._tail_sub_exe = None

    # -- the fused executable ----------------------------------------------
    def _grad_transform(self, pvals, grads):
        """Regularization + grad clip exactly as Optimizer.step applies
        them, traced over param shims so the user's own clip/regularizer
        objects run unmodified."""
        reg = self.reg_ref
        clip = self.clip_ref
        if reg is None and clip is None:
            return grads
        shims = self._shims
        pgs = []
        for shim, pv, gv in zip(shims, pvals, grads):
            shim._value = pv
            g = Tensor(gv, stop_gradient=True)
            if reg is not None:
                g = reg.apply(shim, g)
            pgs.append((shim, g))
        if clip is not None:
            pgs = clip(pgs)
        return [g._value for _, g in pgs]

    def exe(self):
        if self._exe is not None:
            return self._exe
        from ..jit.train_step import donation_argnums
        from . import aot_cache as _aot
        if _aot.enabled() and self.aot_digest is not None:
            # warm start: deserialize the stored whole-step program (zero
            # fresh traces); a corrupt/mismatched artifact heals through
            # _compile transparently
            self._exe = _aot.load_step(
                self, self._compile,
                donation_argnums(self.donate_params, 0, 2))
            if self._exe is not None:
                if self.spmd_plan is not None:
                    # a stored SPMD artifact only exists because a prior
                    # process fired it AFTER passing probation on this
                    # exact cycle + mesh topology (the env fingerprint
                    # pins both) — the restored program re-validates
                    # nothing and commits fused from its first replay
                    self.spmd_ok = True
                return self._exe
        self._exe = self._compile()
        return self._exe

    def _compile(self):
        from ..jit.train_step import donation_argnums
        from . import guardian
        from . import spmd_fusion as _spmd
        plan = self.spmd_plan
        chain = self.chain
        pure = chain.pure_fn
        root = self.root_flat
        seed_shape, seed_dtype = chain.flat_avals[root][:2]
        param_slots = tuple(sorted(self.param_slots.items()))
        ext_order = self.ext_order
        n_ext = chain.n_ext
        # the closure holds the WEAKREF, not the optimizer: jit retains the
        # traced fn for the program's lifetime, and a strong capture would
        # pin the optimizer (and through _parameter_list the whole model)
        # even after the user discards both. The deref only runs at trace
        # time, when the firing hook has the optimizer live in hand.
        opt_ref = self.opt_ref
        acc_names = self.acc_names
        check = self.check
        scaler_consts = self.scaler_consts
        rng_items = tuple(sorted(self.rng_slots.items())) \
            if self.rng_slots else ()
        self._ensure_shims()

        def step_body(pvals, ext, accs, lr, step_count, rng_state,
                      scaler_state):
            STEP_STATS.retraces += 1   # side effect: runs only while tracing
            full = [None] * n_ext
            for pos, slot in enumerate(ext_order):
                full[slot] = ext[pos]
            if rng_state is not None:
                # hoisted RNG: every key derives IN-GRAPH from (base key
                # data, first stream position) — the same fold_in the
                # eager path applies, so the fused key stream is
                # bit-identical to eager's
                from ..framework import random as _random
                base_kd, ep0 = rng_state
                for slot, delta in rng_items:
                    full[slot] = _random.derive_key_data(base_kd,
                                                         ep0 + delta)

            def fwd(pv):
                env = list(full)
                for slot, k in param_slots:
                    env[slot] = pv[k]
                return pure(*env)[root]

            # stored-sharded (ZeRO) params all-gather to full for the
            # forward; grads come back full so p.grad parity holds
            pvals_full = pvals if plan is None \
                else _spmd.gather_params(plan, pvals)
            root_val, vjp = jax.vjp(fwd, list(pvals_full))
            (grads,) = vjp(jnp.ones(seed_shape, seed_dtype))
            if plan is not None:
                # the gradient all-reduce + loss sync of the distributed
                # lowering (ops/spmd_fusion.py): every grad rides ONE
                # fused pmean region over the batch axes
                root_val, grads = _spmd.sync_root_and_grads(
                    plan, root_val, grads)
            finite_of = guardian.finite_all if plan is None \
                else (lambda vals: _spmd.global_finite(plan, vals))
            extras = ()
            if scaler_state is not None:
                # check_finite_and_unscale + update_loss_scaling, folded
                # in: grads leave the executable UNSCALED (exactly what
                # the eager path leaves in p.grad after scaler.step), and
                # the loss-scale transition is the same pure function the
                # eager GradScaler.update() evaluates. Under a mesh plan
                # found-inf is all-reduced, so the backoff is globally
                # consistent even when one shard saw the blowup.
                scale, good, bad = scaler_state
                inv = jnp.asarray(1.0, jnp.float32) / scale
                grads = [g * inv.astype(g.dtype) for g in grads]
                found_inf = jnp.logical_not(finite_of(grads))
                (_en, _dyn, incr_ratio, decr_ratio,
                 incr_n, decr_n) = scaler_consts
                scale2, good2, bad2 = guardian.update_scaler_state(
                    scale, good, bad, found_inf, incr_ratio, decr_ratio,
                    incr_n, decr_n)
                extras = (found_inf, scale2, good2, bad2)
            upd = self._grad_transform(pvals_full, grads)
            opt = opt_ref()   # trace-time only; firing keeps it alive
            new_p, new_accs = [], []
            for k, (pv, gv, ac) in enumerate(zip(pvals, upd, accs)):
                acc_dict = dict(zip(acc_names, ac))
                if plan is not None and plan.param_shard[k] is not None:
                    # ZeRO-sharded slots: slice-update-allgather
                    np_, na_ = _spmd.sharded_single_update(
                        plan, k, opt, pv, gv, acc_dict, lr, step_count)
                else:
                    np_, na_ = opt._single_update(pv, gv, acc_dict, lr,
                                                  step_count)
                new_p.append(np_)
                new_accs.append([na_.get(n) for n in acc_names])
            if check:
                # skip-step rescue: non-finite grads OR a non-finite
                # updated state make the whole update a bitwise no-op on
                # params AND optimizer slots — ONE fused scalar
                # predicate, zero extra launches. The new params/slots
                # are part of the predicate because finite grads can
                # still blow up the state (an LR spike overflowing
                # `p - lr*g`, a momentum buffer saturating): gating on
                # grads alone would wave the blowup through the gate.
                # Under a mesh plan the predicate is ALL-REDUCED first:
                # sharded slots make it device-varying, and every shard
                # must take the same skip/keep branch.
                new_state = list(new_p) + [v for row in new_accs
                                           for v in row if v is not None]
                upd_finite = finite_of(list(upd) + new_state)
                fwd_finite = finite_of([root_val])
                new_p = [jnp.where(upd_finite, nv, pv)
                         for nv, pv in zip(new_p, pvals)]
                new_accs = [
                    [None if nv is None else jnp.where(upd_finite, nv, ov)
                     for nv, ov in zip(row, ac)]
                    for row, ac in zip(new_accs, accs)]
                extras = (upd_finite, fwd_finite) + extras
            return (root_val, grads, new_p, new_accs) + extras

        n_rng = 2 if rng_items else 0

        def step_fn(pvals, ext, accs, lr, step_count, *tail):
            # tail layout: [base_key_data, epoch0] when the program has
            # hoisted RNG slots, then [scale, good, bad] for a folded
            # GradScaler — both ride as device args so neither randomness
            # nor loss-scale dynamics ever retrace the program
            rng_state = tail[:2] if n_rng else None
            sc = tail[n_rng:]
            scaler_state = tuple(sc) if sc else None
            return step_body(pvals, ext, accs, lr, step_count, rng_state,
                             scaler_state)

        donate = donation_argnums(self.donate_params, 0, 2)
        if plan is not None:
            # the distributed lowering: shard_map over the plan's mesh,
            # same outer signature and donation argnums as the plain path
            n_scaler = 3 if scaler_consts is not None else 0
            n_extras = (2 if check else 0) \
                + (4 if scaler_consts is not None else 0)
            self._exe = _spmd.compile_step(
                plan, step_fn, len(self.param_refs), n_rng + n_scaler,
                n_extras, donate)
            return self._exe
        self._exe = jax.jit(step_fn, donate_argnums=donate)
        return self._exe

    # -- the super-cycle pair (grad accumulation) --------------------------
    def _ensure_shims(self):
        if self._shims is None:
            shims = []
            for nm, nc, pr in zip(self.param_names, self.need_clip,
                                  self.param_regs):
                s = _ParamShim()
                s.name = nm
                s.need_clip = nc
                s.regularizer = pr
                shims.append(s)
            self._shims = shims

    def sub_exe(self):
        """The reusable micro-batch sub-executable: fwd + vjp over the
        param slots, gradients ADDED into the running accumulator. Fired
        once per `loss.backward()` of the accumulation loop — the same
        compiled program at any k."""
        if self._sub_exe is None:
            self._maybe_load_super()
        if self._sub_exe is None:
            self._sub_exe = self._compile_sub()
        return self._sub_exe

    def upd_exe(self):
        """The boundary update executable: clip/regularizer + optimizer
        update + guardian skip predicate + GradScaler transition, all
        evaluated on the ACCUMULATED grads. Fired once per `opt.step()`."""
        if self._upd_exe is None:
            self._maybe_load_super()
        if self._upd_exe is None:
            self._upd_exe = self._compile_update()
        return self._upd_exe

    def tail_sub_exe(self):
        """The ragged-tail sub-executable: the same fwd+vjp+accumulate
        body compiled against the TAIL segment's op template (the one
        smaller epoch-boundary micro-batch). Adds into the same
        accumulator as the main sub — grads share the param avals."""
        if self._tail_sub_exe is None:
            self._tail_sub_exe = self._compile_sub(
                chain=self.tail_chain, root_flat=self.tail_root_flat,
                rng_slots=self.tail_rng_slots)
        return self._tail_sub_exe

    def _maybe_load_super(self):
        """AOT warm start for the super-cycle pair: deserialize both
        stored executables (zero fresh traces); corrupt or mismatched
        artifacts heal through the live compilers transparently."""
        from ..jit.train_step import donation_argnums
        from . import aot_cache as _aot
        if not (_aot.enabled() and self.aot_digest is not None):
            return
        sub, upd = _aot.load_super_step(
            self, self._compile_sub, self._compile_update,
            donation_argnums(self.donate_params, 0, 1))
        if sub is not None:
            self._sub_exe = sub
            self._upd_exe = upd
            if self.spmd_plan is not None:
                # the stored pair proved itself post-probation in the
                # storing process, on this exact cycle + topology —
                # skip probation and fire fused immediately
                self.spmd_ok = True

    def zero_state(self):
        """(zero grad accumulators, all-finite True scalar): the round-0
        inputs of the sub executable. Never donated or mutated — one
        allocation per program, reused every cycle."""
        if self._zero_acc is None:
            from . import spmd_fusion as _spmd
            shapes = []
            for r in self.param_refs:
                v = r()._value     # grads share the param aval
                shapes.append((tuple(v.shape), v.dtype))
            if self.spmd_plan is not None:
                accs = _spmd.zero_accum(self.spmd_plan, shapes)
            else:
                accs = [jnp.zeros(s, d) for s, d in shapes]
            self._zero_acc = (accs, jnp.asarray(True))
        return self._zero_acc

    def _compile_sub(self, chain=None, root_flat=None, rng_slots=None):
        from . import guardian
        from . import spmd_fusion as _spmd
        plan = self.spmd_plan
        chain = self.chain if chain is None else chain
        pure = chain.pure_fn
        root = self.root_flat if root_flat is None else root_flat
        seed_shape, seed_dtype = chain.flat_avals[root][:2]
        param_slots = tuple(sorted(self.param_slots.items()))
        ext_order = self.ext_order
        n_ext = chain.n_ext
        rng_slots = self.rng_slots if rng_slots is None else rng_slots
        rng_items = tuple(sorted(rng_slots.items())) if rng_slots else ()
        n_rng = 2 if rng_items else 0
        check = self.check

        def sub_fn(pvals, ext, acc, *tail):
            STEP_STATS.retraces += 1   # side effect: runs only while tracing
            # tail layout: [base_key_data, epoch0] when the segment
            # consumes hoisted RNG, then [fwd_ok] under the guardian —
            # the running all-rounds-finite predicate threads through
            full = [None] * n_ext
            for pos, slot in enumerate(ext_order):
                full[slot] = ext[pos]
            if n_rng:
                from ..framework import random as _random
                base_kd, ep0 = tail[0], tail[1]
                for slot, delta in rng_items:
                    full[slot] = _random.derive_key_data(base_kd,
                                                         ep0 + delta)

            def fwd(pv):
                env = list(full)
                for slot, k in param_slots:
                    env[slot] = pv[k]
                return pure(*env)[root]

            pvals_full = pvals if plan is None \
                else _spmd.gather_params(plan, pvals)
            root_val, vjp = jax.vjp(fwd, list(pvals_full))
            (grads,) = vjp(jnp.ones(seed_shape, seed_dtype))
            if plan is not None and plan.data_axes:
                # the per-round LOSS syncs (one scalar pmean — it may be
                # served to the caller); the GRADIENTS do not: local sums
                # accumulate, and ONE fused pmean fires in the update
                # executable — k× less collective traffic than syncing
                # every micro-batch
                root_val = jax.lax.pmean(root_val, plan.data_axes)
            new_acc = [a + g for a, g in zip(acc, grads)]
            if check:
                fwd_ok = jnp.logical_and(tail[n_rng],
                                         guardian.finite_all([root_val]))
                return (root_val, new_acc, fwd_ok)
            return (root_val, new_acc)

        if plan is not None:
            sub_fn._returns_fwd_ok = check
            return _spmd.compile_accum(plan, sub_fn, len(self.param_refs),
                                       n_rng + (1 if check else 0))
        return jax.jit(sub_fn)

    def _compile_update(self):
        from ..jit.train_step import donation_argnums
        from . import guardian
        from . import spmd_fusion as _spmd
        plan = self.spmd_plan
        opt_ref = self.opt_ref
        acc_names = self.acc_names
        check = self.check
        scaler_consts = self.scaler_consts
        self._ensure_shims()

        def upd_fn(pvals, accs, gsum, lr, step_count, *tail):
            STEP_STATS.retraces += 1
            # tail layout: [fwd_ok] under the guardian, then
            # [scale, good, bad] for a folded GradScaler. The body mirrors
            # the post-gradient half of _compile's step_body, evaluated on
            # the ACCUMULATED grads — guardian skip and scaler backoff see
            # exactly what the eager path sees in p.grad after k backwards.
            grads = list(gsum)
            if plan is not None and plan.data_axes:
                # the ONE fused gradient collective of the super-cycle
                grads = [jax.lax.pmean(g, plan.data_axes) for g in grads]
            finite_of = guardian.finite_all if plan is None \
                else (lambda vals: _spmd.global_finite(plan, vals))
            i_tail = 0
            fwd_ok = None
            if check:
                fwd_ok = tail[0]
                i_tail = 1
            extras = ()
            sc = tail[i_tail:]
            if sc:
                scale, good, bad = sc
                inv = jnp.asarray(1.0, jnp.float32) / scale
                grads = [g * inv.astype(g.dtype) for g in grads]
                found_inf = jnp.logical_not(finite_of(grads))
                (_en, _dyn, incr_ratio, decr_ratio,
                 incr_n, decr_n) = scaler_consts
                scale2, good2, bad2 = guardian.update_scaler_state(
                    scale, good, bad, found_inf, incr_ratio, decr_ratio,
                    incr_n, decr_n)
                extras = (found_inf, scale2, good2, bad2)
            pvals_full = pvals if plan is None \
                else _spmd.gather_params(plan, pvals)
            upd = self._grad_transform(pvals_full, grads)
            opt = opt_ref()   # trace-time only; firing keeps it alive
            new_p, new_accs = [], []
            for k, (pv, gv, ac) in enumerate(zip(pvals, upd, accs)):
                acc_dict = dict(zip(acc_names, ac))
                if plan is not None and plan.param_shard[k] is not None:
                    np_, na_ = _spmd.sharded_single_update(
                        plan, k, opt, pv, gv, acc_dict, lr, step_count)
                else:
                    np_, na_ = opt._single_update(pv, gv, acc_dict, lr,
                                                  step_count)
                new_p.append(np_)
                new_accs.append([na_.get(n) for n in acc_names])
            if check:
                new_state = list(new_p) + [v for row in new_accs
                                           for v in row if v is not None]
                upd_finite = finite_of(list(upd) + new_state)
                new_p = [jnp.where(upd_finite, nv, pv)
                         for nv, pv in zip(new_p, pvals)]
                new_accs = [
                    [None if nv is None else jnp.where(upd_finite, nv, ov)
                     for nv, ov in zip(row, ac)]
                    for row, ac in zip(new_accs, accs)]
                extras = (upd_finite, fwd_ok) + extras
            return (grads, new_p, new_accs) + extras

        donate = donation_argnums(self.donate_params, 0, 1)
        if plan is not None:
            n_tail = (1 if check else 0) \
                + (3 if scaler_consts is not None else 0)
            n_extras = (2 if check else 0) \
                + (4 if scaler_consts is not None else 0)
            return _spmd.compile_update(plan, upd_fn, len(self.param_refs),
                                        n_tail, n_extras, donate)
        return jax.jit(upd_fn, donate_argnums=donate)


class _PendingStep:
    """A speculative whole-step replay in flight."""

    __slots__ = ("program", "owner", "entry_pos", "op_pos", "ext_vals",
                 "ext_edges", "placeholders", "params", "grad_phs",
                 "backward_done", "fired", "done", "lock", "t0",
                 "rng_epoch0", "rng_base", "rounds", "round_losses",
                 "acc_vals", "fwd_ok", "sub_args", "in_tail", "tail_done")

    def __init__(self, program, params, owner):
        self.program = program
        self.owner = owner
        self.entry_pos = 0
        self.op_pos = 0
        self.ext_vals = []
        self.ext_edges = []
        self.placeholders = []
        self.params = params
        self.grad_phs = None
        self.backward_done = False
        self.fired = False
        self.done = False
        self.lock = threading.RLock()
        self.t0 = time.perf_counter_ns()
        # hoisted RNG: absolute stream position of this cycle's first
        # consumption (the epoch0 device arg of the fused fire) and the
        # BASE KEY the round's tensors were reserved against — the fire
        # must derive from that base, not whatever the global generator
        # holds at boundary time (a mid-cycle reseed swaps it)
        self.rng_epoch0 = None
        self.rng_base = None
        # super-cycle replay (grad accumulation): archived micro-batch
        # rounds [(ext_vals, ext_edges, placeholders, rng_epoch0), ...],
        # the per-round losses from sub-executable fires, the running
        # donated grad accumulator, and the running fwd-finite predicate
        self.rounds = []
        self.round_losses = []
        self.acc_vals = None
        self.fwd_ok = None
        self.sub_args = None    # last MAIN sub fire's args (AOT export)
        # ragged tail: the current round is matching against the TAIL op
        # template (the smaller epoch-boundary micro-batch); tail_done
        # records that a tail round already archived this cycle
        self.in_tail = False
        self.tail_done = False


class _TLS(threading.local):
    def __init__(self):
        self.recording = None      # _Cycle or None
        self.prev_sig = None
        self.streak = 0
        self.library = OrderedDict()   # sig -> _StepProgram | _UNBUILDABLE
        self.active = None         # armed program
        self.replay_arm = False    # next cycle's first entry may start replay
        self.pending = None
        self.busy = False
        self.aot_probe = {}        # sig -> AOT step digest (or None)


class _StepFusionManager:
    """Cycle recorder + promotion + whole-step replay. All state is
    per-thread (a training loop is one thread); cross-thread escapes of
    pending placeholders resolve through the shared owner protocol of
    ops/fusion.py."""

    def __init__(self):
        self._tls = _TLS()

    # -- config ------------------------------------------------------------
    @staticmethod
    def enabled():
        return bool(_FLAGS.get("FLAGS_eager_step_fusion")) \
            and int(_FLAGS.get("FLAGS_eager_step_fusion_cache_size", 8)
                    or 0) > 0 \
            and bool(_FLAGS.get("FLAGS_eager_op_cache")) \
            and int(_FLAGS.get("FLAGS_eager_op_cache_size", 512) or 0) > 0

    # -- dispatch hooks ----------------------------------------------------
    def step(self, name, fn, inputs, num_outputs, key, diff_mask,
             bypass_reason=None):
        """First crack at every non-debug dispatch (before chain fusion).
        Returns deferred placeholders while a whole-step replay is
        matching, else MISS (the dispatcher proceeds and later feeds
        record()). `bypass_reason` attributes a key=None poison/split to
        the dispatch-level cause (rng_rekey, unkeyable_closure, ...)."""
        st = self._tls
        if st.busy:
            return MISS
        if not self.enabled():
            if st.pending is not None or st.recording is not None \
                    or st.active is not None:
                self._disable(st)
            return MISS
        arm = st.replay_arm
        st.replay_arm = False
        if key is None:
            # un-jittable/un-keyable op: the cycle cannot promote
            self._poison(st, bypass_reason or "unkeyable_closure", op=name)
            pending = st.pending
            if pending is not None and not pending.fired:
                with pending.lock:
                    if not pending.done:
                        self._split(pending, escape=False,
                                    reason=bypass_reason
                                    or "unkeyable_closure",
                                    blocked_op=name)
                st.pending = None
            return MISS

        pending = st.pending
        if pending is not None or (arm and st.active is not None):
            # replay matching is about to read input state: genuinely
            # foreign pendings (another thread's chain, a fired step) must
            # be resolved lock-free first, mirroring chain fusion. This
            # thread's own in-flight CHAIN pending is NOT foreign — the
            # chain manager handles it in its own step() — and while step
            # fusion merely observes, no pre-forcing happens at all.
            own_chain = _CHAIN_MANAGER._tls.pending
            for t in inputs:
                if _is_pending(t) and t._pending_chain is not st.pending \
                        and t._pending_chain is not own_chain:
                    t._pending_chain.owner.resolve_pending(
                        t._pending_chain, escape=True)
        if pending is not None and not pending.fired:
            program = pending.program
            with pending.lock:
                if pending.done:
                    st.pending = None
                else:
                    entry = program.entries[pending.entry_pos]
                    if entry[0] != "op":
                        self._split(pending, escape=False,
                                    reason="event_mismatch", blocked_op=name)
                        return MISS
                    mismatch = self._match_round(
                        program, pending, key, inputs, diff_mask,
                        num_outputs)
                    if mismatch is None:
                        return self._defer(st, pending, inputs, num_outputs)
                    self._split(pending, escape=False, reason=mismatch,
                                blocked_op=name)
            return MISS
        if arm and st.active is not None:
            program = st.active
            if program.entries and program.entries[0][0] == "op":
                pending = self._start_pending(st, program)
                if pending is not None:
                    with pending.lock:
                        mismatch = self._match_round(
                            program, pending, key, inputs, diff_mask,
                            num_outputs)
                        if mismatch is None:
                            return self._defer(st, pending, inputs,
                                               num_outputs)
                        self._split(pending, escape=False, reason=mismatch,
                                    blocked_op=name)
        return MISS

    def record(self, name, fn, inputs, num_outputs, key, diff_mask, outs,
               cached_ok, bypass_reason=None):
        """Feed the cycle recorder after a dispatch ran (per-op cached,
        per-op uncached, or deferred into a chain replay)."""
        st = self._tls
        if st.busy or not self.enabled():
            return
        cyc = st.recording
        if cyc is None:
            cyc = st.recording = _Cycle()
        if cyc.dirty:
            return
        if key is None or not cached_ok or len(cyc.ops) >= _MAX_CYCLE_OPS:
            if key is None:
                reason = bypass_reason or "unkeyable_closure"
            elif not cached_ok:
                reason = "uncached_dispatch"
            else:
                reason = "cycle_too_long"
            self._poison(st, reason, op=name)
            return
        wiring = tuple(
            ("prev",) + cyc.produced[id(t)] if id(t) in cyc.produced
            else ("ext",)
            for t in inputs)
        try:
            out_avals = tuple(_out_aval(t) for t in outs)
        except Exception:
            self._poison(st, "tracer_input", op=name)
            return
        # hoisted RNG inputs (framework/random.rng_key_input): note each
        # one's stream position as a DELTA from the cycle's first — the
        # sig stays identical across steps while the stream advances, and
        # _build hoists (base key, first position) into the executable so
        # replay derives every key in-graph
        rng_marks = ()
        for k, t in enumerate(inputs):
            ep = getattr(t, "_rng_epoch", None)
            if ep is None:
                continue
            if cyc.rng_epoch0 is None:
                cyc.rng_epoch0 = ep
            rng_marks += ((k, ep - cyc.rng_epoch0),)
        entry = ("op", key, wiring, diff_mask, num_outputs)
        if rng_marks:
            entry += (rng_marks,)
        cyc.entries.append(entry)
        cyc.ops.append(_OpRec(
            name, key, fn, wiring, diff_mask, num_outputs, out_avals,
            tuple(t.stop_gradient for t in outs), tuple(inputs),
            tuple(outs)))
        i = len(cyc.ops) - 1
        for j, t in enumerate(outs):
            cyc.produced[id(t)] = (i, j)

    def interrupt(self):
        """Debug mode (NaN scan / benchmark sync) needs per-op results:
        resolve any pending replay and poison the cycle."""
        st = self._tls
        if st.busy:
            return
        if st.pending is not None and not st.pending.fired:
            with st.pending.lock:
                if not st.pending.done:
                    self._split(st.pending, escape=False,
                                reason="debug_interrupt")
            st.pending = None
        self._poison(st, "debug_interrupt")

    # -- backward / optimizer hooks ----------------------------------------
    def on_backward(self, tensor, grad_tensor, retain_graph):
        """Called at the top of Tensor.backward. Returns True when the
        backward was consumed by a pending whole-step replay (the caller
        must return immediately)."""
        st = self._tls
        if st.busy or not self.enabled():
            return False
        st.replay_arm = False
        pending = st.pending
        if pending is not None and not pending.fired:
            program = pending.program
            with pending.lock:
                if pending.done:
                    st.pending = None
                    return False
                entry = program.entries[pending.entry_pos]
                clean = entry[0] == "bwd" and grad_tensor is None \
                    and not retain_graph \
                    and not _autograd._saved_tensor_hooks \
                    and self._is_root(pending, tensor)
                if program.super:
                    # super-cycle: this backward closes ONE micro-batch
                    # round — fire the reusable sub-executable (grads
                    # accumulate on device) and keep matching: the next
                    # event is either another round or the boundary
                    round_chain = self._round_template(program, pending)[0]
                    if clean and pending.op_pos == len(round_chain.ops):
                        if pending.rounds:
                            clean = all(
                                p.grad is ph and not p._hooks
                                for p, ph in zip(pending.params,
                                                 pending.grad_phs))
                        else:
                            clean = all(p.grad is None and not p._hooks
                                        for p in pending.params)
                    else:
                        clean = False
                    if clean:
                        if not pending.rounds:
                            self._install_grad_placeholders(pending)
                        pending.backward_done = True
                        if self._fire_sub(st, pending):
                            return True
                        # the sub fire split transactionally: the caller
                        # runs the real backward on the replayed graph
                        return False
                    if entry[0] != "bwd" \
                            or not self._is_root(pending, tensor):
                        reason = "event_mismatch"
                    else:
                        reason = "hook_present"
                    self._split(pending, escape=False, reason=reason,
                                blocked_op="backward")
                    return False
                if clean and all(p.grad is None and not p._hooks
                                 for p in pending.params):
                    pending.entry_pos += 1
                    pending.backward_done = True
                    self._install_grad_placeholders(pending)
                    return True
                if entry[0] != "bwd" or not self._is_root(pending, tensor):
                    reason = "event_mismatch"
                else:
                    # retain_graph / explicit grad seed / saved-tensor or
                    # param hooks / stale grads: semantics a fused replay
                    # cannot honor
                    reason = "hook_present"
                self._split(pending, escape=False, reason=reason,
                            blocked_op="backward")
            return False
        # observation
        cyc = st.recording
        if cyc is None:
            cyc = st.recording = _Cycle()
        if cyc.dirty:
            return False
        cyc.n_backward += 1
        coord = cyc.produced.get(id(tensor))
        if coord is None or grad_tensor is not None or retain_graph \
                or _autograd._saved_tensor_hooks:
            if coord is None:
                reason = "event_mismatch"   # root not in the recorded cycle
            else:
                reason = "hook_present"
            self._poison(st, reason, op="backward")
            return False
        # multiple backwards per cycle are NO LONGER a poison: the
        # boundary tries to canonicalize k×(fwd+bwd)+step into a
        # super-cycle signature (grad accumulation) — unrecognizable
        # multi-backward shapes attribute `unpromotable_cycle` there
        cyc.entries.append(("bwd", coord))
        _EVENTS.emit("step.record", "backward",
                     detail={"kind": "bwd", "pos": len(cyc.ops)})
        return False

    def on_clear_grad(self, opt):
        """Called at the top of Optimizer.clear_grad; the caller always
        proceeds to clear the grads."""
        st = self._tls
        if st.busy or not self.enabled():
            return
        arm = st.replay_arm
        st.replay_arm = False
        pending = st.pending
        if pending is not None and not pending.fired:
            program = pending.program
            with pending.lock:
                if pending.done:
                    st.pending = None
                else:
                    entry = program.entries[pending.entry_pos]
                    if entry[0] == "cg" and opt is program.opt_ref():
                        pending.entry_pos += 1
                    else:
                        self._split(pending, escape=False,
                                    reason="event_mismatch",
                                    blocked_op="clear_grad")
            return
        if arm and st.active is not None:
            program = st.active
            if program.entries and program.entries[0][0] == "cg" \
                    and opt is program.opt_ref():
                pending = self._start_pending(st, program)
                if pending is not None:
                    pending.entry_pos = 1
                    return
        cyc = st.recording
        if cyc is None:
            cyc = st.recording = _Cycle()
        if not cyc.dirty:
            cyc.entries.append(("cg", id(opt)))

    def on_optimizer_step(self, opt):
        """Called at the top of Optimizer.step. Returns True when the
        fused executable performed the whole update (the caller must
        return immediately); always delimits the observation cycle."""
        st = self._tls
        if st.busy or not self.enabled():
            return False
        st.replay_arm = False
        pending = st.pending
        if pending is not None and not pending.fired:
            program = pending.program
            with pending.lock:
                if pending.done:
                    st.pending = None
                else:
                    entry = program.entries[pending.entry_pos]
                    split_reason = "event_mismatch"
                    if program.super:
                        # boundary of a matched accumulation loop: every
                        # round archived (entry_pos back at the segment
                        # start), and a scaler-folded program must arrive
                        # through on_scaler_step instead
                        terminal = program.scaler_ref is None \
                            and bool(pending.rounds) \
                            and pending.op_pos == 0 \
                            and pending.entry_pos == program.seg_start
                    else:
                        terminal = entry[0] == "step" \
                            and pending.entry_pos \
                            == len(program.entries) - 1 \
                            and pending.backward_done \
                            and pending.op_pos == len(program.chain.ops)
                    if terminal:
                        verify_fail = self._verify_fire(program, pending,
                                                        opt)
                        if verify_fail is None:
                            if program.spmd_plan is not None \
                                    and not program.spmd_ok:
                                # SPMD probation: this step commits EAGER
                                # results (the caller proceeds); the fused
                                # lowering is validated on the side
                                if program.super:
                                    self._probation_super(st, pending, opt)
                                else:
                                    self._probation(st, pending, opt)
                                st.pending = None
                                self._after_boundary(st)
                                return False
                            fired = self._fire_super(st, pending, opt) \
                                if program.super \
                                else self._fire(st, pending, opt)
                            if fired:
                                self._after_boundary(st)
                                return True
                            split_reason = None   # _fire already split
                        else:
                            split_reason = verify_fail
                    if not pending.done and split_reason is not None:
                        self._split(pending, escape=False,
                                    reason=split_reason,
                                    blocked_op="optimizer_step")
                    elif not pending.done:
                        self._split(pending, escape=False,
                                    reason="exec_fault",
                                    blocked_op="optimizer_step")
            st.pending = None
            self._boundary(st, opt, dirty=True)
            return False
        self._boundary(st, opt, dirty=False)
        return False

    def on_scaler_step(self, scaler, opt):
        """Called at the top of GradScaler.step (an ENABLED scaler), before
        its eager unscale/step path. Returns True when a pending
        whole-step replay matched through the scaler event and the ONE
        fused executable performed unscale + finite-check + the
        where()-rescued update + the loss-scale transition (the caller
        must skip its eager path and let update() commit the transition).
        During observation it records the scaler into the cycle — only
        under the guardian (FLAGS_check_numerics), whose in-graph
        skip-step semantics make the fold legal — and returns False."""
        from . import guardian
        st = self._tls
        if st.busy or not self.enabled():
            return False
        st.replay_arm = False
        pending = st.pending
        if pending is not None and not pending.fired:
            program = pending.program
            fired = False
            with pending.lock:
                if pending.done:
                    st.pending = None
                    return False
                if program.super:
                    if program.scaler_ref is None:
                        # recorded without this scaler: eager path runs,
                        # its grad reads split the replay
                        return False
                    split_reason = "event_mismatch"
                    if program.scaler_ref() is not scaler \
                            or scaler._consts() != program.scaler_consts:
                        self._kill(program)
                        split_reason = "optimizer_state_change"
                    elif pending.rounds and pending.op_pos == 0 \
                            and pending.entry_pos == program.seg_start:
                        verify_fail = self._verify_fire(program, pending,
                                                        opt)
                        if verify_fail is None:
                            if program.spmd_plan is not None \
                                    and not program.spmd_ok:
                                self._probation_super(st, pending, opt,
                                                      scaler=scaler)
                                st.pending = None
                                self._after_boundary(st)
                                return False
                            if self._fire_super(st, pending, opt,
                                                scaler=scaler):
                                fired = True
                                self._after_boundary(st)
                            else:
                                split_reason = None
                        else:
                            split_reason = verify_fail
                    if not fired and not pending.done \
                            and split_reason is not None:
                        self._split(pending, escape=False,
                                    reason=split_reason,
                                    blocked_op="scaler_step")
                    if fired:
                        return True
                    st.pending = None
                    self._boundary(st, opt, dirty=True)
                    return False
                entry = program.entries[pending.entry_pos]
                if entry[0] != "scaler":
                    # the program was recorded without this scaler (legacy
                    # mode / changed loop): let the eager path run — its
                    # grad reads split the replay
                    return False
                split_reason = "event_mismatch"
                if program.scaler_ref() is not scaler \
                        or scaler._consts() != program.scaler_consts:
                    # the scale hyper-parameters are baked into the traced
                    # loss-scale transition: a change is stale for good
                    self._kill(program)
                    split_reason = "optimizer_state_change"
                elif pending.entry_pos == len(program.entries) - 2 \
                        and pending.backward_done \
                        and pending.op_pos == len(program.chain.ops):
                    pending.entry_pos += 1
                    verify_fail = self._verify_fire(program, pending, opt)
                    if verify_fail is None:
                        if program.spmd_plan is not None \
                                and not program.spmd_ok:
                            # SPMD probation: eager scaler path proceeds
                            self._probation(st, pending, opt,
                                            scaler=scaler)
                            st.pending = None
                            self._after_boundary(st)
                            return False
                        if self._fire(st, pending, opt, scaler=scaler):
                            fired = True
                            self._after_boundary(st)
                        else:
                            split_reason = None   # _fire already split
                    else:
                        split_reason = verify_fail
                if not fired and not pending.done \
                        and split_reason is not None:
                    self._split(pending, escape=False, reason=split_reason,
                                blocked_op="scaler_step")
            if fired:
                return True
            st.pending = None
            self._boundary(st, opt, dirty=True)
            return False
        # observation: the scaler joins the cycle signature so _build folds
        # it into the fused step (guardian mode only — without the in-graph
        # skip the eager scaler syncs found_inf per step and cannot fuse)
        if guardian.skip_step_enabled():
            cyc = st.recording
            if cyc is None:
                cyc = st.recording = _Cycle()
            if not cyc.dirty:
                cyc.entries.append(("scaler", id(scaler), scaler._consts()))
                cyc.scaler = scaler
        return False

    # -- replay internals --------------------------------------------------
    @staticmethod
    def _is_root(pending, tensor):
        i, j = pending.program.root_coord
        try:
            return pending.placeholders[i][j] is tensor
        except IndexError:
            return False

    def _start_pending(self, st, program):
        if program.dead:
            st.active = None
            return None
        params = [r() for r in program.param_refs]
        if any(p is None for p in params):
            program.dead = True
            _EVENTS.emit("step.deactivate", program.label,
                         reason="param_mismatch",
                         detail={"why": "parameter_gc"})
            st.active = None
            return None
        # the chain layer must not be mid-replay under a step replay
        _CHAIN_MANAGER.flush()
        _CHAIN_MANAGER.reset()
        pending = _PendingStep(program, params, self)
        st.pending = pending
        return pending

    @staticmethod
    def _round_template(program, pending):
        """(chain, rng_slots) of the op template the CURRENT round matches
        against — the tail template when a ragged-tail round is in
        flight, else the main segment."""
        if program.super and pending.in_tail \
                and program.tail_chain is not None:
            return program.tail_chain, program.tail_rng_slots
        return program.chain, program.rng_slots

    def _match_round(self, program, pending, key, inputs, diff_mask,
                     num_outputs):
        """Tail-aware round matching: at a round boundary (op_pos 0) of a
        ragged-tail program, a main-template key mismatch retries against
        the TAIL template before splitting — the epoch-boundary batch is
        the recorded second shape, not a replay failure."""
        mismatch = self._op_mismatch_reason(program, pending, key, inputs,
                                            diff_mask, num_outputs)
        if mismatch is not None and program.super \
                and program.tail_chain is not None \
                and pending.op_pos == 0 and not pending.in_tail:
            pending.in_tail = True
            tail_mismatch = self._op_mismatch_reason(
                program, pending, key, inputs, diff_mask, num_outputs)
            if tail_mismatch is None:
                return None
            pending.in_tail = False
        return mismatch

    def _op_mismatch_reason(self, program, pending, key, inputs, diff_mask,
                            num_outputs):
        """None when the incoming dispatch matches the program's next op
        template; else the reason code the split should carry."""
        chain, rng_slots = self._round_template(program, pending)
        op = chain.ops[pending.op_pos]
        if key != op.key:
            return _key_diff_reason(op.key, key)
        if diff_mask != op.diff_mask or num_outputs != op.num_outputs \
                or len(inputs) != len(op.wiring):
            return "key_mismatch"
        slots = chain.ext_of[pending.op_pos]
        for k, (t, w) in enumerate(zip(inputs, op.wiring)):
            if _is_pending(t) and t._pending_chain is pending:
                if w[0] != "prev" or t._chain_coord != (w[1], w[2]):
                    return "wiring_mismatch"
            elif w[0] != "ext":
                return "wiring_mismatch"
            else:
                pk = program.param_slots.get(slots[k])
                if pk is not None and t is not pending.params[pk]:
                    # the slot must be fed by the SAME parameter object the
                    # program was built against — identity is the binding
                    return "param_mismatch"
                delta = rng_slots.get(slots[k]) if rng_slots else None
                if delta is not None:
                    # hoisted RNG slot: the incoming key must sit at the
                    # recorded stream offset from this cycle's first
                    # consumption — a shifted stream (an extra consumer
                    # interleaved, a mid-cycle reseed) cannot replay
                    ep = getattr(t, "_rng_epoch", None)
                    if ep is None:
                        return "rng_rekey"
                    if pending.rng_epoch0 is None:
                        pending.rng_epoch0 = ep - delta
                        pending.rng_base = getattr(t, "_rng_base", None)
                    elif ep - pending.rng_epoch0 != delta \
                            or getattr(t, "_rng_base", None) \
                            is not pending.rng_base:
                        # a shifted position OR a different base key (a
                        # reseed between this round's consumptions): the
                        # recorded derivation would sample wrong
                        return "rng_rekey"
        return None

    def _defer(self, st, pending, inputs, num_outputs):
        program = pending.program
        chain, rng_slots = self._round_template(program, pending)
        op = chain.ops[pending.op_pos]
        slots = chain.ext_of[pending.op_pos]
        for k, t in enumerate(inputs):
            if op.wiring[k][0] != "ext":
                continue
            if rng_slots and slots[k] in rng_slots:
                # hoisted RNG slot: keep the LAZY key tensor — the fused
                # fire derives the key in-graph (nothing launches), and a
                # transactional split forces it then (bitwise the same
                # key, so the eager fallback samples identically)
                pending.ext_vals.append(t)
                pending.ext_edges.append(None)
                continue
            pending.ext_vals.append(t._value)
            if op.diff_mask is not None and op.diff_mask[k]:
                node = t._grad_node if t._grad_node is not None \
                    else t._ensure_grad_node()
                pending.ext_edges.append((node, t._out_index))
            else:
                pending.ext_edges.append(None)
        outs = tuple(
            _DeferredTensor(av, op.out_stop_grads[j], pending,
                            (pending.op_pos, j))
            for j, av in enumerate(op.out_avals))
        pending.placeholders.append(outs)
        pending.op_pos += 1
        pending.entry_pos += 1
        if num_outputs is not None:
            return list(outs)
        return outs[0]

    @staticmethod
    def _force_rng_ext(program, ext_vals):
        """A transactional fallback is about to replay per-op: materialize
        the lazy hoisted-key ext slots. Each derives its reserved stream
        position's exact key (fold_in(base, position)), so the eager
        fallback samples bit-identically to what the fused program would
        have computed in-graph."""
        for s in (program.rng_slots or ()):
            if s >= len(ext_vals):
                continue    # prefix split: the slot was never deferred
            t = ext_vals[s]
            if isinstance(t, Tensor):
                ext_vals[s] = t._value

    @staticmethod
    def _rng_base_data(base):
        """Raw key data of the base the cycle's keys were RESERVED
        against. Never read the live generator here: a reseed between
        dispatch and fire would make the fused derivation diverge from
        what eager (and the transactional split) samples."""
        from ..framework import random as _random
        if base is None:
            return _random.stream_base_data()
        return jax.random.key_data(base)

    def _rng_fire_args(self, pending):
        """The hoisted RNG device args of a fused fire: (base key data,
        this cycle's first stream position)."""
        return (self._rng_base_data(pending.rng_base),
                jnp.asarray(pending.rng_epoch0 or 0, jnp.int32))

    def _install_grad_placeholders(self, pending):
        program = pending.program
        phs = []
        for k, p in enumerate(pending.params):
            v = p._value
            ph = _DeferredTensor((v.shape, v.dtype, False), True, pending,
                                 ("grad", k))
            ph.name = (p.name + "@GRAD") if p.name else "grad"
            p.grad = ph
            phs.append(ph)
        pending.grad_phs = phs

    def _verify_fire(self, program, pending, opt):
        """None when the fused fire may proceed; else the reason code the
        split should carry (optimizer-state changes also kill the
        program: the baked constants are stale for good)."""
        from ..jit.train_step import bake_decay_flags
        if opt is not program.opt_ref():
            return "param_mismatch"
        params = pending.params
        ext_lists = [r[0] for r in pending.rounds] if program.super \
            else [pending.ext_vals]
        if program.spmd_plan is not None:
            from . import spmd_fusion as _spmd
            for evals in ext_lists:
                mm = _spmd.fire_mismatch(program.spmd_plan, evals, params)
                if mm is not None:
                    # the batch moved to another mesh/layout (or a
                    # parameter got sharded): the compiled collectives
                    # would run over the wrong axes — kill and let the
                    # loop re-promote with a fresh plan
                    self._kill(program, reason="mesh_mismatch")
                    return "mesh_mismatch"
        slot_items = program.param_slots.items()
        for evals in ext_lists:
            if any(evals[s] is not params[k]._value for s, k in slot_items):
                # a parameter buffer was swapped mid-cycle (in-place
                # mutation): the forward consumed the captured value, the
                # update would use the new one — not fusable
                return "param_mismatch"
        for p, nm, nc, pr in zip(params, program.param_names,
                                 program.need_clip, program.param_regs):
            if p._hooks:
                return "hook_present"
            if p.stop_gradient or p.name != nm:
                return "param_mismatch"
            if getattr(p, "need_clip", True) != nc:
                return "optimizer_state_change"
            if getattr(p, "regularizer", None) is not pr:
                return "optimizer_state_change"
            node = p._grad_node
            if node is not None and node.out_hooks:
                return "hook_present"
        own = {id(p) for p in params}
        for p in opt._parameter_list:
            if id(p) not in own and p.grad is not None:
                # an outside gradient would be updated by the eager step
                # but not by the fused one
                return "param_mismatch"
        if opt._grad_clip is not program.clip_ref \
                or _snapshot_obj(opt._grad_clip) != program.clip_snapshot:
            self._kill(program)
            return "optimizer_state_change"
        if opt.regularization is not program.reg_ref \
                or _snapshot_obj(opt.regularization) != program.reg_snapshot:
            self._kill(program)
            return "optimizer_state_change"
        bake_decay_flags(opt, params)
        if tuple(opt._extra_cache_key()) != program.extra_key:
            self._kill(program)
            return "optimizer_state_change"
        opt._create_accumulators(params)
        if tuple(sorted(opt._accumulators.keys())) != program.acc_names:
            self._kill(program)
            return "optimizer_state_change"
        return None

    def _kill(self, program, reason="optimizer_state_change"):
        """A baked-in constant (clip/regularizer attrs, optimizer hyper
        params, accumulator structure) changed: the compiled executable is
        stale for good. Drop it so a re-stabilized loop rebuilds."""
        st = self._tls
        if not program.dead:
            program.dead = True
            program.release_heavy()
            STEP_STATS.deactivated += 1
            _EVENTS.emit("step.deactivate", program.label, reason=reason)
        if st.active is program:
            st.active = None
        st.library.pop(program.sig, None)

    def _fire(self, st, pending, opt, scaler=None):
        """All entries matched and the optimizer is verified: run the ONE
        fused executable and commit. Returns False (after splitting) on a
        fault so the caller falls back to the eager step. `scaler` is the
        verified GradScaler of a scaler-folded program (on_scaler_step):
        its state rides as hoisted scalar args and the computed transition
        lands in `scaler._fused_next` for update() to commit."""
        from ..jit.train_step import bake_decay_flags
        from . import guardian as _guardian
        program = pending.program
        params = pending.params
        acc_names = program.acc_names
        check = program.check
        upd_finite = fwd_finite = scale_before = scale_after = None
        if _guardian.faults_armed() and _guardian.poll_fault(
                "fused_step", ("raise", "nan_output")) is not None:
            # fused-tier chaos: ANY untrusted fused-step output means the
            # whole transaction is suspect — recover through the
            # transactional per-op split (bitwise-identical params/grads),
            # exactly the path a real mid-fire fault takes
            self._split(pending, escape=False, reason="injected_fault",
                        blocked_op="chaos")
            return False
        st.busy = True
        if not hasattr(opt, "_step_count"):
            opt._step_count = 0
        opt._step_count += 1
        try:
            bake_decay_flags(opt, params)
            pvals = [p._value for p in params]
            ext = [pending.ext_vals[s] for s in program.ext_order]
            accs = [[opt._accumulators[n].get(p.name) for n in acc_names]
                    for p in params]
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            step_count = jnp.asarray(opt._step_count, jnp.int32)
            rng_tail = self._rng_fire_args(pending) \
                if program.rng_slots else ()
            if scaler is not None:
                scale_before, good, bad = scaler._state_arrays()
                fire_args = (pvals, ext, accs, lr, step_count, *rng_tail,
                             scale_before, good, bad)
                (root_val, grads, new_p, new_accs, upd_finite, fwd_finite,
                 found_inf, scale_after, good2, bad2) = \
                    program.exe()(*fire_args)
            elif check:
                fire_args = (pvals, ext, accs, lr, step_count, *rng_tail)
                (root_val, grads, new_p, new_accs, upd_finite,
                 fwd_finite) = program.exe()(*fire_args)
            else:
                fire_args = (pvals, ext, accs, lr, step_count, *rng_tail)
                root_val, grads, new_p, new_accs = program.exe()(
                    *fire_args)
        except jax.errors.JaxRuntimeError:
            # transient execution fault: keep the program and replay
            # eagerly — UNLESS the launch already consumed the donated
            # accumulator (or param) buffers, in which case a transparent
            # fallback is impossible and the fault must surface (the
            # eager optimizer's own donating update has the same contract)
            opt._step_count -= 1
            consumed = any(
                getattr(a, "is_deleted", lambda: False)()
                for row in accs for a in row if a is not None)
            if program.donate_params and not consumed:
                consumed = any(
                    getattr(v, "is_deleted", lambda: False)()
                    for v in pvals)
            if consumed:
                st.busy = False
                st.pending = None   # placeholders resolve via escape-split
                self._kill(program, reason="exec_fault")
                raise
            st.busy = False
            self._split(pending, escape=False, reason="exec_fault")
            return False
        except Exception:
            # the fused trace failed: never let fusion take eager down
            opt._step_count -= 1
            st.busy = False
            self._kill(program, reason="trace_fail")
            self._split(pending, escape=False, reason="trace_fail")
            return False
        try:
            for p, v in zip(params, new_p):
                p._value = v
            for p, ac in zip(params, new_accs):
                for n, v in zip(acc_names, ac):
                    if v is not None:
                        opt._accumulators[n][p.name] = v
            # the loss: served from the fused outputs, tape-marked consumed
            i, j = program.root_coord
            root_ph = pending.placeholders[i][j]
            if _VALUE_SLOT.__get__(root_ph) is _PENDING:
                _VALUE_SLOT.__set__(root_ph, root_val)
            node = FusedStepNode(program.label,
                                 (root_val.shape, root_val.dtype))
            _NODE_SLOT.__set__(root_ph, node)
            _IDX_SLOT.__set__(root_ph, 0)
            root_ph._pending_chain = None
            # raw grads land in the placeholders installed at backward
            # (scaler programs emit them UNSCALED, like the eager path)
            for ph, g in zip(pending.grad_phs, grads):
                if _VALUE_SLOT.__get__(ph) is _PENDING:
                    _VALUE_SLOT.__set__(ph, g)
                ph._pending_chain = None
            if scaler is not None:
                # update() commits this instead of re-running the
                # transition (the backoff, if any, is attributed by the
                # note_step flush below — never twice)
                scaler._found_inf = found_inf
                scaler._fused_next = (found_inf, scale_after, good2, bad2)
            if check:
                from . import guardian
                guardian.note_step(program.label, upd_finite, fwd_finite,
                                   scale_before, scale_after,
                                   step_index=opt._step_count)
            pending.fired = True
            program.fail_streak = 0
            if not program.aot_stored:
                from . import aot_cache as _aot
                if _aot.enabled():
                    # persist the ONE fused step right after it proved
                    # itself (store-if-absent; restored programs and
                    # donated-buffer shapes are both handled there)
                    program.aot_stored = True
                    _aot.store_step(program, fire_args)
            elapsed = time.perf_counter_ns() - pending.t0
            STEP_STATS.replay(program.label, program.n_launches,
                              program.baseline_ns - elapsed)
            # telemetry plane (profiler/goodput.py): per-mesh SPMD step
            # labeling + cycle-derived analytic FLOPs/step; one flag
            # check when FLAGS_metrics is off
            from ..profiler import goodput as _goodput
            _goodput.on_fused_fire(program)
            _EVENTS.emit("step.fire", program.label,
                         detail={"ops": len(program.chain.ops),
                                 "launches_saved": program.n_launches - 1})
            self._demote(pending)
        finally:
            st.busy = False
            st.pending = None
        return True

    # -- super-cycle replay internals (grad accumulation) ------------------
    @classmethod
    def _sub_fire_args(cls, program, ext_vals, rng_epoch0, acc, fwd_ok):
        """Concrete arguments of one sub-executable fire: params and side
        inputs from the round's captured ext values, the running grad
        accumulator (program zeros on round 0), and the scalar tail
        (hoisted RNG state — the base the round's keys were reserved
        against, read off the still-lazy key tensors — plus the running
        fwd-finite predicate)."""
        pvals = [None] * len(program.param_refs)
        for s, k in program.param_slots.items():
            pvals[k] = ext_vals[s]
        ext = [ext_vals[s] for s in program.ext_order]
        if acc is None:
            zeros, true = program.zero_state()
            acc = list(zeros)
            fwd_ok = true
        tail = ()
        if program.rng_slots:
            base = None
            for s in program.rng_slots:
                if s < len(ext_vals):
                    base = getattr(ext_vals[s], "_rng_base", None)
                    if base is not None:
                        break
            tail += (cls._rng_base_data(base),
                     jnp.asarray(rng_epoch0 or 0, jnp.int32))
        if program.check:
            tail += (fwd_ok,)
        return (pvals, ext, acc) + tail

    @staticmethod
    def _archive_round(pending):
        """The current micro-batch round matched completely: archive its
        captured state and reset the per-round cursors so the next event
        may open another round or hit the boundary."""
        pending.rounds.append([pending.ext_vals, pending.ext_edges,
                               pending.placeholders, pending.rng_epoch0,
                               pending.in_tail])
        if pending.in_tail:
            pending.tail_done = True
        pending.in_tail = False
        pending.ext_vals = []
        pending.ext_edges = []
        pending.placeholders = []
        pending.rng_epoch0 = None
        pending.rng_base = None
        pending.op_pos = 0
        pending.entry_pos = pending.program.seg_start

    def _fire_sub(self, st, pending):
        """Fire the micro-batch sub-executable for the just-completed
        round (gradients add into the running device accumulator) and
        archive the round. Under SPMD probation nothing fused may commit
        — the fires are deferred to the boundary — but the round archives
        either way. Returns False after a transactional split (the caller
        must run the real backward)."""
        from . import guardian as _guardian
        program = pending.program
        if _guardian.faults_armed() and _guardian.poll_fault(
                "fused_step", ("raise", "nan_output")) is not None:
            self._split(pending, escape=False, reason="injected_fault",
                        blocked_op="chaos")
            return False
        probation = program.spmd_plan is not None and not program.spmd_ok
        if not probation:
            st.busy = True
            try:
                args = self._sub_fire_args(program, pending.ext_vals,
                                           pending.rng_epoch0,
                                           pending.acc_vals,
                                           pending.fwd_ok)
                exe = program.tail_sub_exe() if pending.in_tail \
                    else program.sub_exe()
                out = exe(*args)
            except jax.errors.JaxRuntimeError:
                self._split(pending, escape=False, reason="exec_fault",
                            blocked_op="backward")
                return False
            except Exception:
                self._kill(program, reason="trace_fail")
                self._split(pending, escape=False, reason="trace_fail",
                            blocked_op="backward")
                return False
            finally:
                st.busy = False
            pending.round_losses.append(out[0])
            pending.acc_vals = list(out[1])
            if program.check:
                pending.fwd_ok = out[2]
            if not pending.in_tail:
                # AOT export specs must describe the MAIN sub's arg
                # shapes; a tail round's smaller batch would corrupt them
                pending.sub_args = args
        self._archive_round(pending)
        return True

    def _fire_super(self, st, pending, opt, scaler=None):
        """The boundary of a matched super-cycle: every round's sub fire
        already accumulated the gradient sum; run the ONE update
        executable (clip/reg + optimizer + guardian skip + scaler
        transition, all on the ACCUMULATED grads) and commit — params and
        slots in place, each round's loss placeholder from its sub
        output, p.grad from the accumulated grads. Same transactional
        contract as _fire."""
        from ..jit.train_step import bake_decay_flags
        from . import guardian as _guardian
        program = pending.program
        params = pending.params
        acc_names = program.acc_names
        check = program.check
        upd_finite = fwd_finite = scale_before = scale_after = None
        if _guardian.faults_armed() and _guardian.poll_fault(
                "fused_step", ("raise", "nan_output")) is not None:
            self._split(pending, escape=False, reason="injected_fault",
                        blocked_op="chaos")
            return False
        st.busy = True
        if not hasattr(opt, "_step_count"):
            opt._step_count = 0
        opt._step_count += 1
        try:
            bake_decay_flags(opt, params)
            pvals = [p._value for p in params]
            accs = [[opt._accumulators[n].get(p.name) for n in acc_names]
                    for p in params]
            gsum = pending.acc_vals
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            step_count = jnp.asarray(opt._step_count, jnp.int32)
            tail = ()
            if check:
                tail += (pending.fwd_ok,)
            if scaler is not None:
                scale_before, good, bad = scaler._state_arrays()
                tail += (scale_before, good, bad)
                (grads, new_p, new_accs, upd_finite, fwd_finite,
                 found_inf, scale_after, good2, bad2) = program.upd_exe()(
                    pvals, accs, gsum, lr, step_count, *tail)
            elif check:
                (grads, new_p, new_accs, upd_finite,
                 fwd_finite) = program.upd_exe()(pvals, accs, gsum, lr,
                                                 step_count, *tail)
            else:
                grads, new_p, new_accs = program.upd_exe()(
                    pvals, accs, gsum, lr, step_count)
        except jax.errors.JaxRuntimeError:
            opt._step_count -= 1
            consumed = any(
                getattr(a, "is_deleted", lambda: False)()
                for row in accs for a in row if a is not None)
            if program.donate_params and not consumed:
                consumed = any(
                    getattr(v, "is_deleted", lambda: False)()
                    for v in pvals)
            if consumed:
                st.busy = False
                st.pending = None
                self._kill(program, reason="exec_fault")
                raise
            st.busy = False
            self._split(pending, escape=False, reason="exec_fault")
            return False
        except Exception:
            opt._step_count -= 1
            st.busy = False
            self._kill(program, reason="trace_fail")
            self._split(pending, escape=False, reason="trace_fail")
            return False
        try:
            for p, v in zip(params, new_p):
                p._value = v
            for p, ac in zip(params, new_accs):
                for n, v in zip(acc_names, ac):
                    if v is not None:
                        opt._accumulators[n][p.name] = v
            # each round's loss: served from its sub-executable output,
            # tape-marked consumed (one FusedStepNode per micro-batch)
            i, j = program.root_coord
            for r, (evals, eedges, rows, ep0, _tail) in \
                    enumerate(pending.rounds):
                root_ph = rows[i][j]
                rv = pending.round_losses[r]
                if _VALUE_SLOT.__get__(root_ph) is _PENDING:
                    _VALUE_SLOT.__set__(root_ph, rv)
                node = FusedStepNode(program.label, (rv.shape, rv.dtype))
                _NODE_SLOT.__set__(root_ph, node)
                _IDX_SLOT.__set__(root_ph, 0)
                root_ph._pending_chain = None
            # accumulated grads land in the placeholders installed at the
            # first round's backward (scaler programs emit them UNSCALED,
            # exactly what the eager path leaves in p.grad)
            for ph, g in zip(pending.grad_phs, grads):
                if _VALUE_SLOT.__get__(ph) is _PENDING:
                    _VALUE_SLOT.__set__(ph, g)
                ph._pending_chain = None
            if scaler is not None:
                scaler._found_inf = found_inf
                scaler._fused_next = (found_inf, scale_after, good2, bad2)
            if check:
                from . import guardian
                guardian.note_step(program.label, upd_finite, fwd_finite,
                                   scale_before, scale_after,
                                   step_index=opt._step_count)
            pending.fired = True
            program.fail_streak = 0
            if not program.aot_stored and pending.sub_args is not None:
                from . import aot_cache as _aot
                if _aot.enabled():
                    # persist the proven PAIR once (store-if-absent; a
                    # restored pair never re-exports)
                    program.aot_stored = True
                    _aot.store_super_step(
                        program, pending.sub_args,
                        (pvals, accs, gsum, lr, step_count) + tail)
            elapsed = time.perf_counter_ns() - pending.t0
            STEP_STATS.replay(program.label, program.n_launches,
                              program.baseline_ns - elapsed)
            from ..profiler import goodput as _goodput
            _goodput.on_fused_fire(program, rounds=len(pending.rounds))
            _EVENTS.emit("step.fire", program.label,
                         detail={"ops": len(program.chain.ops),
                                 "rounds": len(pending.rounds),
                                 "launches_saved": program.n_launches
                                 - len(pending.rounds) - 1})
            self._demote(pending)
        finally:
            st.busy = False
            st.pending = None
        return True

    def _probation_super(self, st, pending, opt, scaler=None):
        """First fire of an SPMD-lowered super-cycle: run every archived
        round's sub fire plus the update on SCRATCH state, replay the
        whole accumulation eagerly (bitwise, through the transactional
        core), and compare per-round losses + accumulated grads. A
        divergence or trace failure demotes to the plain jit lowering,
        attributed `spmd_divergence`. The caller lets the eager
        optimizer/scaler step proceed."""
        import numpy as np
        from ..jit.train_step import bake_decay_flags
        from ..profiler import goodput as _goodput
        from . import spmd_fusion as _spmd
        _goodput.mark("probation")

        def scratch(v):
            return v + jnp.zeros((), v.dtype)

        program = pending.program
        params = pending.params
        acc_names = program.acc_names
        fused = None
        losses = []
        st.busy = True
        try:
            bake_decay_flags(opt, params)
            zeros, fwd_ok = program.zero_state()
            acc = [scratch(z) for z in zeros]
            for evals, eedges, rows, ep0, is_tail in pending.rounds:
                args = self._sub_fire_args(program, evals, ep0, acc,
                                           fwd_ok)
                exe = program.tail_sub_exe() if is_tail \
                    else program.sub_exe()
                out = exe(*args)
                losses.append(out[0])
                acc = list(out[1])
                if program.check:
                    fwd_ok = out[2]
            pvals = [p._value for p in params]
            if program.donate_params:
                pvals = [scratch(v) for v in pvals]
            accs = [[None if opt._accumulators[n].get(p.name) is None
                     else scratch(opt._accumulators[n][p.name])
                     for n in acc_names] for p in params]
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            step_count = jnp.asarray(
                getattr(opt, "_step_count", 0) + 1, jnp.int32)
            tail = ()
            if program.check:
                tail += (fwd_ok,)
            if scaler is not None:
                scale, good, bad = scaler._state_arrays()
                tail += (scratch(scale), scratch(good), scratch(bad))
            fused = program.upd_exe()(pvals, accs, acc, lr, step_count,
                                      *tail)
        except Exception:
            fused = None
        finally:
            st.busy = False
        self._replay_pending(pending)
        ok = fused is not None
        why = "trace_fail" if fused is None else None
        if ok:
            i, j = program.root_coord
            for r, (evals, eedges, rows, ep0, _tail) in \
                    enumerate(pending.rounds):
                ev = np.asarray(_VALUE_SLOT.__get__(rows[i][j]))
                rt, at = _spmd.probation_tolerance(ev.dtype)
                if not np.allclose(np.asarray(losses[r]), ev, rtol=rt,
                                   atol=at, equal_nan=True):
                    ok = False
                    break
            scale_np = None
            if ok and scaler is not None:
                scale_np = np.asarray(scaler._state_arrays()[0])
            if ok:
                for ph, g in zip(pending.grad_phs, fused[0]):
                    ev = _VALUE_SLOT.__get__(ph)
                    if ev is _PENDING:
                        continue
                    ev = np.asarray(ev)
                    gv = np.asarray(g)
                    if scale_np is not None:
                        gv = gv * scale_np.astype(gv.dtype)
                    rt, at = _spmd.probation_tolerance(ev.dtype)
                    if not np.allclose(gv, ev, rtol=rt, atol=at,
                                       equal_nan=True):
                        ok = False
                        break
            if not ok and why is None:
                why = "numeric_divergence"
        if ok:
            program.spmd_ok = True
            _EVENTS.emit("step.record", program.label,
                         detail={"kind": "spmd_probation", "ok": True,
                                 "super": True})
        else:
            program.spmd_plan = None
            program.spmd_ok = True
            program._exe = None
            program._sub_exe = None
            program._upd_exe = None
            program._zero_acc = None
            _EVENTS.emit("step.record", program.label,
                         reason="spmd_divergence",
                         detail={"kind": "spmd_probation", "ok": False,
                                 "why": why, "super": True})

    @staticmethod
    def _demote(pending):
        """Release the fired step's retention (ROADMAP item 4(c)): swap
        the placeholder store to weakrefs, breaking the strong
        pending↔placeholder cycle that used to keep `ext_vals` — the
        PRE-UPDATE parameter buffers and the batch arrays among them —
        alive into the next step (until a gc pass, in the worst case).
        Post-demote the pending survives only through placeholders the
        CALLER still references (each holds `_pending_chain` strongly),
        so in the common loop — where mid-step intermediates are
        temporaries — everything, ext store included, is refcount-freed
        before `optimizer.step()` returns. A caller that kept an
        intermediate keeps exactly the state its post-fire lazy
        recompute needs, no more."""
        pending.placeholders = [[weakref.ref(t) for t in row]
                                for row in pending.placeholders]
        for rnd in pending.rounds:
            rnd[2] = [[weakref.ref(t) for t in row] for row in rnd[2]]
        # grads were committed to p.grad and the loss to its own handle;
        # the pending's strong duplicates would pin those buffers past
        # clear_grad()
        pending.grad_phs = None
        pending.params = ()
        pending.round_losses = []
        pending.acc_vals = None
        pending.fwd_ok = None

    def _probation(self, st, pending, opt, scaler=None):
        """First fire of an SPMD-lowered program (ops/spmd_fusion.py): run
        the shard_map executable on scratch copies of the donated buffers,
        then replay the step EAGERLY through the transactional core — this
        step's numerics stay bitwise-identical to unfused dispatch — and
        compare loss + grads. A match validates the distributed lowering
        (the next fire commits fused results); a divergence (a sum-reduced
        loss, a batch-coupled op — anything outside the data-parallel
        pmean contract) demotes the program to the plain jit lowering,
        attributed as `spmd_divergence`. Callers hold pending.lock; the
        caller must let the eager optimizer step proceed."""
        import numpy as np
        from ..jit.train_step import bake_decay_flags
        from ..profiler import goodput as _goodput
        from . import spmd_fusion as _spmd
        # goodput: this interval is a probation replay (fused + bitwise
        # eager both run), not a normal productive step
        _goodput.mark("probation")

        def scratch(v):
            # a DISTINCT buffer with the same value and placement, so the
            # executable's donation can never consume live state
            return v + jnp.zeros((), v.dtype)

        program = pending.program
        params = pending.params
        acc_names = program.acc_names
        fused = None
        st.busy = True
        try:
            bake_decay_flags(opt, params)
            pvals = [p._value for p in params]
            if program.donate_params:
                pvals = [scratch(v) for v in pvals]
            ext = [pending.ext_vals[s] for s in program.ext_order]
            accs = [[None if opt._accumulators[n].get(p.name) is None
                     else scratch(opt._accumulators[n][p.name])
                     for n in acc_names] for p in params]
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            step_count = jnp.asarray(
                getattr(opt, "_step_count", 0) + 1, jnp.int32)
            rng_tail = self._rng_fire_args(pending) \
                if program.rng_slots else ()
            if scaler is not None:
                scale, good, bad = scaler._state_arrays()
                fused = program.exe()(pvals, ext, accs, lr, step_count,
                                      *rng_tail, scratch(scale),
                                      scratch(good), scratch(bad))
            else:
                fused = program.exe()(pvals, ext, accs, lr, step_count,
                                      *rng_tail)
        except Exception:
            # the distributed lowering failed to trace/execute (a baked
            # global shape, an op the manual mapping rejects): demote to
            # the plain jit lowering — still ONE executable — and replay
            # this step eagerly
            fused = None
        finally:
            st.busy = False
        self._replay_pending(pending)
        ok = fused is not None
        why = "trace_fail" if fused is None else None
        if ok:
            i, j = program.root_coord
            root_ph = pending.placeholders[i][j]
            eager_loss = np.asarray(_VALUE_SLOT.__get__(root_ph))
            rtol, atol = _spmd.probation_tolerance(eager_loss.dtype)
            ok = bool(np.allclose(np.asarray(fused[0]), eager_loss,
                                  rtol=rtol, atol=atol, equal_nan=True))
            scale_np = None
            if ok and scaler is not None:
                # fused grads are UNSCALED; the eager tape's (pre-
                # scaler.step) grads still carry the loss scale
                scale_np = np.asarray(scaler._state_arrays()[0])
            if ok:
                for ph, g in zip(pending.grad_phs, fused[1]):
                    ev = _VALUE_SLOT.__get__(ph)
                    if ev is _PENDING:
                        continue
                    ev = np.asarray(ev)
                    gv = np.asarray(g)
                    if scale_np is not None:
                        gv = gv * scale_np.astype(gv.dtype)
                    rt, at = _spmd.probation_tolerance(ev.dtype)
                    if not np.allclose(gv, ev, rtol=rt, atol=at,
                                       equal_nan=True):
                        ok = False
                        break
            if not ok and why is None:
                why = "numeric_divergence"
        if ok:
            program.spmd_ok = True
            _EVENTS.emit("step.record", program.label,
                         detail={"kind": "spmd_probation", "ok": True})
        else:
            program.spmd_plan = None
            program.spmd_ok = True
            program._exe = None
            _EVENTS.emit("step.record", program.label,
                         reason="spmd_divergence",
                         detail={"kind": "spmd_probation", "ok": False,
                                 "why": why})

    def resolve_pending(self, pending, escape):
        """Owner-protocol escape hatch (ops/fusion._DeferredTensor._force).
        Pre-fire: any touch of a pending placeholder splits the replay.
        Post-fire: intermediates are lazily recomputed through the per-op
        path (the fused step only materialized the loss and the grads)."""
        st = self._tls
        with pending.lock:
            if pending.done:
                pass
            elif pending.fired:
                self._recompute(pending)
            else:
                self._split(pending, escape=escape)
        if st.pending is pending:
            st.pending = None

    def _recompute(self, pending):
        """A placeholder of a FIRED step was read: materialize every
        intermediate via the per-op cached path from the captured external
        inputs (the pre-update parameter values among them). The store
        was demoted to weakrefs at the fire (`_demote`); the reader that
        triggered this keeps its own chain of placeholders alive, and
        rows that died anyway are replayed through throwaway carriers —
        their values exist only long enough to feed downstream ops."""
        st = self._tls
        st.busy = True
        try:
            program = pending.program

            def revive(store):
                rows = []
                for row in store:
                    live = []
                    for ref in row:
                        t = ref()
                        if t is None:
                            t = _DeferredTensor(None, True, None, None)
                        live.append(t)
                    rows.append(live)
                return rows

            if program.super:
                # a fired super-cycle's intermediates: every round
                # replays from its own captured inputs (tail rounds
                # through the tail op template)
                for evals, eedges, store, _ep, is_tail in pending.rounds:
                    ops = program.tail_chain.ops if is_tail \
                        else program.chain.ops
                    self._force_rng_ext(program, evals)
                    replay_ops_per_op(ops, evals, eedges,
                                      revive(store), len(ops),
                                      skip_materialized=True)
                pending.done = True
                return
            self._force_rng_ext(program, pending.ext_vals)
            replay_ops_per_op(program.chain.ops, pending.ext_vals,
                              pending.ext_edges, revive(pending.placeholders),
                              pending.op_pos, skip_materialized=True)
            pending.done = True
        finally:
            st.busy = False

    def _replay_pending(self, pending):
        """The bitwise transactional core: replay the deferred prefix
        per-op and, if the backward event was already consumed, run the
        real tape backward so p.grad holds exactly what unfused dispatch
        would have produced. Shared by `_split` (failure fallback) and
        `_probation` (the SPMD first-fire validation, which is not a
        failure). Callers hold pending.lock."""
        st = self._tls
        program = pending.program
        if program.super:
            return self._replay_pending_super(pending)
        st.busy = True
        try:
            self._force_rng_ext(program, pending.ext_vals)
            replay_ops_per_op(program.chain.ops, pending.ext_vals,
                              pending.ext_edges, pending.placeholders,
                              pending.op_pos)
            if pending.backward_done:
                for p in pending.params:
                    p.grad = None
                i, j = program.root_coord
                root = pending.placeholders[i][j]
                node = _NODE_SLOT.__get__(root)
                if node is not None:
                    seed = _autograd._one_cotangent(
                        _VALUE_SLOT.__get__(root).shape,
                        _VALUE_SLOT.__get__(root).dtype)
                    run_backward(node, _IDX_SLOT.__get__(root), seed)
                for p, ph in zip(pending.params, pending.grad_phs):
                    real = p.grad
                    if real is not None:
                        if _VALUE_SLOT.__get__(ph) is _PENDING:
                            _VALUE_SLOT.__set__(ph, real._value)
                        ph._pending_chain = None
                        p.grad = ph
                    else:
                        ph._pending_chain = None
            pending.done = True
        finally:
            st.busy = False

    def _replay_pending_super(self, pending):
        """The super-cycle transactional core: replay every archived
        round per-op AND run its real tape backward (p.grad accumulates
        across rounds exactly as unfused dispatch would), then replay the
        current round's deferred prefix. Nothing fused ever committed —
        the sub fires only touched scratch accumulators — so the result
        is bitwise-identical to eager execution. Callers hold
        pending.lock."""
        st = self._tls
        program = pending.program
        st.busy = True
        try:
            params = pending.params
            i, j = program.root_coord
            if pending.rounds:
                # the cycle began with fresh grads (verified at round 0's
                # backward): re-accumulate from scratch
                for p in params:
                    p.grad = None
            for evals, eedges, rows, _ep, is_tail in pending.rounds:
                ops = program.tail_chain.ops if is_tail \
                    else program.chain.ops
                self._force_rng_ext(program, evals)
                replay_ops_per_op(ops, evals, eedges, rows, len(ops))
                root = rows[i][j]
                node = _NODE_SLOT.__get__(root)
                if node is not None:
                    seed = _autograd._one_cotangent(
                        _VALUE_SLOT.__get__(root).shape,
                        _VALUE_SLOT.__get__(root).dtype)
                    run_backward(node, _IDX_SLOT.__get__(root), seed)
            # current round's deferred prefix (its backward — if one is in
            # flight — is run by the caller on the replayed real graph)
            cur_ops = self._round_template(program, pending)[0].ops
            self._force_rng_ext(program, pending.ext_vals)
            replay_ops_per_op(cur_ops, pending.ext_vals,
                              pending.ext_edges, pending.placeholders,
                              pending.op_pos)
            if pending.grad_phs is not None:
                if not pending.rounds:
                    # split before any round committed (a round-0 sub
                    # fault): grads are None exactly as eager would have
                    # them — withdraw the installed placeholders
                    for p, ph in zip(params, pending.grad_phs):
                        if p.grad is ph:
                            p.grad = None
                        ph._pending_chain = None
                    pending.grad_phs = None
                else:
                    for p, ph in zip(params, pending.grad_phs):
                        real = p.grad
                        if real is not None and real is not ph:
                            if _VALUE_SLOT.__get__(ph) is _PENDING:
                                _VALUE_SLOT.__set__(ph, real._value)
                            ph._pending_chain = None
                            p.grad = ph
                        else:
                            ph._pending_chain = None
            pending.done = True
        finally:
            st.busy = False

    def _split(self, pending, escape, reason=None, blocked_op=None):
        """Transactional fallback: the deferred prefix replays per-op; if
        the backward event was already consumed, the real tape backward
        runs so p.grad holds exactly what unfused dispatch would have
        produced. Callers hold pending.lock. `reason` is the
        flight-recorder attribution (a REASON_CODES entry); `blocked_op`
        names the dispatch/event that broke the replay."""
        st = self._tls
        program = pending.program
        if pending.done:
            return
        try:
            self._replay_pending(pending)
            program.fail_streak += 1
            deactivated = False
            if program.fail_streak >= _MAX_FAIL_STREAK \
                    and not program.dead:
                program.dead = True
                deactivated = True
                program.release_heavy()
                STEP_STATS.deactivated += 1
                if st.active is program:
                    st.active = None
            STEP_STATS.split(program.label, escape=escape)
            if reason is None:
                reason = "mid_step_peek" if escape else "key_mismatch"
            detail = {"entry_pos": pending.entry_pos,
                      "op_pos": pending.op_pos,
                      "ops": len(program.chain.ops)}
            if blocked_op:
                detail["blocked_op"] = blocked_op
            if deactivated:
                detail["deactivated"] = True
            _EVENTS.emit("step.split", program.label, reason=reason,
                         detail=detail)
            if deactivated:
                _EVENTS.emit("step.deactivate", program.label,
                             reason="fail_streak")
            self._mark_dirty(st)
        finally:
            if st.pending is pending:
                st.pending = None

    # -- cycle boundary / promotion ----------------------------------------
    def _mark_dirty(self, st):
        if st.recording is None:
            st.recording = _Cycle()
        st.recording.poison()

    def _poison(self, st, reason, op=""):
        """Mark the observation cycle un-promotable AND record why in the
        flight recorder. The (reason, op) pairs emitted here are exactly
        what the fusion doctor aggregates into "step never promoted:
        <op> <reason> ×N" — every poison call emits (not just the first
        of a cycle) so per-cycle multiplicity survives into the report."""
        if st.recording is None:
            st.recording = _Cycle()
        cyc = st.recording
        _EVENTS.emit("step.record", op, reason=reason,
                     detail={"kind": "poison", "pos": len(cyc.ops),
                             "first": not cyc.dirty})
        cyc.poison()

    def _after_boundary(self, st):
        st.recording = _Cycle()
        st.replay_arm = st.active is not None

    def _boundary(self, st, opt, dirty):
        cyc = st.recording
        if cyc is None or dirty or cyc.dirty:
            _EVENTS.emit("step.record", "optimizer_step",
                         detail={"kind": "cycle", "clean": False})
            st.prev_sig, st.streak = None, 0
            self._after_boundary(st)
            return
        updated = [p for p in opt._parameter_list if p.grad is not None]
        cyc.entries.append(("step", id(opt), tuple(id(p) for p in updated)))
        sig = tuple(cyc.entries)
        if cyc.n_backward > 1:
            # grad accumulation: canonicalize k×(fwd+bwd)+step into the
            # k-INDEPENDENT super-cycle signature, so a k=4 warm-up
            # promotes a program that replays at any k without recompiling
            ssig = self._super_sig(sig)
            if ssig is not None:
                sig = ssig
        if sig == st.prev_sig:
            st.streak += 1
        else:
            st.prev_sig, st.streak = sig, 1
        _EVENTS.emit("step.record", "optimizer_step",
                     detail={"kind": "cycle", "clean": True,
                             "ops": len(cyc.ops), "streak": st.streak})
        min_count = int(
            _FLAGS.get("FLAGS_eager_step_fusion_min_count", 40) or 1)
        promote = st.streak >= min_count
        warm = False
        if not promote and sig not in st.library:
            # AOT warm start (ops/aot_cache.py): when the store already
            # holds this cycle's compiled step, the stability threshold is
            # moot — a restarting worker promotes on its FIRST clean cycle
            # and fires the restored executable on the next one
            warm = self._aot_step_digest(st, sig, opt, updated) is not None
            promote = warm
        if promote:
            program = st.library.get(sig)
            if program is None and sig not in st.library:
                program = self._build(st, cyc, sig, opt, updated,
                                      warm=warm)
                st.library[sig] = program if program is not None \
                    else _UNBUILDABLE
                cap = int(_FLAGS.get("FLAGS_eager_step_fusion_cache_size",
                                     8) or 0)
                while len(st.library) > max(cap, 1):
                    st.library.popitem(last=False)
            if isinstance(program, _StepProgram) and not program.dead:
                st.library.move_to_end(sig)
                st.active = program
        self._after_boundary(st)

    @staticmethod
    def _super_sig(entries):
        """Canonical k-independent signature of a grad-accumulation
        super-cycle, or None when the shape is not recognizable.
        Recognized: [cg?] + k×(ops…, bwd) + [scaler?] + step with k ≥ 2,
        all k segments structurally identical after rebasing wiring, bwd
        coords, and hoisted-RNG stream deltas to segment-local form, and
        NO dataflow crossing a segment boundary."""
        step_e = entries[-1]
        body = list(entries[:-1])
        cg = None
        if body and body[0][0] == "cg":
            cg = body.pop(0)
        scaler_e = None
        if body and body[-1][0] == "scaler":
            scaler_e = body.pop()
        if not body or any(e[0] not in ("op", "bwd") for e in body):
            return None
        cuts = [i for i, e in enumerate(body) if e[0] == "bwd"]
        k = len(cuts)
        if k < 2 or cuts[-1] != len(body) - 1:
            return None
        seg_len = cuts[0] + 1
        if len(body) != k * seg_len \
                or any(cuts[s] != (s + 1) * seg_len - 1 for s in range(k)):
            return None
        canon = []
        for s in range(k):
            seg = body[s * seg_len:(s + 1) * seg_len]
            base = s * (seg_len - 1)       # recorded ops per segment
            rebased = []
            rng0 = None
            for e in seg[:-1]:
                wiring = []
                for w in e[2]:
                    if w[0] == "prev":
                        i2 = w[1] - base
                        if i2 < 0:
                            return None    # cross-segment dataflow
                        wiring.append(("prev", i2, w[2]))
                    else:
                        wiring.append(w)
                ent = ("op", e[1], tuple(wiring), e[3], e[4])
                if len(e) > 5:
                    marks = []
                    for ki, d in e[5]:
                        if rng0 is None:
                            rng0 = d   # segment-local stream anchor
                        marks.append((ki, d - rng0))
                    ent += (tuple(marks),)
                rebased.append(ent)
            bcoord = seg[-1][1]
            if bcoord is None:
                return None
            bi = bcoord[0] - base
            if bi < 0 or bi >= seg_len - 1:
                return None
            rebased.append(("bwd", (bi, bcoord[1])))
            canon.append(tuple(rebased))
        if any(c != canon[0] for c in canon[1:]):
            # Ragged tail: k−1 identical full segments + one differing
            # final segment (the epoch-boundary short micro-batch). The
            # tail shape joins the signature — same sig on every epoch,
            # one extra tail sub-executable, still ≤3 programs total.
            if k >= 3 and canon[-1] != canon[0] \
                    and all(c == canon[0] for c in canon[1:-1]):
                return ("super", cg, canon[0], scaler_e, step_e,
                        canon[-1])
            return None
        return ("super", cg, canon[0], scaler_e, step_e)

    def _aot_step_digest(self, st, sig, opt, updated):
        """The warm-start probe: this cycle's AOT step digest when the
        store holds a matching artifact, else None. The digest computation
        (canonicalizing every op key) is memoized per sig; the existence
        check re-runs each boundary — another worker may populate the
        shared store at any time."""
        from . import aot_cache as _aot
        if not _aot.enabled():
            return None
        dg = st.aot_probe.get(sig, 0)
        if dg == 0:
            dg = _aot.step_digest(sig, opt, updated)
            if len(st.aot_probe) > 64:
                st.aot_probe.clear()
            st.aot_probe[sig] = dg
        if dg is not None and _aot.has_step(dg):
            return dg
        return None

    def _build(self, st, cyc, sig, opt, updated, warm=False):
        """Compile-time qualification + program construction from the last
        observed cycle. Returns None when the cycle cannot promote — every
        None is attributed in the flight recorder (`unpromotable_cycle`
        with a `why` detail) so a loop that records clean cycles but never
        promotes still explains itself."""
        from ..jit.train_step import bake_decay_flags

        if sig and sig[0] == "super":
            return self._build_super(st, cyc, sig, opt, updated, warm=warm)

        def unbuildable(why, op=""):
            _EVENTS.emit("step.record", op, reason="unpromotable_cycle",
                         detail={"kind": "build_fail", "why": why})
            return None

        entries = []
        bwd_entries = [e for e in cyc.entries if e[0] == "bwd"]
        if len(bwd_entries) > 1:
            # a multi-backward cycle that _super_sig could NOT
            # canonicalize (irregular segments, cross-micro-batch
            # dataflow): name the real blocker instead of a generic fail
            return unbuildable("irregular_accum", op="backward")
        if len(bwd_entries) != 1 or bwd_entries[0][1] is None \
                or not cyc.ops or not updated:
            return unbuildable("no_backward_or_params")
        if any(p._hooks or p.stop_gradient for p in updated):
            return unbuildable("param_hooks")
        for p in updated:
            node = p._grad_node
            if node is not None and node.out_hooks:
                return unbuildable("param_hooks")
        ops = [
            _ChainOp(r.name, r.key, r.fn, r.wiring, r.diff_mask,
                     r.num_outputs, r.out_avals, r.out_stop_grads)
            for r in cyc.ops]
        chain = Chain(sig, ops, 0)
        if not chain.grad_mode:
            return unbuildable("no_grad_ops")
        # GradScaler folding (on_scaler_step): requires the guardian —
        # the in-graph where() skip is what makes an unconditional fused
        # update legal — and the scaler event must follow the backward
        # (unscale consumes its grads)
        scaler_es = [e for e in cyc.entries if e[0] == "scaler"]
        scaler_obj = cyc.scaler
        if len(scaler_es) > 1:
            return unbuildable("multi_scaler")
        if scaler_es:
            if scaler_obj is None or id(scaler_obj) != scaler_es[0][1]:
                return unbuildable("scaler_gone")
            if not chain.check:
                return unbuildable("scaler_without_guardian")
            order = [e[0] for e in cyc.entries]
            if order.index("scaler") < order.index("bwd"):
                return unbuildable("scaler_before_backward")
        else:
            scaler_obj = None
        # flat index of the backward root in the chain's output catalog
        root_coord = bwd_entries[0][1]
        root_flat = None
        for flat, owner in enumerate(chain.owners):
            if owner == root_coord:
                root_flat = flat
                break
        if root_flat is None:
            return unbuildable("root_not_in_chain")
        # classify external slots: every differentiable ext input must be
        # one of the optimizer's updated params, every updated param must
        # appear (otherwise the eager step and the fused step would update
        # different sets)
        param_idx = {id(p): k for k, p in enumerate(updated)}
        slot_inputs = {}
        for i, rec in enumerate(cyc.ops):
            slots = chain.ext_of[i]
            for k, s in enumerate(slots):
                if s is not None:
                    slot_inputs[s] = rec.ins[k]
        param_slots = {}
        for s in chain.diff_ext_idx:
            k = param_idx.get(id(slot_inputs[s]))
            if k is None:
                # a differentiable external input that is not an updated
                # parameter (e.g. a float mask with stop_gradient=False)
                return unbuildable("nonparam_diff_input")
            param_slots[s] = k
        if {k for k in param_slots.values()} != set(range(len(updated))):
            return unbuildable("param_set_mismatch")
        # hoisted RNG slots: {ext slot -> stream delta} from the recorded
        # per-op marks — these slots are derived in-graph at fire time
        rng_slots = {}
        op_i = 0
        for e in cyc.entries:
            if e[0] != "op":
                continue
            if len(e) > 5:
                for k, delta in e[5]:
                    s = chain.ext_of[op_i][k]
                    if s is None or s in param_slots:
                        return unbuildable("rng_wiring")
                    rng_slots[s] = delta
            op_i += 1
        # events with per-op entries collapsed to ("op",) markers, in order
        # (the trailing ("step", ...) sig entry becomes the terminal event)
        op_iter = 0
        for e in cyc.entries:
            if e[0] == "op":
                entries.append(("op", op_iter))
                op_iter += 1
            elif e[0] != "step":
                entries.append(e)
        entries.append(("step",))
        program = _StepProgram()
        program.sig = sig
        program.chain = chain
        program.entries = tuple(entries)
        program.root_coord = root_coord
        program.root_flat = root_flat
        program.param_refs = tuple(weakref.ref(p) for p in updated)
        program.param_names = tuple(p.name for p in updated)
        program.param_regs = tuple(
            getattr(p, "regularizer", None) for p in updated)
        program.need_clip = tuple(
            getattr(p, "need_clip", True) for p in updated)
        program.param_slots = param_slots
        program.rng_slots = rng_slots
        program.ext_order = tuple(
            s for s in range(chain.n_ext)
            if s not in param_slots and s not in rng_slots)
        program.opt_ref = weakref.ref(opt)
        program.clip_ref = opt._grad_clip
        program.clip_snapshot = _snapshot_obj(opt._grad_clip)
        program.reg_ref = opt.regularization
        program.reg_snapshot = _snapshot_obj(opt.regularization)
        bake_decay_flags(opt, updated)
        program.extra_key = tuple(opt._extra_cache_key())
        program.acc_names = tuple(sorted(opt._accumulators.keys()))
        program.check = chain.check
        if scaler_obj is not None:
            program.scaler_ref = weakref.ref(scaler_obj)
            program.scaler_consts = scaler_es[0][2]
        # distributed lowering (ops/spmd_fusion.py): when the cycle's
        # inputs live sharded on a mesh, the step compiles through
        # shard_map with the collectives fused in — validated by a
        # probation fire before any fused result commits
        from . import spmd_fusion as _spmd
        plan, plan_reason = _spmd.plan_program(
            chain, slot_inputs, program.ext_order, updated, opt,
            program.acc_names, root_flat)
        if plan_reason is not None:
            # a mesh-level contradiction (inputs spanning meshes) is a
            # first-class reason code, not an anonymous build detail
            _EVENTS.emit("step.record", "", reason=plan_reason,
                         detail={"kind": "build_fail"})
        if plan is not None:
            program.spmd_plan = plan
            program.spmd_ok = False
        names = [op.name for op in ops]
        head = "→".join(names[:3]) + ("→…" if len(names) > 3 else "")
        program.label = (f"{head}[{len(ops)}ops]"
                         f"+{type(opt).__name__}"
                         + ("+GradScaler" if scaler_obj is not None else "")
                         + (f"@mesh[{plan.axes_label}]"
                            if plan is not None else ""))
        program.n_launches = len(ops) + sum(
            1 for op in ops if op.diff_mask is not None) + 1 \
            + (2 if scaler_obj is not None else 0)
        program.baseline_ns = time.perf_counter_ns() - cyc.t0
        program.donate_params = bool(
            _FLAGS.get("FLAGS_eager_step_fusion_donate_params"))
        from . import aot_cache as _aot
        if _aot.enabled():
            # SPMD programs participate too: the env fingerprint's mesh
            # topology token keys artifacts to one mesh shape, so a
            # shard_map module only ever reloads on the topology it was
            # exported from (same-digest different-sharding is impossible
            # across topologies, and within one mesh the plan is a pure
            # function of the cycle)
            dg = st.aot_probe.get(sig, 0)
            program.aot_digest = dg if dg != 0 \
                else _aot.step_digest(sig, opt, updated)
            if warm:
                # AOT warm promote: pull the stored executable NOW so the
                # very next replay fires it — and a restored SPMD program
                # has probation waived before the replay's probation
                # check runs (see exe())
                program.exe()
        STEP_STATS.promoted(program.label)
        _EVENTS.emit("step.promote", program.label,
                     detail={"ops": len(ops), "params": len(updated),
                             "launches_estimate": program.n_launches,
                             "warm_start": warm,
                             "spmd": plan is not None,
                             "mesh": plan.axes_label if plan is not None
                             else None})
        return program

    def _build_super(self, st, cyc, sig, opt, updated, warm=False):
        """Super-cycle qualification + program construction. `sig` is the
        canonical ("super", cg, segment entries, scaler, step) form from
        _super_sig; `cyc` holds the k identically-recorded segments. The
        program's chain is ONE segment — the sub/update executable pair
        replays it at any k."""
        from ..jit.train_step import bake_decay_flags

        def unbuildable(why, op=""):
            _EVENTS.emit("step.record", op, reason="unpromotable_cycle",
                         detail={"kind": "build_fail", "why": why,
                                 "super": True})
            return None

        _tag, cg_e, seg_entries, scaler_e, _step_e = sig[:5]
        tail_entries = sig[5] if len(sig) > 5 else None
        seg_ops = len(seg_entries) - 1
        k = cyc.n_backward
        if not cyc.ops or not updated:
            return unbuildable("no_backward_or_params")
        if any(p._hooks or p.stop_gradient for p in updated):
            return unbuildable("param_hooks")
        for p in updated:
            node = p._grad_node
            if node is not None and node.out_hooks:
                return unbuildable("param_hooks")
        recs = cyc.ops[:seg_ops]
        # segment 0's recorded wiring is already segment-local (its op
        # indices start at 0), so the recs translate directly
        ops = [
            _ChainOp(r.name, r.key, r.fn, r.wiring, r.diff_mask,
                     r.num_outputs, r.out_avals, r.out_stop_grads)
            for r in recs]
        chain = Chain(sig, ops, 0)
        if not chain.grad_mode:
            return unbuildable("no_grad_ops")
        scaler_obj = cyc.scaler
        if scaler_e is not None:
            if scaler_obj is None or id(scaler_obj) != scaler_e[1]:
                return unbuildable("scaler_gone")
            if not chain.check:
                return unbuildable("scaler_without_guardian")
        else:
            scaler_obj = None
        root_coord = seg_entries[-1][1]
        root_flat = None
        for flat, owner in enumerate(chain.owners):
            if owner == root_coord:
                root_flat = flat
                break
        if root_flat is None:
            return unbuildable("root_not_in_chain")
        param_idx = {id(p): kk for kk, p in enumerate(updated)}
        slot_inputs = {}
        for i, rec in enumerate(recs):
            slots = chain.ext_of[i]
            for k2, s in enumerate(slots):
                if s is not None:
                    slot_inputs[s] = rec.ins[k2]
        param_slots = {}
        for s in chain.diff_ext_idx:
            kk = param_idx.get(id(slot_inputs[s]))
            if kk is None:
                return unbuildable("nonparam_diff_input")
            param_slots[s] = kk
        if {v for v in param_slots.values()} != set(range(len(updated))):
            return unbuildable("param_set_mismatch")
        # every segment must feed the SAME param objects into the param
        # slots — micro-batches vary the data, never the binding
        for seg in range(1, k):
            base = seg * seg_ops
            for i in range(seg_ops):
                slots = chain.ext_of[i]
                for k2, s in enumerate(slots):
                    if s in param_slots and \
                            cyc.ops[base + i].ins[k2] is not recs[i].ins[k2]:
                        return unbuildable("accum_param_mismatch")
        # hoisted RNG slots (segment-relative stream deltas)
        rng_slots = {}
        for i, e in enumerate(seg_entries[:-1]):
            if len(e) > 5:
                for k2, delta in e[5]:
                    s = chain.ext_of[i][k2]
                    if s is None or s in param_slots:
                        return unbuildable("rng_wiring")
                    rng_slots[s] = delta
        # ragged tail: build the tail segment's own chain. It compiles to
        # a SECOND sub-executable that adds into the same accumulator —
        # grads share the param avals regardless of batch shape — so the
        # program stays ≤3 executables (main sub, tail sub, update).
        tail_chain = tail_root_flat = None
        tail_rng_slots = {}
        if tail_entries is not None:
            tail_base = (k - 1) * seg_ops
            recs_tail = cyc.ops[tail_base:]
            tail_ops = []
            for r in recs_tail:
                # recorded wiring is cycle-global; rebase to tail-local
                # (cross-segment dataflow already excluded by _super_sig)
                wiring = tuple(
                    ("prev", w[1] - tail_base, w[2]) if w[0] == "prev"
                    else w
                    for w in r.wiring)
                tail_ops.append(_ChainOp(
                    r.name, r.key, r.fn, wiring, r.diff_mask,
                    r.num_outputs, r.out_avals, r.out_stop_grads))
            tail_chain = Chain(sig, tail_ops, 0)
            if not tail_chain.grad_mode \
                    or tail_chain.n_ext != chain.n_ext:
                return unbuildable("ragged_tail_mismatch")
            # the tail must bind the SAME param objects into the SAME
            # slots — only the data inputs (the short batch) may differ
            for i, r in enumerate(recs_tail):
                slots = tail_chain.ext_of[i]
                for k2, s in enumerate(slots):
                    if s in param_slots \
                            and r.ins[k2] is not slot_inputs[s]:
                        return unbuildable("ragged_tail_mismatch")
            troot = tail_entries[-1][1]
            for flat, owner in enumerate(tail_chain.owners):
                if owner == troot:
                    tail_root_flat = flat
                    break
            if tail_root_flat is None:
                return unbuildable("root_not_in_chain")
            for i, e in enumerate(tail_entries[:-1]):
                if len(e) > 5:
                    for k2, delta in e[5]:
                        s = tail_chain.ext_of[i][k2]
                        if s is None or s in param_slots:
                            return unbuildable("rng_wiring")
                        tail_rng_slots[s] = delta
            if set(tail_rng_slots) != set(rng_slots):
                return unbuildable("ragged_tail_mismatch")
        entries = []
        if cg_e is not None:
            entries.append(cg_e)
        seg_start = len(entries)
        for i in range(seg_ops):
            entries.append(("op", i))
        entries.append(("bwd",))
        if scaler_e is not None:
            entries.append(scaler_e)
        entries.append(("step",))
        program = _StepProgram()
        program.super = True
        program.seg_start = seg_start
        program.sig = sig
        program.chain = chain
        program.tail_chain = tail_chain
        program.tail_root_flat = tail_root_flat
        program.tail_rng_slots = tail_rng_slots
        program.entries = tuple(entries)
        program.root_coord = root_coord
        program.root_flat = root_flat
        program.param_refs = tuple(weakref.ref(p) for p in updated)
        program.param_names = tuple(p.name for p in updated)
        program.param_regs = tuple(
            getattr(p, "regularizer", None) for p in updated)
        program.need_clip = tuple(
            getattr(p, "need_clip", True) for p in updated)
        program.param_slots = param_slots
        program.rng_slots = rng_slots
        program.ext_order = tuple(
            s for s in range(chain.n_ext)
            if s not in param_slots and s not in rng_slots)
        program.opt_ref = weakref.ref(opt)
        program.clip_ref = opt._grad_clip
        program.clip_snapshot = _snapshot_obj(opt._grad_clip)
        program.reg_ref = opt.regularization
        program.reg_snapshot = _snapshot_obj(opt.regularization)
        bake_decay_flags(opt, updated)
        program.extra_key = tuple(opt._extra_cache_key())
        opt._create_accumulators(updated)
        program.acc_names = tuple(sorted(opt._accumulators.keys()))
        program.check = chain.check
        if scaler_obj is not None:
            program.scaler_ref = weakref.ref(scaler_obj)
            program.scaler_consts = scaler_e[2]
        from . import spmd_fusion as _spmd
        plan, plan_reason = _spmd.plan_program(
            chain, slot_inputs, program.ext_order, updated, opt,
            program.acc_names, root_flat)
        if plan_reason is not None:
            _EVENTS.emit("step.record", "", reason=plan_reason,
                         detail={"kind": "build_fail"})
        if plan is not None and not plan.data_axes:
            # no batch axis to defer the gradient pmean over: the plain
            # GSPMD lowering already does the right thing
            plan = None
        if plan is not None:
            program.spmd_plan = plan
            program.spmd_ok = False
        names = [op.name for op in ops]
        head = "→".join(names[:3]) + ("→…" if len(names) > 3 else "")
        program.label = (f"{head}[{len(ops)}ops×k]"
                         f"+{type(opt).__name__}+accum"
                         + ("+GradScaler" if scaler_obj is not None else "")
                         + (f"@mesh[{plan.axes_label}]"
                            if plan is not None else ""))
        program.n_launches = k * (len(ops) + sum(
            1 for op in ops if op.diff_mask is not None) + 1) + 1 \
            + (2 if scaler_obj is not None else 0)
        program.baseline_ns = time.perf_counter_ns() - cyc.t0
        program.donate_params = bool(
            _FLAGS.get("FLAGS_eager_step_fusion_donate_params"))
        from . import aot_cache as _aot
        if _aot.enabled():
            dg = st.aot_probe.get(sig, 0)
            program.aot_digest = dg if dg != 0 \
                else _aot.step_digest(sig, opt, updated)
            if warm:
                # AOT warm promote: restore the (sub, update) pair NOW —
                # probation defers sub fires, so a lazy load would never
                # be reached before the probation decision; an eagerly
                # restored SPMD pair waives probation instead
                program._maybe_load_super()
        STEP_STATS.promoted(program.label)
        _EVENTS.emit("step.promote", program.label,
                     detail={"ops": len(ops), "params": len(updated),
                             "super": True, "rounds_seen": k,
                             "launches_estimate": program.n_launches,
                             "warm_start": warm,
                             "spmd": plan is not None,
                             "mesh": plan.axes_label if plan is not None
                             else None})
        return program

    def _disable(self, st):
        """Flag flipped off mid-run: resolve and forget everything."""
        if st.pending is not None and not st.pending.fired:
            with st.pending.lock:
                if not st.pending.done:
                    self._split(st.pending, escape=False,
                                reason="flag_off")
        st.pending = None
        st.recording = None
        st.prev_sig, st.streak = None, 0
        st.active = None
        st.replay_arm = False

    # -- maintenance --------------------------------------------------------
    def clear(self):
        """Drop the calling thread's promoted steps, observation state, and
        any pending replay (test hook / clear_dispatch_cache)."""
        st = self._tls
        self._disable(st)
        st.library.clear()
        st.aot_probe.clear()

    def info(self):
        st = self._tls
        return {
            "library": len(st.library),
            "active": st.active.label if st.active is not None else None,
            "streak": st.streak,
            "programs": [
                {"label": p.label, "ops": len(p.chain.ops),
                 "params": len(p.param_refs), "dead": p.dead,
                 "launches_estimate": p.n_launches,
                 "spmd": (p.spmd_plan.axes_label
                          if p.spmd_plan is not None else None)}
                for p in st.library.values()
                if isinstance(p, _StepProgram)],
        }


STEP = _StepFusionManager()


def clear_step_cache():
    """Drop every promoted whole-step program and observation state on the
    calling thread (test hook / manual invalidation)."""
    STEP.clear()


def step_cache_info():
    """Promoted-step library summary for the calling thread."""
    return STEP.info()
