"""Tensor creation ops. Reference analog: python/paddle/tensor/creation.py
backed by phi full/arange/eye/... kernels (phi/kernels/full_kernel.h etc.)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, to_tensor
from ..framework.dtype import get_default_dtype, to_jax_dtype
from .registry import register_op
from ._helpers import ensure_tensor, unary, call_op, scalar_or_value

__all__ = [
    "zeros", "ones", "full", "zeros_like", "ones_like", "full_like",
    "arange", "linspace", "logspace", "eye", "empty", "empty_like", "assign",
    "diag", "diagflat", "tril", "triu", "meshgrid", "clone", "to_tensor",
    "tril_indices", "triu_indices", "complex",
]


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in shape.numpy().tolist()]
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape]


def _dt(dtype, default=None):
    if dtype is None:
        return to_jax_dtype(default or get_default_dtype())
    return to_jax_dtype(dtype)


@register_op("zeros", "creation", ref="python/paddle/tensor/creation.py")
def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape_list(shape), _dt(dtype)))


@register_op("ones", "creation")
def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape_list(shape), _dt(dtype)))


@register_op("full", "creation")
def full(shape, fill_value, dtype=None, name=None):
    fill_value = scalar_or_value(fill_value)
    if dtype is None:
        if isinstance(fill_value, bool):
            dtype = "bool"
        elif isinstance(fill_value, int):
            dtype = "int64"
        else:
            dtype = get_default_dtype()
    return Tensor(jnp.full(_shape_list(shape), fill_value, _dt(dtype)))


@register_op("zeros_like", "creation")
def zeros_like(x, dtype=None, name=None):
    x = ensure_tensor(x)
    return Tensor(jnp.zeros(x._value.shape, _dt(dtype) if dtype else x._value.dtype))


@register_op("ones_like", "creation")
def ones_like(x, dtype=None, name=None):
    x = ensure_tensor(x)
    return Tensor(jnp.ones(x._value.shape, _dt(dtype) if dtype else x._value.dtype))


@register_op("full_like", "creation")
def full_like(x, fill_value, dtype=None, name=None):
    x = ensure_tensor(x)
    return Tensor(jnp.full(x._value.shape, scalar_or_value(fill_value),
                           _dt(dtype) if dtype else x._value.dtype))


@register_op("arange", "creation")
def arange(start=0, end=None, step=1, dtype=None, name=None):
    start = scalar_or_value(start)
    end = scalar_or_value(end)
    step = scalar_or_value(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        if all(isinstance(v, (int, np.integer)) for v in (start, end, step)):
            dtype = "int64"
        else:
            dtype = get_default_dtype()
    return Tensor(jnp.arange(start, end, step, _dt(dtype)))


@register_op("linspace", "creation")
def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(scalar_or_value(start), scalar_or_value(stop),
                               int(scalar_or_value(num)), dtype=_dt(dtype)))


@register_op("logspace", "creation")
def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(scalar_or_value(start), scalar_or_value(stop),
                               int(scalar_or_value(num)), base=base,
                               dtype=_dt(dtype)))


@register_op("eye", "creation")
def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


@register_op("empty", "creation")
def empty(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape_list(shape), _dt(dtype)))


@register_op("empty_like", "creation")
def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


@register_op("assign", "creation")
def assign(x, output=None):
    x = ensure_tensor(x)
    out = unary("assign", lambda v: jnp.asarray(v), x)
    if output is not None:
        output._assign_value_(out._value)
        return output
    return out


@register_op("diag", "creation")
def diag(x, offset=0, padding_value=0, name=None):
    x = ensure_tensor(x)
    if x.ndim == 1 and padding_value != 0:
        def fn(v):
            d = jnp.diag(v, k=offset)
            mask = jnp.eye(d.shape[0], d.shape[1], k=offset, dtype=bool)
            return jnp.where(mask, d, jnp.asarray(padding_value, d.dtype))
        return unary("diag", fn, x)
    return unary("diag", lambda v: jnp.diag(v, k=offset), x)


@register_op("diagflat", "creation")
def diagflat(x, offset=0, name=None):
    return unary("diagflat", lambda v: jnp.diagflat(v, k=offset), ensure_tensor(x))


@register_op("tril", "creation")
def tril(x, diagonal=0, name=None):
    return unary("tril", lambda v: jnp.tril(v, k=diagonal), ensure_tensor(x))


@register_op("triu", "creation")
def triu(x, diagonal=0, name=None):
    return unary("triu", lambda v: jnp.triu(v, k=diagonal), ensure_tensor(x))


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = jnp.tril_indices(row, k=offset, m=col)
    return Tensor(jnp.stack([r, c]).astype(to_jax_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = jnp.triu_indices(row, k=offset, m=col if col is not None else row)
    return Tensor(jnp.stack([r, c]).astype(to_jax_dtype(dtype)))


@register_op("meshgrid", "creation")
def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    tensors = [ensure_tensor(a) for a in args]
    outs = jnp.meshgrid(*[t._value for t in tensors], indexing="ij")
    return [Tensor(o) for o in outs]


@register_op("clone", "creation")
def clone(x, name=None):
    return ensure_tensor(x).clone()


@register_op("complex", "creation")
def complex(real, imag, name=None):
    from ._helpers import binary
    return binary("complex", jax.lax.complex, ensure_tensor(real), ensure_tensor(imag))
