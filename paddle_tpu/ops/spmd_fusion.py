"""Distributed step fusion: collective-aware promotion to ONE shard_map
executable per mesh.

The whole-step promoter (ops/step_fusion.py) collapses a stable eager
training cycle into one jitted executable — but a DATA-PARALLEL cycle, whose
batch lives sharded over a device mesh, used to promote into a plain jit and
leave every collective decision (gradient all-reduce placement, sharded
optimizer update, found-inf sync) to the GSPMD partitioner's mood. This
module makes the promoter see the mesh: it classifies the recorded cycle's
external inputs by their placement (distributed/mesh.value_mesh_and_spec)
and, when the cycle is a recognizable data-parallel or group-sharded step,
lowers the promoted program GShard-style through `shard_map` instead —
explicit, deterministic collectives fused into the ONE launch:

  fwd + vjp            per-device on the local batch shard
  gradient psum        `lax.pmean` over the batch axes (the Fleet
                       fused-allreduce gradient merge: ALL gradients ride
                       one fused region, not one all-reduce per tensor)
  clip + update        replicated — or SHARDED when the optimizer states
                       carry a NamedSharding over the "sharding" axis
                       (ZeRO stage 1/2): each device updates its 1/Nth
                       slice and all-gathers the fresh parameter, the
                       DistributedFusedLamb shape
  guardian skip        the all-finite predicate is all-reduced (min) over
                       the mesh so every shard takes the SAME skip/keep
                       branch even when only one shard saw the blowup
  GradScaler           found-inf is computed on the post-psum grads and
                       all-reduced with the same predicate, so the
                       loss-scale transition is globally consistent

Safety: the lowering assumes the canonical data-parallel contract — a
scalar loss whose per-shard value is the mean over the local batch shard,
so `pmean(local losses)` IS the global loss and `pmean(local grads)` IS the
global gradient. Cycles that fit the shape but violate the contract (a
sum-reduced loss, a batch-coupled normalization) are caught by PROBATION:
the first fired replay runs the shard_map executable on scratch buffers,
replays the step eagerly (bitwise, through the existing transactional
split machinery), and compares. A divergence demotes the program to the
plain-jit lowering — still ONE executable, GSPMD-exact — attributed as
`spmd_divergence` in the flight recorder. Promotion itself never changes
numerics beyond the documented single-program layout caveat.

A plan is refused (plain jit promotion proceeds) when: no external input is
mesh-sharded; sharded inputs span different meshes (`mesh_mismatch`, also
the split reason when a fired program's inputs move to another mesh);
parameters themselves are sharded (model parallel / ZeRO-3 — GSPMD already
owns that placement); the loss is not scalar; or optimizer-state sharding
is not the uniform one-axis layout `shard_optimizer_states` produces.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..framework.flags import _FLAGS

__all__ = ["MeshPlan", "plan_program", "enabled", "sync_root_and_grads",
           "global_finite", "sharded_single_update", "compile_step",
           "compile_accum", "compile_update", "zero_accum",
           "fire_mismatch", "probation_tolerance",
           "pipeline_signature", "promote_pipeline", "fire_pipeline",
           "clear_pipeline_programs"]


def enabled():
    """SPMD lowering of promoted steps (FLAGS_eager_step_fusion_spmd)."""
    return bool(_FLAGS.get("FLAGS_eager_step_fusion_spmd", True))


class MeshPlan:
    """Everything the step compiler needs to lower one promoted cycle
    through shard_map over one mesh."""

    __slots__ = ("mesh", "mesh_token", "data_axes", "all_axes", "ext_specs",
                 "shard_checks", "param_specs", "param_gather",
                 "param_checks", "param_shard", "acc_layout", "accf_specs",
                 "acc_out_specs", "axes_label")

    def __init__(self):
        self.mesh = None
        self.mesh_token = None
        self.data_axes = ()       # grad/loss pmean axes (batch placement)
        self.all_axes = ()        # every size>1 axis (predicate all-reduce)
        self.ext_specs = ()       # PartitionSpec per program.ext_order slot
        self.shard_checks = ()    # (ext slot, expected NamedSharding)
        self.param_specs = ()     # per param: P() | its stored-shard spec
        self.param_gather = ()    # per param: None | (dim, nshard) — the
                                  # param is STORED sharded (GSPMD placed
                                  # it beside its ZeRO slots) and must be
                                  # all-gathered for the forward
        self.param_checks = ()    # per param: None (must be replicated) |
                                  # the expected NamedSharding
        self.param_shard = ()     # per param: None | (dim, nshard) sliced
                                  # (ZeRO) update
        self.acc_layout = ()      # per param: tuple of present-bools
        self.accf_specs = ()      # spec per present accumulator, flattened
        self.acc_out_specs = ()   # per param: tuple of specs (acc_names order)
        self.axes_label = ""


def _spec_of(norm):
    """PartitionSpec from the normalized per-dim axis tuples of
    distributed/mesh.value_mesh_and_spec."""
    entries = []
    for axes in norm:
        if not axes:
            entries.append(None)
        elif len(axes) == 1:
            entries.append(axes[0])
        else:
            entries.append(tuple(axes))
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def plan_program(chain, slot_inputs, ext_order, updated, opt,
                 acc_names, root_flat):
    """(MeshPlan, None) when the cycle lowers through shard_map;
    (None, None) when it should promote through plain jit; (None, reason)
    when a mesh-level contradiction is worth attributing (the reason is a
    REASON_CODES entry, e.g. `mesh_mismatch`)."""
    from ..distributed.mesh import mesh_key, value_mesh_and_spec
    if not enabled():
        return None, None
    mesh = None
    token = None
    ext_info = {}
    for s in ext_order:
        t = slot_inputs.get(s)
        v = getattr(t, "_value", None)
        if v is None:
            return None, None
        m, norm = value_mesh_and_spec(v)
        if m is None:
            continue
        tk = mesh_key(m)
        if tk is None:
            return None, None
        if mesh is None:
            mesh, token = m, tk
        elif tk != token:
            return None, "mesh_mismatch"
        ext_info[s] = (norm, v.sharding)
    # parameters: replicated, or STORED sharded over the "sharding" axis
    # on exactly one dim — the placement GSPMD gives them after an eager
    # step beside ZeRO-sharded slots. Anything else (tensor-parallel
    # placements, "data"-sharded params) keeps the plain GSPMD lowering.
    param_gather = []
    param_info = []
    for p in updated:
        m, norm = value_mesh_and_spec(p._value)
        if m is None:
            param_gather.append(None)
            param_info.append(None)
            continue
        tk = mesh_key(m)
        if mesh is not None and tk != token:
            return None, "mesh_mismatch"
        if mesh is None:
            mesh, token = m, tk
        dims = [i for i, axes in enumerate(norm) if axes]
        if len(dims) != 1 or norm[dims[0]] != ("sharding",):
            return None, None
        nsh = int(mesh.shape.get("sharding", 1))
        pshape = tuple(p._value.shape)
        if nsh <= 1 or not pshape or pshape[dims[0]] % nsh:
            return None, None
        param_gather.append((dims[0], nsh))
        param_info.append((norm, p._value.sharding))
    if mesh is None:
        return None, None
    data_axes = sorted({a for norm, _ in ext_info.values()
                        for axes in norm for a in axes})
    if any(a not in ("data", "sharding") for a in data_axes):
        return None, None     # pipeline/model placements: plain jit
    if tuple(chain.flat_avals[root_flat][0]) != ():
        return None, None     # non-scalar loss: the pmean contract is moot

    nshard = int(mesh.shape.get("sharding", 1))
    param_shard = []
    acc_layout = []
    accf_specs = []
    acc_out_specs = []
    for k, p in enumerate(updated):
        row_present = []
        row_out = []
        shard_dim = None
        full_unsharded = False
        pshape = tuple(p._value.shape)
        for n in acc_names:
            a = opt._accumulators[n].get(p.name)
            row_present.append(a is not None)
            if a is None:
                row_out.append(P())
                continue
            m2, norm2 = value_mesh_and_spec(a)
            if m2 is None:
                if tuple(a.shape) == pshape and pshape:
                    full_unsharded = True
                accf_specs.append(P())
                row_out.append(P())
                continue
            if mesh_key(m2) != token:
                return None, "mesh_mismatch"
            dims = [i for i, axes in enumerate(norm2) if axes]
            if len(dims) != 1 or norm2[dims[0]] != ("sharding",) \
                    or nshard <= 1:
                return None, None   # non-canonical state sharding
            if shard_dim is None:
                shard_dim = dims[0]
            elif shard_dim != dims[0]:
                return None, None
            spec = _spec_of(norm2)
            accf_specs.append(spec)
            row_out.append(spec)
        if shard_dim is not None:
            if full_unsharded or not pshape \
                    or pshape[shard_dim] % nshard:
                # a full-shape replicated slot beside sharded ones (or an
                # indivisible dim) breaks the slice-update contract
                return None, None
            if param_gather[k] is not None \
                    and param_gather[k][0] != shard_dim:
                return None, None
            param_shard.append((shard_dim, nshard))
        else:
            if param_gather[k] is not None:
                # a stored-sharded param with replicated slots has no
                # slice-update to keep it local: plain lowering
                return None, None
            param_shard.append(None)
        acc_layout.append(tuple(row_present))
        acc_out_specs.append(tuple(row_out))

    plan = MeshPlan()
    plan.mesh = mesh
    plan.mesh_token = token
    plan.data_axes = tuple(data_axes)
    plan.all_axes = tuple(a for a, s in zip(mesh.axis_names,
                                            mesh.devices.shape)
                          if int(s) > 1)
    plan.ext_specs = tuple(
        _spec_of(ext_info[s][0]) if s in ext_info else P()
        for s in ext_order)
    plan.shard_checks = tuple(
        (s, ext_info[s][1]) for s in ext_order if s in ext_info)
    plan.param_specs = tuple(
        P() if info is None else _spec_of(info[0]) for info in param_info)
    plan.param_gather = tuple(param_gather)
    plan.param_checks = tuple(
        None if info is None else info[1] for info in param_info)
    plan.param_shard = tuple(param_shard)
    plan.acc_layout = tuple(acc_layout)
    plan.accf_specs = tuple(accf_specs)
    plan.acc_out_specs = tuple(acc_out_specs)
    plan.axes_label = "×".join(
        f"{a}{int(mesh.shape[a])}" for a in plan.all_axes) or "1"
    return plan, None


# ---------------------------------------------------------------------------
# traced pieces, woven into the step body by ops/step_fusion._compile
# ---------------------------------------------------------------------------

def sync_root_and_grads(plan, root_val, grads):
    """The gradient all-reduce + loss sync of the data-parallel contract:
    pmean over the batch axes. One fused region for EVERY gradient — the
    Fleet fused-allreduce gradient merge, emitted by construction."""
    if not plan.data_axes:
        return root_val, grads
    root_val = jax.lax.pmean(root_val, plan.data_axes)
    grads = [jax.lax.pmean(g, plan.data_axes) for g in grads]
    return root_val, grads


def global_finite(plan, vals):
    """The guardian's all-finite predicate, all-reduced (min) over every
    live mesh axis so the skip-step where()-rescue takes the same branch on
    every shard — a single poisoned shard skips the step EVERYWHERE."""
    from . import guardian
    return guardian.finite_all_reduced(vals, plan.all_axes)


def gather_params(plan, pvals):
    """Stored-sharded params (GSPMD keeps a ZeRO param beside its sharded
    slots) arrive as local shards: all-gather them to full for the forward
    — the ZeRO-3-style just-in-time gather, one per param per step."""
    out = []
    for k, pv in enumerate(pvals):
        g = plan.param_gather[k]
        out.append(pv if g is None else
                   jax.lax.all_gather(pv, "sharding", axis=g[0],
                                      tiled=True))
    return out


def sharded_single_update(plan, k, opt, pv, gv, acc_dict, lr, step_count):
    """ZeRO-sharded optimizer update for parameter k: slice the (full,
    post-psum) grad — and the param, unless it is stored sharded already —
    to this device's 1/Nth along the state-sharded dim, update with the
    LOCAL accumulator shard, and (for replicated storage) all-gather the
    fresh parameter back — the DistributedFusedLamb shape. The new
    accumulator stays local (its out_spec keeps it sharded)."""
    dim, n = plan.param_shard[k]
    chunk = gv.shape[dim] // n
    idx = jax.lax.axis_index("sharding")
    gv_s = jax.lax.dynamic_slice_in_dim(gv, idx * chunk, chunk, dim)
    stored_local = plan.param_gather[k] is not None
    pv_s = pv if stored_local else \
        jax.lax.dynamic_slice_in_dim(pv, idx * chunk, chunk, dim)
    np_s, na = opt._single_update(pv_s, gv_s, acc_dict, lr, step_count)
    if stored_local:
        return np_s, na        # storage stays sharded (out_spec local)
    return jax.lax.all_gather(np_s, "sharding", axis=dim, tiled=True), na


def compile_step(plan, step_fn, n_params, n_scaler, n_extras,
                 donate_argnums):
    """Wrap the (local-semantics) step body in shard_map over the plan's
    mesh and jit the whole thing — the ONE executable per mesh. The outer
    call signature is identical to the plain lowering (pvals, ext, accs,
    lr, step_count[, scale, good, bad]), so the firing hook and the
    donation argnums are shared verbatim."""
    from ..framework.jax_compat import shard_map
    P0 = P()
    acc_layout = plan.acc_layout
    in_specs = (
        tuple(plan.param_specs),     # params: replicated or stored-sharded
        tuple(plan.ext_specs),       # batch shards / replicated side inputs
        tuple(plan.accf_specs),      # optimizer slots (sharded slots local)
        P0, P0,                      # lr, step_count
    ) + (P0,) * n_scaler
    out_specs = (
        P0,                          # loss (post-pmean, replicated)
        (P0,) * n_params,            # grads (post-pmean, replicated)
        tuple(plan.param_specs),     # new params (storage layout preserved)
        tuple(plan.acc_out_specs),   # new slots (sharded ones stay local)
    ) + (P0,) * n_extras

    def local(pv_t, ext_t, accf_t, lr, step_count, *sargs):
        it = iter(accf_t)
        accs = [[next(it) if pres else None for pres in row]
                for row in acc_layout]
        out = step_fn(list(pv_t), list(ext_t), accs, lr, step_count, *sargs)
        return (out[0], tuple(out[1]), tuple(out[2]),
                tuple(tuple(r) for r in out[3])) + tuple(out[4:])

    smapped = shard_map(local, mesh=plan.mesh, in_specs=in_specs,
                        out_specs=out_specs)

    def wrapper(pvals, ext, accs, lr, step_count, *sargs):
        flat = tuple(a for row in accs for a in row if a is not None)
        return smapped(tuple(pvals), tuple(ext), flat, lr, step_count,
                       *sargs)

    return jax.jit(wrapper, donate_argnums=donate_argnums)


# ---------------------------------------------------------------------------
# super-cycle (grad accumulation) lowering: the sub-executable accumulates
# LOCAL gradients — no collective per micro-batch — and the update
# executable fires ONE fused pmean over the accumulated sums before the
# optimizer update: k× less gradient traffic than per-micro-batch sync,
# numerically pmean(Σ local) == Σ pmean(local) (linearity; probation
# verifies within single-program tolerance).
#
# A device-varying accumulator must cross launch boundaries as a real
# global array: it carries ONE stacked leading dim of size
# Π|data axes|, sharded over those axes — each device owns its [1, ...]
# slab of local gradient sums.
# ---------------------------------------------------------------------------

def _stack_spec(plan):
    """PartitionSpec of the stacked-accumulator leading dim."""
    axes = plan.data_axes
    return P(axes[0] if len(axes) == 1 else tuple(axes))


def stack_devices(plan):
    import math
    return math.prod(int(plan.mesh.shape[a]) for a in plan.data_axes)


def zero_accum(plan, shapes):
    """Zero grad accumulators for one super-cycle program: per param a
    [n_dev, *shape] array sharded over the data axes on dim 0."""
    from jax.sharding import NamedSharding
    n = stack_devices(plan)
    sharding = NamedSharding(plan.mesh, _stack_spec(plan))
    return [jax.device_put(jnp.zeros((n,) + tuple(s), d), sharding)
            for s, d in shapes]


def compile_accum(plan, sub_fn, n_params, n_tail):
    """shard_map lowering of the micro-batch sub-executable: per-device
    fwd+vjp on the local batch shard, local gradient sums into the stacked
    accumulator, NO gradient collective (only the scalar loss pmean the
    sub body emits). `n_tail` counts replicated scalar tail args (hoisted
    RNG + the running fwd-finite predicate)."""
    from ..framework.jax_compat import shard_map
    P0 = P()
    sspec = _stack_spec(plan)
    in_specs = (
        tuple(plan.param_specs),
        tuple(plan.ext_specs),
        (sspec,) * n_params,
    ) + (P0,) * n_tail
    def local(pv_t, ext_t, acc_t, *tail):
        acc_in = [a[0] for a in acc_t]
        out = sub_fn(list(pv_t), list(ext_t), acc_in, *tail)
        new_acc = tuple(a[None] for a in out[1])
        return (out[0], new_acc) + tuple(out[2:])

    # the sub body returns (loss, new_acc[, fwd_ok]) — fwd_ok present iff
    # the program checks, signalled by the builder via an fn attribute
    n_extra = 1 if getattr(sub_fn, "_returns_fwd_ok", False) else 0
    specs = (P0, (sspec,) * n_params) + (P0,) * n_extra
    m = shard_map(local, mesh=plan.mesh, in_specs=in_specs,
                  out_specs=specs)

    def wrapper(pvals, ext, acc, *tail):
        return m(tuple(pvals), tuple(ext), tuple(acc), *tail)
    return jax.jit(wrapper)


def compile_update(plan, upd_fn, n_params, n_tail, n_extras,
                   donate_argnums):
    """shard_map lowering of the boundary update executable: ONE fused
    pmean region over the accumulated gradient sums (inside `upd_fn`),
    then the same clip/update/guardian/scaler weave as the whole-step
    lowering — sharded (ZeRO) slots update their local 1/Nth."""
    from ..framework.jax_compat import shard_map
    P0 = P()
    sspec = _stack_spec(plan)
    acc_layout = plan.acc_layout
    in_specs = (
        tuple(plan.param_specs),
        tuple(plan.accf_specs),
        (sspec,) * n_params,
        P0, P0,
    ) + (P0,) * n_tail
    out_specs = (
        (P0,) * n_params,            # grads (post-pmean, replicated)
        tuple(plan.param_specs),
        tuple(plan.acc_out_specs),
    ) + (P0,) * n_extras

    def local(pv_t, accf_t, gsum_t, lr, step_count, *tail):
        it = iter(accf_t)
        accs = [[next(it) if pres else None for pres in row]
                for row in acc_layout]
        gsum = [g[0] for g in gsum_t]
        out = upd_fn(list(pv_t), accs, gsum, lr, step_count, *tail)
        return (tuple(out[0]), tuple(out[1]),
                tuple(tuple(r) for r in out[2])) + tuple(out[3:])

    smapped = shard_map(local, mesh=plan.mesh, in_specs=in_specs,
                        out_specs=out_specs)

    def wrapper(pvals, accs, gsum, lr, step_count, *tail):
        flat = tuple(a for row in accs for a in row if a is not None)
        return smapped(tuple(pvals), flat, tuple(gsum), lr, step_count,
                       *tail)

    return jax.jit(wrapper, donate_argnums=donate_argnums)


# ---------------------------------------------------------------------------
# fire-time verification + probation
# ---------------------------------------------------------------------------

def fire_mismatch(plan, ext_vals, params):
    """None when this fire's placements still match the plan, else
    "mesh_mismatch": the batch moved to another mesh/layout or a parameter
    got sharded under the program's feet — the compiled collectives would
    run over the WRONG axes, so the program must die and re-promote."""
    from ..distributed.mesh import value_mesh_and_spec
    try:
        for s, expected in plan.shard_checks:
            if getattr(ext_vals[s], "sharding", None) != expected:
                return "mesh_mismatch"
        for p, expected in zip(params, plan.param_checks):
            if expected is None:
                m, _ = value_mesh_and_spec(p._value)
                if m is not None:
                    return "mesh_mismatch"
            elif getattr(p._value, "sharding", None) != expected:
                return "mesh_mismatch"
    except Exception:
        return "mesh_mismatch"
    return None


def probation_tolerance(dtype):
    """(rtol, atol) for the probation fused-vs-eager comparison: layout
    differences only, scaled to the compute dtype."""
    d = jnp.dtype(dtype)
    if d in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16)):
        return 3e-2, 1e-2
    return 2e-3, 1e-5


# ---------------------------------------------------------------------------
# pipeline promotion registry
# ---------------------------------------------------------------------------
# The pipe-axis train step (meta_parallel/spmd_pipeline.PipelineTrainStep)
# is already ONE shard_map program — k micro-batches rotated between stages
# by a single lax.ppermute per scan step, fwd+bwd+update fused. What it
# lacked was the funnel's bookkeeping: programs compiled as anonymous bare
# jits, invisible to the flight recorder and the retrace counters, and a
# schedule change (micro-batch count, virtual-stage interleave, optimizer
# swap) silently rebuilt the whole step. This registry gives every pipeline
# program the same lifecycle as a promoted cycle: a canonical mesh-keyed
# signature, step.promote / step.fire events, STEP_STATS accounting, and a
# `pipe_schedule_mismatch` record when a new schedule forces a second
# program over the same mesh + stage structure.

_PIPE_PROGRAMS = {}        # sig -> _PipelineProgram
_PIPE_BASES = {}           # base key -> last schedule tuple seen


class _PipelineProgram:
    """One promoted pipeline train-step executable."""

    __slots__ = ("sig", "label", "exe", "fires", "n_launches", "chain",
                 "entries", "spmd_plan")

    def __init__(self, sig, label, exe, n_launches):
        self.sig = sig
        self.label = label
        self.exe = exe
        self.fires = 0
        self.n_launches = n_launches
        # goodput.on_fused_fire introspection surface (no recorded cycle:
        # bench legs pin exact FLOPs for pipeline programs)
        self.chain = None
        self.entries = ()
        self.spmd_plan = None


def pipeline_signature(mesh, axis, num_stages, num_virtual, num_micro,
                       stage_struct, opt):
    """Canonical identity of one pipeline train-step program: the mesh key
    + pipe axis name + stage structure (what is compiled in) and the
    schedule + optimizer binding (what forces a recompile). Returns None
    when the mesh has no canonical key — the caller falls back to an
    anonymous jit and the build is attributed `collective_unkeyed`."""
    from ..distributed.mesh import mesh_key
    mk = mesh_key(mesh)
    if mk is None:
        return None
    try:
        opt_key = (type(opt).__qualname__, tuple(opt._extra_cache_key()))
    except Exception:
        opt_key = (type(opt).__qualname__,)
    return ("pipe", mk, axis,
            (int(num_stages), int(num_virtual), int(num_micro)),
            tuple(stage_struct), opt_key)


def _pipe_base(sig):
    # everything but the schedule triple: same mesh + stage structure
    return (sig[1], sig[2], sig[4], sig[5])


def promote_pipeline(sig, label, build, n_launches=1):
    """Look up or build the pipeline program for `sig`. `build()` returns
    the compiled step callable; the first build of a signature emits
    `step.promote` and counts as a promotion, and a signature that differs
    from a previously promoted one ONLY in its schedule triple is recorded
    as `pipe_schedule_mismatch` before building — the doctor's hint for
    schedule churn. `sig=None` (unkeyable mesh) builds uncached and poisons
    as `collective_unkeyed`."""
    from ..profiler.events import EVENTS as _EVENTS
    from ..profiler.step_fusion import STEP_STATS
    if sig is None:
        _EVENTS.emit("step.record", "pipeline_step",
                     reason="collective_unkeyed",
                     detail={"kind": "pipe", "label": label})
        return _PipelineProgram(None, label, build(), n_launches)
    prog = _PIPE_PROGRAMS.get(sig)
    if prog is not None:
        return prog
    base = _pipe_base(sig)
    prev_sched = _PIPE_BASES.get(base)
    if prev_sched is not None and prev_sched != sig[3]:
        _EVENTS.emit("step.record", "pipeline_step",
                     reason="pipe_schedule_mismatch",
                     detail={"kind": "pipe", "label": label,
                             "prev_schedule": prev_sched,
                             "schedule": sig[3]})
    prog = _PipelineProgram(sig, label, build(), n_launches)
    _PIPE_PROGRAMS[sig] = prog
    _PIPE_BASES[base] = sig[3]
    if len(_PIPE_PROGRAMS) > 16:
        _PIPE_PROGRAMS.pop(next(iter(_PIPE_PROGRAMS)))
    STEP_STATS.promoted(label)
    _EVENTS.emit("step.promote", label,
                 detail={"pipe": True, "schedule": sig[3],
                         "mesh_axes": sig[1][0] if sig[1] else None,
                         "launches_estimate": n_launches})
    return prog


def fire_pipeline(prog):
    """One completed pipeline step through `prog.exe`: the step.fire /
    goodput accounting of a fused replay (launch savings are the unfused
    schedule's per-micro-batch launches collapsed into one program)."""
    from ..profiler.events import EVENTS as _EVENTS
    from ..profiler.step_fusion import STEP_STATS
    from ..profiler import goodput as _goodput
    prog.fires += 1
    STEP_STATS.replay(prog.label, prog.n_launches, 0)
    _goodput.on_fused_fire(prog)
    _EVENTS.emit("step.fire", prog.label,
                 detail={"pipe": True, "fires": prog.fires,
                         "launches_saved": prog.n_launches - 1})


def clear_pipeline_programs():
    """Test/teardown hook: drop every promoted pipeline program."""
    _PIPE_PROGRAMS.clear()
    _PIPE_BASES.clear()
