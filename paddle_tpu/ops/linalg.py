"""Linear algebra ops. Reference analog: python/paddle/tensor/linalg.py backed
by phi linalg kernels (svd/qr/cholesky/...). On TPU, decompositions lower to
XLA's linalg custom calls."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from .registry import register_op
from ._helpers import ensure_tensor, unary, binary, nary, call_op, \
    call_op_multi, const_input

__all__ = [
    "norm", "dist", "cond", "inv", "pinv", "det", "slogdet", "svd", "qr",
    "eig", "eigh", "eigvals", "eigvalsh", "matrix_power", "matrix_rank",
    "cholesky", "cholesky_solve", "solve", "triangular_solve", "lstsq", "lu",
    "lu_unpack", "cross", "histogram", "bincount", "multi_dot", "corrcoef", "cov",
    "householder_product", "vander", "pca_lowrank",
]


@register_op("norm", "linalg")
def norm(x, p="fro", axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)

    def fn(v):
        if axis is None:
            flat = v.reshape(-1)
            if p in ("fro", 2, 2.0):
                return jnp.sqrt(jnp.sum(flat * flat))
            if p in ("inf", float("inf"), np.inf):
                return jnp.max(jnp.abs(flat))
            if p in ("-inf", float("-inf"), -np.inf):
                return jnp.min(jnp.abs(flat))
            if p == 0:
                return jnp.sum((flat != 0).astype(v.dtype))
            if p == 1:
                return jnp.sum(jnp.abs(flat))
            return jnp.power(jnp.sum(jnp.power(jnp.abs(flat), p)), 1.0 / p)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if p == "fro" or (isinstance(ax, tuple) and p in (2, 2.0)):
            return jnp.sqrt(jnp.sum(v * v, axis=ax, keepdims=keepdim))
        if p in ("inf", float("inf"), np.inf):
            return jnp.max(jnp.abs(v), axis=ax, keepdims=keepdim)
        if p in ("-inf", float("-inf"), -np.inf):
            return jnp.min(jnp.abs(v), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((v != 0).astype(v.dtype), axis=ax, keepdims=keepdim)
        if p == 1:
            return jnp.sum(jnp.abs(v), axis=ax, keepdims=keepdim)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(v), p), axis=ax,
                                 keepdims=keepdim), 1.0 / p)
    return unary("norm", fn, x)


@register_op("dist", "linalg")
def dist(x, y, p=2, name=None):
    return binary("dist", lambda a, b: _pnorm_flat(a - b, p), x, y)


def _pnorm_flat(v, p):
    flat = v.reshape(-1)
    if p in ("inf", float("inf"), np.inf):
        return jnp.max(jnp.abs(flat))
    if p in ("-inf", float("-inf"), -np.inf):
        return jnp.min(jnp.abs(flat))
    if p == 0:
        return jnp.sum((flat != 0).astype(flat.dtype))
    if p == 1:
        return jnp.sum(jnp.abs(flat))
    if p == 2:
        return jnp.sqrt(jnp.sum(flat * flat))
    return jnp.power(jnp.sum(jnp.power(jnp.abs(flat), p)), 1.0 / p)


@register_op("cond", "linalg")
def cond(x, p=None, name=None):
    return unary("cond", lambda v: jnp.linalg.cond(v, p=p), ensure_tensor(x))


@register_op("inv", "linalg")
def inv(x, name=None):
    return unary("inv", jnp.linalg.inv, ensure_tensor(x))


@register_op("pinv", "linalg")
def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return unary("pinv", lambda v: jnp.linalg.pinv(v, rtol=rcond,
                                                   hermitian=hermitian),
                 ensure_tensor(x))


@register_op("det", "linalg")
def det(x, name=None):
    return unary("det", jnp.linalg.det, ensure_tensor(x))


@register_op("slogdet", "linalg")
def slogdet(x, name=None):
    x = ensure_tensor(x)

    def fn(v):
        s, l = jnp.linalg.slogdet(v)
        return jnp.stack([s, l]) if s.ndim == 0 else jnp.stack([s, l])
    return unary("slogdet", fn, x)


@register_op("svd", "linalg")
def svd(x, full_matrices=False, name=None):
    x = ensure_tensor(x)

    def fn(v):
        u, s, vh = jnp.linalg.svd(v, full_matrices=full_matrices)
        return u, s, jnp.swapaxes(vh, -1, -2).conj()
    return call_op_multi("svd", fn, (x,), num_outputs=3)


@register_op("qr", "linalg")
def qr(x, mode="reduced", name=None):
    x = ensure_tensor(x)
    if mode == "r":
        return unary("qr", lambda v: jnp.linalg.qr(v, mode="r"), x)

    def fn(v):
        q, r = jnp.linalg.qr(v, mode=mode)
        return q, r
    return call_op_multi("qr", fn, (x,), num_outputs=2)


@register_op("eig", "linalg", differentiable=False)
def eig(x, name=None):
    w, v = np.linalg.eig(np.asarray(ensure_tensor(x)._value))
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


@register_op("eigh", "linalg")
def eigh(x, UPLO="L", name=None):
    x = ensure_tensor(x)

    def fn(v):
        w, vec = jnp.linalg.eigh(v, UPLO=UPLO)
        return w, vec
    return call_op_multi("eigh", fn, (x,), num_outputs=2)


@register_op("eigvals", "linalg", differentiable=False)
def eigvals(x, name=None):
    return Tensor(jnp.asarray(np.linalg.eigvals(np.asarray(ensure_tensor(x)._value))))


@register_op("eigvalsh", "linalg")
def eigvalsh(x, UPLO="L", name=None):
    return unary("eigvalsh", lambda v: jnp.linalg.eigvalsh(v, UPLO=UPLO),
                 ensure_tensor(x))


@register_op("matrix_power", "linalg")
def matrix_power(x, n, name=None):
    return unary("matrix_power", lambda v: jnp.linalg.matrix_power(v, n),
                 ensure_tensor(x))


@register_op("matrix_rank", "linalg", differentiable=False)
def matrix_rank(x, tol=None, hermitian=False, name=None):
    x = ensure_tensor(x)
    return Tensor(jnp.linalg.matrix_rank(x._value, rtol=tol).astype(jnp.int64))


@register_op("cholesky", "linalg")
def cholesky(x, upper=False, name=None):
    def fn(v):
        l = jnp.linalg.cholesky(v)
        return jnp.swapaxes(l, -1, -2) if upper else l
    return unary("cholesky", fn, ensure_tensor(x))


@register_op("cholesky_solve", "linalg")
def cholesky_solve(x, y, upper=False, name=None):
    def fn(b, chol):
        c = jnp.swapaxes(chol, -1, -2) if upper else chol
        z = jax.scipy.linalg.solve_triangular(c, b, lower=True)
        return jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(c, -1, -2), z, lower=False)
    return binary("cholesky_solve", fn, x, y)


@register_op("solve", "linalg")
def solve(x, y, name=None):
    return binary("solve", jnp.linalg.solve, x, y)


@register_op("triangular_solve", "linalg")
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    def fn(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return binary("triangular_solve", fn, x, y)


@register_op("lstsq", "linalg", differentiable=False)
def lstsq(x, y, rcond=None, driver=None, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    sol, res, rank, sv = jnp.linalg.lstsq(x._value, y._value, rcond=rcond)
    return (Tensor(sol), Tensor(res), Tensor(rank.astype(jnp.int64)), Tensor(sv))


@register_op("lu", "linalg", differentiable=False)
def lu(x, pivot=True, get_infos=False, name=None):
    import jax.scipy.linalg as jsl
    x = ensure_tensor(x)
    lu_mat, piv = jsl.lu_factor(x._value)
    outs = [Tensor(lu_mat), Tensor((piv + 1).astype(jnp.int32))]
    if get_infos:
        outs.append(Tensor(jnp.zeros((), jnp.int32)))
    return tuple(outs)


@register_op("lu_unpack", "linalg", differentiable=False)
def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack the (LU, pivots) pair from `lu` into P, L, U.

    Reference analog: python/paddle/tensor/linalg.py lu_unpack → phi
    lu_unpack kernel. Pivots are 1-based LAPACK-style sequential row swaps.

    Always returns a 3-tuple (P, L, U); outputs disabled via
    unpack_pivots/unpack_ludata are returned as None (and not computed).
    """
    lu_mat = ensure_tensor(x)._value
    m, n = lu_mat.shape[-2], lu_mat.shape[-1]
    k = min(m, n)
    batch = lu_mat.shape[:-2]

    l_t = u_t = p_t = None
    if unpack_ludata:
        l_val = jnp.tril(lu_mat[..., :, :k], -1)
        diag = jnp.arange(k)
        l_val = l_val.at[..., diag, diag].set(1.0)
        l_t = Tensor(l_val)
        u_t = Tensor(jnp.triu(lu_mat[..., :k, :]))
    if unpack_pivots:
        # pivot-to-perm composition is inherently sequential; runs on host
        piv = np.asarray(ensure_tensor(y)._value) - 1
        p_out = np.zeros(batch + (m, m), lu_mat.dtype)
        for idx in np.ndindex(*batch) if batch else [()]:
            perm = np.arange(m)
            for i, p in enumerate(piv[idx]):
                perm[i], perm[p] = perm[p], perm[i]
            # P such that A = P @ L @ U  (row `perm[i]` of P selects row i)
            p_out[idx][perm, np.arange(m)] = 1.0
        p_t = Tensor(jnp.asarray(p_out))
    return p_t, l_t, u_t


@register_op("cross", "linalg")
def cross(x, y, axis=9, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    if axis == 9:  # paddle default: first axis of size 3
        axis = next((i for i, s in enumerate(x.shape) if s == 3), -1)
    return binary("cross", lambda a, b: jnp.cross(a, b, axis=axis), x, y)


@register_op("histogram", "linalg", differentiable=False)
def histogram(input, bins=100, min=0, max=0, name=None):
    x = ensure_tensor(input)._value.reshape(-1)
    lo, hi = (min, max) if (min != 0 or max != 0) else (None, None)
    if lo is None:
        lo = float(jnp.min(x))
        hi = float(jnp.max(x))
    hist, _ = jnp.histogram(x, bins=bins, range=(lo, hi))
    return Tensor(hist.astype(jnp.int64))


@register_op("bincount", "linalg", differentiable=False)
def bincount(x, weights=None, minlength=0, name=None):
    x = ensure_tensor(x)._value
    w = ensure_tensor(weights)._value if weights is not None else None
    n = int(jnp.max(x)) + 1 if x.size else 0
    length = max(n, minlength)
    return Tensor(jnp.bincount(x, weights=w, length=length))


@register_op("multi_dot", "linalg")
def multi_dot(x, name=None):
    tensors = [ensure_tensor(t) for t in x]
    return nary("multi_dot", lambda *vs: jnp.linalg.multi_dot(vs), tensors)


@register_op("corrcoef", "linalg")
def corrcoef(x, rowvar=True, name=None):
    return unary("corrcoef", lambda v: jnp.corrcoef(v, rowvar=rowvar),
                 ensure_tensor(x))


@register_op("cov", "linalg")
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    extra = tuple(const_input(t) for t in (fweights, aweights)
                  if t is not None)
    has_fw, has_aw = fweights is not None, aweights is not None

    def fn(v, *w):
        it = iter(w)
        fw = next(it) if has_fw else None
        aw = next(it) if has_aw else None
        return jnp.cov(v, rowvar=rowvar, ddof=1 if ddof else 0,
                       fweights=fw, aweights=aw)
    return call_op("cov", fn, (ensure_tensor(x),) + extra)


@register_op("householder_product", "linalg")
def householder_product(x, tau, name=None):
    def fn(a, t):
        m, n = a.shape[-2], a.shape[-1]
        q = jnp.eye(m, dtype=a.dtype)
        q = jnp.broadcast_to(q, a.shape[:-2] + (m, m)).copy() if a.ndim > 2 else q
        for i in range(n):
            v = jnp.zeros(a.shape[:-1], a.dtype).at[..., i].set(1.0)
            v = v.at[..., i + 1:].set(a[..., i + 1:, i])
            h = jnp.eye(m, dtype=a.dtype) - t[..., i, None, None] * \
                (v[..., :, None] @ v[..., None, :])
            q = q @ h
        return q[..., :, :n] if m > n else q
    return binary("householder_product", fn, x, tau)


def vander(x, n=None, increasing=False, name=None):
    return unary("vander", lambda v: jnp.vander(v, N=n, increasing=increasing),
                 ensure_tensor(x))


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    x = ensure_tensor(x)
    v = x._value
    if q is None:
        q = min(6, v.shape[-2], v.shape[-1])
    if center:
        v = v - jnp.mean(v, axis=-2, keepdims=True)
    u, s, vh = jnp.linalg.svd(v, full_matrices=False)
    return (Tensor(u[..., :q]), Tensor(s[..., :q]),
            Tensor(jnp.swapaxes(vh, -1, -2)[..., :q]))
