"""Random sampling ops. Reference analog: python/paddle/tensor/random.py over
phi uniform/gaussian kernels + the global Generator. TPU-first: functional jax
PRNG keys split from the framework generator (see framework/random.py); under
jit tracing, keys come from the traced-key scope so compiled steps get fresh
randomness."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework.dtype import to_jax_dtype, get_default_dtype
from ..framework.random import get_rng_key, rng_key_input
from .registry import register_op
from ._helpers import ensure_tensor, scalar_or_value, call_op

__all__ = ["rand", "randn", "randint", "randint_like", "uniform", "normal",
           "standard_normal", "randperm", "bernoulli", "multinomial",
           "poisson", "exponential_", "uniform_", "normal_", "gauss"]


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in shape.numpy().tolist()]
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape]


def _dt(dtype):
    return to_jax_dtype(dtype or get_default_dtype())


@register_op("rand", "random", differentiable=False)
def rand(shape, dtype=None, name=None):
    return Tensor(jax.random.uniform(get_rng_key(), _shape_list(shape),
                                     _dt(dtype)))


@register_op("randn", "random", differentiable=False)
def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(get_rng_key(), _shape_list(shape),
                                    _dt(dtype)))


standard_normal = randn


@register_op("randint", "random", differentiable=False)
def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(get_rng_key(), _shape_list(shape),
                                     low, high, to_jax_dtype(dtype)))


@register_op("randint_like", "random", differentiable=False)
def randint_like(x, low=0, high=None, dtype=None, name=None):
    x = ensure_tensor(x)
    if high is None:
        low, high = 0, low
    dt = to_jax_dtype(dtype) if dtype else x._value.dtype
    return Tensor(jax.random.randint(get_rng_key(), x._value.shape, low, high)
                  .astype(dt))


@register_op("uniform", "random", differentiable=False)
def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.key(seed) if seed else get_rng_key()
    return Tensor(jax.random.uniform(key, _shape_list(shape), _dt(dtype),
                                     minval=scalar_or_value(min),
                                     maxval=scalar_or_value(max)))


@register_op("normal", "random", differentiable=False)
def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = ensure_tensor(mean)._value if isinstance(mean, Tensor) else mean
        s = ensure_tensor(std)._value if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(
            m.shape if hasattr(m, "shape") else (),
            s.shape if hasattr(s, "shape") else ())
        return Tensor(m + s * jax.random.normal(get_rng_key(), shp,
                                                _dt(None)))
    shp = _shape_list(shape) if shape is not None else []
    return Tensor(mean + std * jax.random.normal(get_rng_key(), shp, _dt(None)))


gauss = normal


@register_op("randperm", "random", differentiable=False)
def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(get_rng_key(), n)
                  .astype(to_jax_dtype(dtype)))


@register_op("bernoulli", "random", differentiable=False)
def bernoulli(x, name=None):
    x = ensure_tensor(x)
    # the key rides as a dispatch input (a hoisted stream position), so
    # sampling inside a training cycle stays keyable and promotable —
    # see framework/random.rng_key_input
    kd = rng_key_input()

    def fn(v, key_data):
        return jax.random.bernoulli(
            jax.random.wrap_key_data(key_data), v).astype(v.dtype)
    return call_op("bernoulli", fn, (x, kd))


@register_op("multinomial", "random", differentiable=False)
def multinomial(x, num_samples=1, replacement=False, name=None):
    x = ensure_tensor(x)
    v = x._value
    logits = jnp.log(jnp.clip(v / jnp.sum(v, axis=-1, keepdims=True),
                              1e-30, None))
    if replacement:
        out = jax.random.categorical(get_rng_key(), logits,
                                     shape=(num_samples,) + v.shape[:-1])
        out = jnp.moveaxis(out, 0, -1)
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(get_rng_key(), v.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(jnp.int64))


@register_op("poisson", "random", differentiable=False)
def poisson(x, name=None):
    x = ensure_tensor(x)
    return Tensor(jax.random.poisson(get_rng_key(), x._value)
                  .astype(x._value.dtype))


def exponential_(x, lam=1.0, name=None):
    x = ensure_tensor(x)
    x._value = jax.random.exponential(get_rng_key(), x._value.shape,
                                      x._value.dtype) / lam
    return x


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    x = ensure_tensor(x)
    key = jax.random.key(seed) if seed else get_rng_key()
    x._value = jax.random.uniform(key, x._value.shape, x._value.dtype,
                                  minval=min, maxval=max)
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    x = ensure_tensor(x)
    x._value = mean + std * jax.random.normal(get_rng_key(), x._value.shape,
                                              x._value.dtype)
    return x
