"""Random sampling ops. Reference analog: python/paddle/tensor/random.py over
phi uniform/gaussian kernels + the global Generator. TPU-first: every
registered sampler consumes the global fold_in STREAM through a HOISTED
position (`framework/random.rng_key_input`) passed as a dispatch input —
the key data is lazy, the op keys on structure, and a sampler inside a
training cycle promotes instead of poisoning it as `rng_rekey`
(ROADMAP 1(c), closed; analysis rule R2 pins the pattern at CI time).
The drawn bits are IDENTICAL to the old stateful `get_rng_key()` path:
both derive position i as `fold_in(base, i)`. Under jit tracing,
`rng_key_input` yields traced key data from the tracing scope, so
compiled steps keep fresh randomness exactly as before."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework.dtype import to_jax_dtype, get_default_dtype
from ..framework.random import get_rng_key, rng_key_input
from .registry import register_op
from ._helpers import ensure_tensor, scalar_or_value, call_op, const_input, \
    jnp_dtype

__all__ = ["rand", "randn", "randint", "randint_like", "uniform", "normal",
           "standard_normal", "randperm", "bernoulli", "multinomial",
           "poisson", "exponential_", "uniform_", "normal_", "gauss"]


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in shape.numpy().tolist()]
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape]


def _dt(dtype):
    return to_jax_dtype(dtype or get_default_dtype())


def _wrap(key_data):
    return jax.random.wrap_key_data(key_data)


@register_op("rand", "random", differentiable=False)
def rand(shape, dtype=None, name=None):
    shp, dt = tuple(_shape_list(shape)), _dt(dtype)
    kd = rng_key_input()

    def fn(key_data):
        return jax.random.uniform(_wrap(key_data), shp, dt)
    return call_op("rand", fn, (kd,))


@register_op("randn", "random", differentiable=False)
def randn(shape, dtype=None, name=None):
    shp, dt = tuple(_shape_list(shape)), _dt(dtype)
    kd = rng_key_input()

    def fn(key_data):
        return jax.random.normal(_wrap(key_data), shp, dt)
    return call_op("randn", fn, (kd,))


standard_normal = randn


@register_op("randint", "random", differentiable=False)
def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    shp, dt = tuple(_shape_list(shape)), to_jax_dtype(dtype)
    kd = rng_key_input()

    def fn(key_data):
        return jax.random.randint(_wrap(key_data), shp, low, high, dt)
    return call_op("randint", fn, (kd,))


@register_op("randint_like", "random", differentiable=False)
def randint_like(x, low=0, high=None, dtype=None, name=None):
    x = ensure_tensor(x)
    if high is None:
        low, high = 0, low
    # aval-safe shape/dtype peeks: sizing off a pending fused value must
    # not force it (the values never matter here, only the geometry)
    dt = to_jax_dtype(dtype) if dtype else jnp_dtype(x)
    shp = tuple(x.shape)
    kd = rng_key_input()

    def fn(key_data):
        return jax.random.randint(_wrap(key_data), shp, low, high).astype(dt)
    return call_op("randint_like", fn, (kd,))


@register_op("uniform", "random", differentiable=False)
def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    shp, dt = tuple(_shape_list(shape)), _dt(dtype)
    if seed:
        # explicit-seed contract: same seed -> same sample, no stream
        # position consumed — a deterministic draw, not stateful RNG
        return Tensor(jax.random.uniform(jax.random.key(seed), shp, dt,
                                         minval=scalar_or_value(min),
                                         maxval=scalar_or_value(max)))
    kd = rng_key_input()
    # Tensor-valued bounds ride as dispatch inputs; scalar bounds stay
    # keyable closure constants
    extra = tuple(b for b in (min, max) if isinstance(b, Tensor))
    mn = None if isinstance(min, Tensor) else min
    mx = None if isinstance(max, Tensor) else max

    def fn(key_data, *bounds):
        it = iter(bounds)
        lo = next(it) if mn is None else mn
        hi = next(it) if mx is None else mx
        return jax.random.uniform(_wrap(key_data), shp, dt,
                                  minval=lo, maxval=hi)
    return call_op("uniform", fn, (kd,) + extra)


@register_op("normal", "random", differentiable=False)
def normal(mean=0.0, std=1.0, shape=None, name=None):
    dt = _dt(None)
    kd = rng_key_input()
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m, s = ensure_tensor(mean), ensure_tensor(std)

        def fn(mv, sv, key_data):
            shp = jnp.broadcast_shapes(mv.shape, sv.shape)
            return mv + sv * jax.random.normal(_wrap(key_data), shp, dt)
        return call_op("normal", fn, (m, s, kd))
    shp = tuple(_shape_list(shape)) if shape is not None else ()

    def fn(key_data):
        return mean + std * jax.random.normal(_wrap(key_data), shp, dt)
    return call_op("normal", fn, (kd,))


gauss = normal


@register_op("randperm", "random", differentiable=False)
def randperm(n, dtype="int64", name=None):
    n, dt = int(n), to_jax_dtype(dtype)
    kd = rng_key_input()

    def fn(key_data):
        return jax.random.permutation(_wrap(key_data), n).astype(dt)
    return call_op("randperm", fn, (kd,))


@register_op("bernoulli", "random", differentiable=False)
def bernoulli(x, name=None):
    x = ensure_tensor(x)
    # the key rides as a dispatch input (a hoisted stream position), so
    # sampling inside a training cycle stays keyable and promotable —
    # see framework/random.rng_key_input
    kd = rng_key_input()

    def fn(v, key_data):
        return jax.random.bernoulli(_wrap(key_data), v).astype(v.dtype)
    return call_op("bernoulli", fn, (x, kd))


@register_op("multinomial", "random", differentiable=False)
def multinomial(x, num_samples=1, replacement=False, name=None):
    x = const_input(x)      # sampling draws no gradient through the probs
    kd = rng_key_input()

    def fn(v, key_data):
        key = _wrap(key_data)
        logits = jnp.log(jnp.clip(v / jnp.sum(v, axis=-1, keepdims=True),
                                  1e-30, None))
        if replacement:
            out = jax.random.categorical(key, logits,
                                         shape=(num_samples,) + v.shape[:-1])
            out = jnp.moveaxis(out, 0, -1)
        else:
            # Gumbel top-k trick for sampling without replacement
            g = jax.random.gumbel(key, v.shape)
            _, out = jax.lax.top_k(logits + g, num_samples)
        return out.astype(jnp.int64)
    return call_op("multinomial", fn, (x, kd))


@register_op("poisson", "random", differentiable=False)
def poisson(x, name=None):
    x = const_input(x)      # the counting draw is not differentiable
    kd = rng_key_input()

    def fn(v, key_data):
        return jax.random.poisson(_wrap(key_data), v).astype(v.dtype)
    return call_op("poisson", fn, (x, kd))


# -- in-place host-path variants (not registered ops: they mutate the
# tensor's storage directly and stay on the stateful generator) ------------

def exponential_(x, lam=1.0, name=None):
    x = ensure_tensor(x)
    x._value = jax.random.exponential(get_rng_key(), x._value.shape,
                                      x._value.dtype) / lam
    return x


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    x = ensure_tensor(x)
    key = jax.random.key(seed) if seed else get_rng_key()
    x._value = jax.random.uniform(key, x._value.shape, x._value.dtype,
                                  minval=min, maxval=max)
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    x = ensure_tensor(x)
    x._value = mean + std * jax.random.normal(get_rng_key(), x._value.shape,
                                              x._value.dtype)
    return x
