"""Sparse tensors. Reference analog: paddle/phi/core/sparse_coo_tensor.h +
python/paddle/sparse/ (3.5k LoC).

TPU-first: COO tensors are (indices, values) pairs; compute densifies through
XLA scatter/gather (TPUs have no native sparse units — the reference's GPU
sparse kernels map to segment-sum style dense ops here). BCSR is exposed via
jax.experimental.sparse for matmul-heavy paths.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..framework.core import Tensor
from ..ops._helpers import ensure_tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "is_same_shape", "add", "multiply", "matmul", "relu", "to_dense"]


class SparseCooTensor:
    def __init__(self, indices, values, shape, coalesced=False):
        self.indices = ensure_tensor(indices)
        self.values = ensure_tensor(values)
        self._dense_shape = [int(s) for s in shape]
        self.coalesced = coalesced

    @property
    def shape(self):
        return list(self._dense_shape)

    def to_dense(self):
        idx = self.indices._value
        out = jnp.zeros(tuple(self._dense_shape) ,
                        self.values._value.dtype)
        out = out.at[tuple(idx[i] for i in range(idx.shape[0]))] \
            .add(self.values._value)
        return Tensor(out)

    def nnz(self):
        return self.values.shape[0]

    def __repr__(self):
        return (f"SparseCooTensor(shape={self._dense_shape}, "
                f"nnz={self.nnz()})")


class SparseCsrTensor:
    def __init__(self, crows, cols, values, shape):
        self.crows = ensure_tensor(crows)
        self.cols = ensure_tensor(cols)
        self.values = ensure_tensor(values)
        self._dense_shape = [int(s) for s in shape]

    @property
    def shape(self):
        return list(self._dense_shape)

    def to_dense(self):
        crows = np.asarray(self.crows._value)
        cols = np.asarray(self.cols._value)
        vals = np.asarray(self.values._value)
        out = np.zeros(self._dense_shape, vals.dtype)
        rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
        out[rows, cols] = vals
        return Tensor(jnp.asarray(out))


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    values = ensure_tensor(values)
    indices = ensure_tensor(indices)
    if shape is None:
        idx = np.asarray(indices._value)
        shape = (idx.max(axis=1) + 1).tolist() + list(values.shape[1:])
    return SparseCooTensor(indices, values, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    return SparseCsrTensor(crows, cols, values, shape)


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


def to_dense(x):
    return x.to_dense() if hasattr(x, "to_dense") else x


def _dense_op(fn):
    def op(x, y=None):
        xd = to_dense(x)
        if y is None:
            return fn(xd)
        return fn(xd, to_dense(y))
    return op


def add(x, y):
    from ..ops.math import add as dense_add
    return _dense_op(dense_add)(x, y)


def multiply(x, y):
    from ..ops.math import multiply as dense_mul
    return _dense_op(dense_mul)(x, y)


def matmul(x, y):
    from ..ops.math import matmul as dense_matmul
    return _dense_op(dense_matmul)(x, y)


def relu(x):
    from ..nn.functional import relu as dense_relu
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(x.indices, dense_relu(x.values), x.shape)
    return dense_relu(x)


# --- value-wise unary ops (zero-preserving → sparsity pattern unchanged) ---
# Reference analog: python/paddle/sparse/unary.py (phi sparse_coo/csr
# kernels). Values go through the dense op dispatch so autograd flows.

def _unary_sparse(op_name):
    def op(x, name=None):
        from .. import ops as O
        fn = getattr(O, op_name)
        if isinstance(x, SparseCooTensor):
            return SparseCooTensor(x.indices, fn(x.values), x.shape,
                                   coalesced=x.coalesced)
        if isinstance(x, SparseCsrTensor):
            return SparseCsrTensor(x.crows, x.cols, fn(x.values), x.shape)
        return fn(ensure_tensor(x))
    op.__name__ = op_name
    return op


sin = _unary_sparse("sin")
tan = _unary_sparse("tan")
asin = _unary_sparse("asin")
atan = _unary_sparse("atan")
sinh = _unary_sparse("sinh")
tanh = _unary_sparse("tanh")
asinh = _unary_sparse("asinh")
atanh = _unary_sparse("atanh")
sqrt = _unary_sparse("sqrt")
square = _unary_sparse("square")
log1p = _unary_sparse("log1p")
abs = _unary_sparse("abs")
expm1 = _unary_sparse("expm1")
deg2rad = _unary_sparse("deg2rad")
rad2deg = _unary_sparse("rad2deg")


def neg(x, name=None):
    from ..ops import scale
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(x.indices, scale(x.values, -1.0), x.shape,
                               coalesced=x.coalesced)
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(x.crows, x.cols, scale(x.values, -1.0),
                               x.shape)
    return scale(ensure_tensor(x), -1.0)


def pow(x, factor, name=None):
    from ..ops import pow as dense_pow
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(x.indices, dense_pow(x.values, factor),
                               x.shape, coalesced=x.coalesced)
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(x.crows, x.cols, dense_pow(x.values, factor),
                               x.shape)
    return dense_pow(ensure_tensor(x), factor)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from ..framework.dtype import to_jax_dtype
    def cv(t, dt):
        return Tensor(t._value.astype(to_jax_dtype(dt))) if dt else t
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(cv(x.indices, index_dtype),
                               cv(x.values, value_dtype), x.shape,
                               coalesced=x.coalesced)
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(cv(x.crows, index_dtype),
                               cv(x.cols, index_dtype),
                               cv(x.values, value_dtype), x.shape)
    return cv(ensure_tensor(x), value_dtype)


# --- structure ops ---

def coalesce(x, name=None):
    """Merge duplicate COO indices by summation (reference:
    phi/kernels/sparse/coalesce_kernel.h). Segment-sum over the
    linearized index — the TPU-native pattern for scatter-reduce."""
    assert isinstance(x, SparseCooTensor)
    idx = x.indices._value.astype(jnp.int64)
    shape = x.shape
    sparse_ndim = idx.shape[0]
    flat = jnp.zeros_like(idx[0])
    for d in range(sparse_ndim):
        flat = flat * shape[d] + idx[d]
    uniq, inv = jnp.unique(flat, return_inverse=True, size=flat.shape[0],
                           fill_value=-1)
    n_uniq = int(jnp.sum(uniq >= 0))
    vals = jnp.zeros((flat.shape[0],) + x.values._value.shape[1:],
                     x.values._value.dtype)
    vals = vals.at[inv.reshape(-1)].add(x.values._value)
    # unravel kept (sorted-unique) flat indices back to nd
    kept = jnp.where(uniq >= 0, uniq, 0)
    new_idx = []
    rem = kept
    for d in reversed(range(sparse_ndim)):
        new_idx.append(rem % shape[d])
        rem = rem // shape[d]
    new_idx = jnp.stack(list(reversed(new_idx)))
    return SparseCooTensor(Tensor(new_idx[:, :n_uniq].astype(idx.dtype)),
                           Tensor(vals[:n_uniq]), shape, coalesced=True)


def transpose(x, perm, name=None):
    if isinstance(x, SparseCooTensor):
        idx = x.indices._value
        new_idx = jnp.stack([idx[p] for p in perm])
        new_shape = [x.shape[p] for p in perm]
        return SparseCooTensor(Tensor(new_idx), x.values, new_shape)
    from ..ops import transpose as dense_t
    return dense_t(to_dense(x), perm)


def reshape(x, shape, name=None):
    assert isinstance(x, SparseCooTensor), "sparse.reshape expects COO"
    old_shape = x.shape
    total = int(np.prod(old_shape))
    shape = list(shape)
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        shape[shape.index(-1)] = total // known
    idx = x.indices._value.astype(jnp.int64)
    flat = jnp.zeros_like(idx[0])
    for d in range(len(old_shape)):
        flat = flat * old_shape[d] + idx[d]
    new_idx = []
    rem = flat
    for d in reversed(range(len(shape))):
        new_idx.append(rem % shape[d])
        rem = rem // shape[d]
    new_idx = jnp.stack(list(reversed(new_idx)))
    return SparseCooTensor(Tensor(new_idx.astype(idx.dtype)), x.values,
                           shape, coalesced=x.coalesced)


# --- matmul family ---

def mv(x, vec, name=None):
    """Sparse matrix × dense vector. Reference:
    phi/kernels/sparse/mv_kernel.h. COO path is a gather+segment-sum —
    maps to XLA scatter-add, no dense [M,N] materialization."""
    vec = ensure_tensor(vec)
    if isinstance(x, SparseCooTensor) and len(x.shape) == 2:
        rows = x.indices._value[0]
        cols = x.indices._value[1]
        contrib = x.values._value * vec._value[cols]
        out = jnp.zeros((x.shape[0],), contrib.dtype).at[rows].add(contrib)
        return Tensor(out)
    from ..ops import matmul as dense_matmul
    return dense_matmul(to_dense(x), vec)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x@y) with sparse x (reference:
    python/paddle/sparse/multiary.py addmm)."""
    from ..ops import matmul as dense_matmul, scale, add as dense_add
    prod = dense_matmul(to_dense(x), to_dense(y))
    return dense_add(scale(to_dense(input), beta), scale(prod, alpha))


def masked_matmul(x, y, mask, name=None):
    """SDDMM: (x @ y) sampled at `mask`'s sparsity pattern → sparse out
    (reference: phi/kernels/sparse/masked_matmul kernel on cuSPARSE).
    TPU-first: per-nonzero row·col dot via gather — O(nnz·K), no dense
    [M,N] product."""
    x = ensure_tensor(x)
    y = ensure_tensor(y)
    if isinstance(mask, SparseCsrTensor):
        crows = np.asarray(mask.crows._value)
        cols_v = mask.cols._value
        rows_np = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
        rows_v = jnp.asarray(rows_np)
        vals = jnp.sum(x._value[rows_v] * y._value[:, cols_v].T, axis=-1)
        return SparseCsrTensor(mask.crows, mask.cols, Tensor(vals),
                               [x.shape[0], y.shape[1]])
    assert isinstance(mask, SparseCooTensor)
    rows_v = mask.indices._value[0]
    cols_v = mask.indices._value[1]
    vals = jnp.sum(x._value[rows_v] * y._value[:, cols_v].T, axis=-1)
    return SparseCooTensor(mask.indices, Tensor(vals),
                           [x.shape[0], y.shape[1]])


def subtract(x, y, name=None):
    from ..ops import subtract as dense_sub
    return _dense_op(dense_sub)(x, y)


def divide(x, y, name=None):
    from ..ops import divide as dense_div
    return _dense_op(dense_div)(x, y)


__all__ += ["SparseCsrTensor", "sin", "tan", "asin", "atan", "sinh", "tanh",
            "asinh", "atanh", "sqrt", "square", "log1p", "abs", "pow",
            "cast", "neg", "deg2rad", "rad2deg", "expm1", "mv",
            "masked_matmul", "addmm", "subtract", "transpose", "divide",
            "coalesce", "reshape"]

from . import nn  # noqa: F401,E402
