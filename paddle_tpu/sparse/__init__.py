"""Sparse tensors. Reference analog: paddle/phi/core/sparse_coo_tensor.h +
python/paddle/sparse/ (3.5k LoC).

TPU-first: COO tensors are (indices, values) pairs; compute densifies through
XLA scatter/gather (TPUs have no native sparse units — the reference's GPU
sparse kernels map to segment-sum style dense ops here). BCSR is exposed via
jax.experimental.sparse for matmul-heavy paths.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..framework.core import Tensor
from ..ops._helpers import ensure_tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "is_same_shape", "add", "multiply", "matmul", "relu", "to_dense"]


class SparseCooTensor:
    def __init__(self, indices, values, shape, coalesced=False):
        self.indices = ensure_tensor(indices)
        self.values = ensure_tensor(values)
        self._dense_shape = [int(s) for s in shape]
        self.coalesced = coalesced

    @property
    def shape(self):
        return list(self._dense_shape)

    def to_dense(self):
        idx = self.indices._value
        out = jnp.zeros(tuple(self._dense_shape) ,
                        self.values._value.dtype)
        out = out.at[tuple(idx[i] for i in range(idx.shape[0]))] \
            .add(self.values._value)
        return Tensor(out)

    def nnz(self):
        return self.values.shape[0]

    def __repr__(self):
        return (f"SparseCooTensor(shape={self._dense_shape}, "
                f"nnz={self.nnz()})")


class SparseCsrTensor:
    def __init__(self, crows, cols, values, shape):
        self.crows = ensure_tensor(crows)
        self.cols = ensure_tensor(cols)
        self.values = ensure_tensor(values)
        self._dense_shape = [int(s) for s in shape]

    @property
    def shape(self):
        return list(self._dense_shape)

    def to_dense(self):
        crows = np.asarray(self.crows._value)
        cols = np.asarray(self.cols._value)
        vals = np.asarray(self.values._value)
        out = np.zeros(self._dense_shape, vals.dtype)
        rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
        out[rows, cols] = vals
        return Tensor(jnp.asarray(out))


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    values = ensure_tensor(values)
    indices = ensure_tensor(indices)
    if shape is None:
        idx = np.asarray(indices._value)
        shape = (idx.max(axis=1) + 1).tolist() + list(values.shape[1:])
    return SparseCooTensor(indices, values, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    return SparseCsrTensor(crows, cols, values, shape)


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


def to_dense(x):
    return x.to_dense() if hasattr(x, "to_dense") else x


def _dense_op(fn):
    def op(x, y=None):
        xd = to_dense(x)
        if y is None:
            return fn(xd)
        return fn(xd, to_dense(y))
    return op


def add(x, y):
    from ..ops.math import add as dense_add
    return _dense_op(dense_add)(x, y)


def multiply(x, y):
    from ..ops.math import multiply as dense_mul
    return _dense_op(dense_mul)(x, y)


def matmul(x, y):
    from ..ops.math import matmul as dense_matmul
    return _dense_op(dense_matmul)(x, y)


def relu(x):
    from ..nn.functional import relu as dense_relu
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(x.indices, dense_relu(x.values), x.shape)
    return dense_relu(x)
