"""paddle.sparse.nn — layers over sparse tensors (reference:
python/paddle/sparse/nn: activations, BatchNorm/SyncBatchNorm on values,
Conv3D/SubmConv3D/MaxPool3D via the functional forms)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...nn.layer_base import Layer
from ...nn.initializer_util import materialize_parameter
from ...nn import initializer as I
from .. import SparseCooTensor
from . import functional as F

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "BatchNorm",
           "SyncBatchNorm", "Conv3D", "SubmConv3D", "MaxPool3D"]


class ReLU(Layer):
    def forward(self, x):
        return F.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return F.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._slope)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)


class BatchNorm(Layer):
    """BatchNorm over the VALUES of a sparse tensor (reference
    sparse/nn/layer/norm.py BatchNorm — channels-last values [nnz, C])."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        self._momentum = momentum
        self._epsilon = epsilon
        self.weight = materialize_parameter(
            [num_features], weight_attr, self._dtype,
            default_initializer=I.Constant(1.0))
        self.bias = materialize_parameter(
            [num_features], bias_attr, self._dtype, is_bias=True)
        self._mean = jnp.zeros((num_features,), jnp.float32)
        self._variance = jnp.ones((num_features,), jnp.float32)

    def forward(self, x):
        vals = x.values if isinstance(x, SparseCooTensor) else x
        v = vals._value
        if self.training:
            mean = v.mean(0)
            var = v.var(0)
            m = self._momentum
            self._mean = m * self._mean + (1 - m) * mean
            self._variance = m * self._variance + (1 - m) * var
        else:
            mean, var = self._mean, self._variance
        from ...framework.core import Tensor
        out = (v - mean) / jnp.sqrt(var + self._epsilon) \
            * self.weight._value + self.bias._value
        out_t = Tensor(out)
        if isinstance(x, SparseCooTensor):
            return SparseCooTensor(x.indices, out_t, x.shape,
                                   coalesced=x.coalesced)
        return out_t


class SyncBatchNorm(BatchNorm):
    """Cross-replica BatchNorm (reference sparse SyncBatchNorm): under a
    jitted SPMD program XLA's batch statistics are already global per
    sharded batch; the eager single-controller form equals BatchNorm."""


class Conv3D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__()
        k = kernel_size if isinstance(kernel_size, (list, tuple)) \
            else (kernel_size,) * 3
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self.weight = materialize_parameter(
            list(k) + [in_channels // groups, out_channels], weight_attr,
            self._dtype, default_initializer=I.XavierNormal())
        self.bias = materialize_parameter(
            [out_channels], bias_attr, self._dtype, is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        return F.conv3d(x, self.weight, bias=self.bias,
                        stride=self._stride, padding=self._padding,
                        dilation=self._dilation, groups=self._groups)


class SubmConv3D(Conv3D):
    def forward(self, x):
        return F.subm_conv3d(x, self.weight, bias=self.bias,
                             stride=1, padding=self._padding,
                             dilation=self._dilation, groups=self._groups)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False, data_format="NDHWC",
                 name=None):
        super().__init__()
        self._k = kernel_size
        self._stride = stride
        self._padding = padding

    def forward(self, x):
        return F.max_pool3d(x, self._k, stride=self._stride,
                            padding=self._padding)
