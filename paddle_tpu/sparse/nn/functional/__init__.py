"""paddle.sparse.nn.functional (reference: python/paddle/sparse/nn/
functional — conv3d/subm_conv3d/max_pool3d/activations/attention over the
phi sparse kernels).

TPU-first: activations apply to the VALUES (zero-preserving, pattern
unchanged); the spatial ops (conv3d / subm_conv3d / max_pool3d) densify,
run the MXU-tiled dense op, and re-sparsify. On TPU that IS the fast path
for the occupancies sparse conv targets — the MXU wants dense tiles, and
gather/scatter spconv has no systolic mapping (pallas_guide.md).
subm_conv3d masks the output back to the input's active sites (submanifold
semantics, reference subm_conv3d docs)."""
from __future__ import annotations

import jax.numpy as jnp

from ....framework.core import Tensor
from ....ops._helpers import ensure_tensor
from .... import sparse as _sp

__all__ = ["conv3d", "subm_conv3d", "max_pool3d", "relu", "relu6",
           "leaky_relu", "softmax", "attention"]


def _values_op(x, fn):
    if isinstance(x, _sp.SparseCooTensor):
        return _sp.SparseCooTensor(x.indices, fn(x.values), x.shape,
                                   coalesced=x.coalesced)
    if isinstance(x, _sp.SparseCsrTensor):
        return _sp.SparseCsrTensor(x.crows, x.cols, fn(x.values), x.shape)
    return fn(ensure_tensor(x))


def relu(x, name=None):
    import paddle_tpu.nn.functional as F
    return _values_op(x, F.relu)


def relu6(x, name=None):
    import paddle_tpu.nn.functional as F
    return _values_op(x, F.relu6)


def leaky_relu(x, negative_slope=0.01, name=None):
    import paddle_tpu.nn.functional as F
    return _values_op(x, lambda v: F.leaky_relu(v, negative_slope))


def softmax(x, axis=-1, name=None):
    """Sparse softmax: normalizes over the stored values per row, treating
    absent entries as -inf (reference sparse softmax semantics)."""
    if isinstance(x, _sp.SparseCsrTensor):
        import numpy as np
        crows = np.asarray(x.crows._value)
        vals = x.values._value
        out = []
        for r in range(len(crows) - 1):
            seg = vals[int(crows[r]):int(crows[r + 1])]
            if seg.shape[0]:
                e = jnp.exp(seg - seg.max())
                out.append(e / e.sum())
        new_vals = jnp.concatenate(out) if out else vals
        return _sp.SparseCsrTensor(x.crows, x.cols, Tensor(new_vals),
                                   x.shape)
    import paddle_tpu.nn.functional as F
    return _values_op(x, lambda v: F.softmax(v, axis=axis))


def _dense_to_coo(dense, sparse_ndim):
    """Re-sparsify: active site = any nonzero along the trailing dense
    (channel) dims."""
    import numpy as np
    v = np.asarray(dense._value)
    reduce_axes = tuple(range(sparse_ndim, v.ndim))
    active = np.abs(v).sum(axis=reduce_axes) != 0 if reduce_axes else \
        v != 0
    idx = np.stack(np.nonzero(active))
    vals = dense._value[tuple(jnp.asarray(idx[i])
                              for i in range(idx.shape[0]))]
    return _sp.SparseCooTensor(Tensor(jnp.asarray(idx)), Tensor(vals),
                               list(v.shape), coalesced=True)


def _dense_path(x, dense_fn, mask_to_input_sites=False):
    """densify -> dense op -> re-sparsify (active site = nonzero)."""
    dense = x.to_dense() if isinstance(x, _sp.SparseCooTensor) else \
        ensure_tensor(x)
    out = dense_fn(dense)
    if not isinstance(x, _sp.SparseCooTensor):
        return out
    if mask_to_input_sites:
        # submanifold: output active only where the input was active
        site = jnp.zeros(tuple(x.shape[:-1]) + (1,), out._value.dtype)
        idx = x.indices._value
        site = site.at[tuple(idx[i] for i in range(idx.shape[0] - 1))
                       + (0,)].set(1.0)
        out = Tensor(out._value * site)
    return _dense_to_coo(out, len(x.shape) - 1)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    """Sparse conv3d: x is a 5-D NDHWC SparseCooTensor, weight
    [kd, kh, kw, in_c, out_c] (reference sparse conv3d layout)."""
    import paddle_tpu.nn.functional as F
    from ....ops import manipulation as manip
    w = ensure_tensor(weight)

    def run(dense):
        # NDHWC -> NCDHW for the dense kernel, weight -> [out, in, kd, kh, kw]
        xd = manip.transpose(dense, [0, 4, 1, 2, 3])
        wd = manip.transpose(w, [4, 3, 0, 1, 2])
        out = F.conv3d(xd, wd, bias=bias, stride=stride, padding=padding,
                       dilation=dilation, groups=groups)
        return manip.transpose(out, [0, 2, 3, 4, 1])

    return _dense_path(x, run)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """Submanifold sparse conv3d: the output pattern equals the input
    pattern (reference subm_conv3d). Requires stride 1 (like the
    reference's practical use)."""
    import paddle_tpu.nn.functional as F
    from ....ops import manipulation as manip
    w = ensure_tensor(weight)
    k = w.shape[0:3]
    same_pad = [(kk - 1) // 2 for kk in k]

    def run(dense):
        xd = manip.transpose(dense, [0, 4, 1, 2, 3])
        wd = manip.transpose(w, [4, 3, 0, 1, 2])
        out = F.conv3d(xd, wd, bias=bias, stride=1, padding=same_pad,
                       dilation=dilation, groups=groups)
        return manip.transpose(out, [0, 2, 3, 4, 1])

    return _dense_path(x, run, mask_to_input_sites=True)


def max_pool3d(x, kernel_size, stride=None, padding=0,
               data_format="NDHWC", name=None):
    import paddle_tpu.nn.functional as F
    from ....ops import manipulation as manip

    def run(dense):
        xd = manip.transpose(dense, [0, 4, 1, 2, 3])
        out = F.max_pool3d(xd, kernel_size, stride=stride, padding=padding)
        return manip.transpose(out, [0, 2, 3, 4, 1])

    return _dense_path(x, run)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse-pattern attention (reference sparse/nn/functional/
    transformer.py attention over CSR masks): scores restricted to the
    CSR sparse_mask's pattern."""
    import math
    import numpy as np
    q = ensure_tensor(query)._value
    k = ensure_tensor(key)._value
    v = ensure_tensor(value)._value
    scores = jnp.einsum("bhnd,bhmd->bhnm", q, k) / math.sqrt(q.shape[-1])
    crows = np.asarray(sparse_mask.crows._value).reshape(-1)
    cols = np.asarray(sparse_mask.cols._value).reshape(-1)
    n = q.shape[2]
    # mask: allowed (row, col) pairs from the CSR pattern (shared across
    # batch*heads, reference requires the mask's batch dims to match)
    per_row = np.diff(crows[:n + 1])
    rows = np.repeat(np.arange(n), per_row)
    allow = np.zeros((n, scores.shape[-1]), bool)
    allow[rows, cols[:rows.size]] = True
    masked = jnp.where(jnp.asarray(allow), scores, -1e30)
    import jax
    probs = jax.nn.softmax(masked, axis=-1)
    out = jnp.einsum("bhnm,bhmd->bhnd", probs, v)
    return Tensor(out)
