"""Lazy layer construction (`paddle.LazyGuard`).

Reference analog: python/paddle/fluid/lazy_init.py — under LazyGuard, layer
construction does not allocate/initialize parameters on the accelerator.

TPU-first reading: the reason to defer init is to avoid materializing a
model too big for one chip before its sharding is known. Here parameters
created under the guard are initialized on the *host* (CPU backend) — a
cheap, deterministic materialization in host RAM; the first jitted use (or
an explicit NamedSharding placement) moves them to device with the final
layout, so no oversized device allocation ever happens.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["LazyGuard", "in_lazy_mode"]

_state = threading.local()


def in_lazy_mode() -> bool:
    return getattr(_state, "depth", 0) > 0


class LazyGuard:
    def __enter__(self):
        _state.depth = getattr(_state, "depth", 0) + 1
        try:
            cpu = jax.local_devices(backend="cpu")[0]
            self._dev_ctx = jax.default_device(cpu)
            self._dev_ctx.__enter__()
        except RuntimeError:  # no host backend registered — degrade to eager
            self._dev_ctx = None
        return self

    def __exit__(self, *exc):
        if self._dev_ctx is not None:
            self._dev_ctx.__exit__(*exc)
        _state.depth -= 1
        return False
