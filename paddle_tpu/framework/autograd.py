"""Define-by-run autograd engine over jax VJPs.

Reference analog: paddle/fluid/eager/ — GradNodeBase/Edge (grad_node_info.h:168,50),
engine RunBackward (backward.cc:105, in-degree map + ready queue), accumulation
node (eager/accumulation/), hooks (hooks.h).

TPU-first design: instead of hand-written per-op grad kernels, each forward op
captures its VJP via `jax.vjp` at dispatch time (residuals live as jax arrays on
device). Backward is the same topo-ordered ready-queue walk as the reference,
but every node's backward is a single XLA-compiled callable.
"""
from __future__ import annotations

import threading
from collections import deque

import jax
import jax.numpy as jnp

__all__ = [
    "GradNode", "FusedChainNode", "FusedStepNode", "AccumulationNode",
    "run_backward", "grad", "no_grad", "enable_grad", "set_grad_enabled",
    "is_grad_enabled",
]

_state = threading.local()


def is_grad_enabled() -> bool:
    return getattr(_state, "grad_enabled", True)


def _set(flag: bool):
    _state.grad_enabled = flag


class set_grad_enabled:
    """Context manager / decorator toggling grad tracking."""

    def __init__(self, mode: bool):
        self._mode = mode
        self._prev = None

    def __enter__(self):
        self._prev = is_grad_enabled()
        _set(self._mode)
        return self

    def __exit__(self, *exc):
        _set(self._prev)
        return False


class _GradModeDecorator:
    mode = False

    def __init__(self, func=None):
        self._func = func

    def __call__(self, *args, **kwargs):
        if self._func is not None:
            with set_grad_enabled(self.mode):
                return self._func(*args, **kwargs)
        # `@no_grad()` usage: instance called with the function to wrap
        if len(args) == 1 and callable(args[0]) and not kwargs:
            return type(self)(args[0])
        raise TypeError("no_grad: expected a callable to wrap")

    def __enter__(self):
        self._ctx = set_grad_enabled(self.mode)
        return self._ctx.__enter__()

    def __exit__(self, *exc):
        return self._ctx.__exit__(*exc)


class no_grad(_GradModeDecorator):
    """`paddle.no_grad` — usable as context manager or decorator."""
    mode = False


class enable_grad(_GradModeDecorator):
    mode = True


class GradNode:
    """One node per forward op invocation.

    Holds the op's vjp callable, edges to producer nodes (one per tensor input),
    and output metadata so missing output grads can be zero-filled.
    """

    __slots__ = ("name", "vjp_fn", "edges", "out_avals", "pending",
                 "out_hooks", "retain_count", "fwd_fn", "in_vals",
                 "unpack_hook")

    def __init__(self, name, vjp_fn, edges, out_avals):
        self.name = name
        self.vjp_fn = vjp_fn
        # edges[i] = (producer_node, producer_out_index) or None (stop_gradient)
        self.edges = edges
        # out_avals[j] = (shape, jnp dtype) of forward output j
        self.out_avals = out_avals
        self.pending = {}       # out_index -> accumulated incoming grad
        self.out_hooks = {}     # out_index -> [callable]
        self.retain_count = 0
        # recorded forward (pure fn over full input values) + the input
        # values themselves: lets grad(create_graph=True) re-derive the
        # whole subgraph functionally (higher-order AD by replay, the
        # TPU-first analog of eager/general_grad.h double-grad nodes)
        self.fwd_fn = None
        self.in_vals = None
        self.unpack_hook = None

    # -- engine interface ---------------------------------------------------
    def add_grad(self, out_index: int, g):
        cur = self.pending.get(out_index)
        self.pending[out_index] = g if cur is None else cur + g

    def collect_input_grads(self, final=False):
        """Run hooks, zero-fill missing output grads, call vjp; returns tuple of
        grads aligned with self.edges. `final=True` (this node will be
        released right after — no retained graph) lets a dispatch-cached
        pullback donate its residual buffers to the backward executable."""
        outs = []
        for j, (shape, dt) in enumerate(self.out_avals):
            g = self.pending.get(j)
            if g is None:
                g = _zero_cotangent(shape, dt)
            else:
                for hook in self.out_hooks.get(j, ()):
                    newg = hook(g)
                    if newg is not None:
                        g = newg
            outs.append(g)
        self.pending = {}
        arg = tuple(outs) if len(outs) > 1 else outs[0]
        if final and getattr(self.vjp_fn, "_supports_donate", False):
            grads = self.vjp_fn(arg, donate=True)
        else:
            grads = self.vjp_fn(arg)
        if not isinstance(grads, tuple):
            grads = (grads,)
        return grads

    def release(self):
        self.vjp_fn = None
        self.pending = {}
        # free the recorded forward too — after a non-retained backward the
        # graph is spent (same contract as the vjp residuals). The sentinel
        # distinguishes "spent" from "never recorded" (PyLayer/to_static)
        # so replay errors point at the real cause.
        self.fwd_fn = _RELEASED
        self.in_vals = None


_RELEASED = object()

# Zero-cotangent buffers for outputs nothing fed a grad into — hot for
# FusedChainNode, whose flat output tuple includes every chain intermediate
# (a linear chain zero-fills all but the last slot on every backward).
# Zeros are immutable and never donated (appliers donate residuals, not
# cotangents), so one device buffer per (shape, dtype) is safe to share.
# Only buffers ≤ _COTANGENT_CACHE_MAX_BYTES are kept: the win is the saved
# eager dispatch, which small shapes dominate — pinning activation-sized
# device buffers for the process lifetime would trade transient allocation
# for persistent memory pressure.
_COTANGENT_CACHE_MAX_BYTES = 1 << 20


def _fill_cotangent(cache, fill, shape, dt):
    key = (tuple(shape), dt)
    z = cache.get(key)
    if z is None:
        z = fill(shape, dt)
        if z.nbytes <= _COTANGENT_CACHE_MAX_BYTES:
            if len(cache) >= 256:
                cache.clear()
            cache[key] = z
    return z


_zero_cache: dict = {}


def _zero_cotangent(shape, dt):
    return _fill_cotangent(_zero_cache, jnp.zeros, shape, dt)


# same contract for the default backward seed (∂loss/∂loss = 1): an eager
# jnp.ones is a full uncompiled dispatch (~30% of a small fused train step
# on CPU) paid on every .backward()/grad() call
_ones_cache: dict = {}


def _one_cotangent(shape, dt):
    return _fill_cotangent(_ones_cache, jnp.ones, shape, dt)


class FusedChainNode(GradNode):
    """One tape node owning the outputs of MULTIPLE logical forward ops — the
    grad node a fused op-chain executable records (ops/fusion.py).

    Where a normal GradNode owns one op invocation's outputs, a fused node's
    `out_avals` concatenates every constituent op's outputs in chain order,
    and `edges` point at the chain's EXTERNAL inputs only (one edge per
    external slot; chain-internal dataflow lives inside the fused vjp).
    `out_index` on a tensor produced mid-chain addresses its slot in the
    flattened output tuple, so downstream consumers, output hooks, and
    partial backward through a side output all work exactly as they do on a
    multi-output GradNode — the engine never needs to know the outputs came
    from different logical ops. `owners[j] = (op position in chain, local
    out index)` keeps the logical attribution for diagnostics and telemetry.
    """

    __slots__ = ("op_names", "owners")

    def __init__(self, op_names, vjp_fn, edges, out_avals, owners):
        super().__init__("fused_chain(" + "→".join(op_names) + ")",
                         vjp_fn, edges, out_avals)
        self.op_names = tuple(op_names)
        self.owners = tuple(owners)

    def output_owner(self, out_index):
        """(op name, local output index) of a flattened chain output."""
        pos, local = self.owners[out_index]
        return self.op_names[pos], local


class FusedStepNode(GradNode):
    """Tape node recorded on the ROOT output (the loss) of a fused
    whole-step replay (ops/step_fusion.py auto-TrainStep).

    A fused step consumes its own backward: the gradients were computed
    inside the whole-step executable and the parameters are already
    updated, so this node exists only to make the root tensor LOOK like a
    backward-consumed output — `is_leaf` is False, diagnostics name the
    fused step — and to turn a second `.backward()` into a clear error
    instead of a silent no-op (the unfused tape errors there too: the
    graph is released after a non-retained backward)."""

    __slots__ = ("step_label",)

    def __init__(self, step_label, out_aval):
        super().__init__(f"fused_step({step_label})", self._consumed,
                         (), (out_aval,))
        self.step_label = step_label
        self.fwd_fn = _RELEASED   # replay sees "spent", like any released op

    @staticmethod
    def _consumed(_g, donate=False):
        raise RuntimeError(
            "this tensor was produced by a fused whole-step replay "
            "(auto-TrainStep): its backward already ran inside the fused "
            "executable and the graph is consumed. Re-run with "
            "FLAGS_eager_step_fusion=False (or retain_graph semantics) if "
            "a second backward is required")

# ---------------------------------------------------------------------------
# saved-tensors hooks (reference: python/paddle/autograd
# saved_tensors_hooks / eager/saved_tensors_hooks.cc). Scope here: the
# tape's REPLAY-saved input values (GradNode.in_vals, consumed by
# create_graph double-grad replay) — XLA owns its vjp residuals, so the
# canonical pack-to-host memory trade applies to the tape-held state.

_saved_tensor_hooks = []


class saved_tensors_hooks:
    """Context manager: `pack_hook(tensor)` runs when the tape saves a
    tensor, its result is stored instead; `unpack_hook(packed)` runs when
    backward/replay needs the value back."""

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        # flight recorder: active hooks silently block chain/step fusion
        # (every backward inside this scope poisons its cycle), so the
        # installation itself is worth a timeline marker
        from ..profiler.events import EVENTS as _EVENTS
        _EVENTS.emit("step.record", "saved_tensors_hooks",
                     reason="hook_present",
                     detail={"kind": "hooks_installed"})
        _saved_tensor_hooks.append((self.pack_hook, self.unpack_hook))
        return self

    def __exit__(self, *exc):
        _saved_tensor_hooks.pop()
        return False


def pack_saved_values(vals, edges=None):
    """Called by the dispatch funnel at record time: returns
    (stored_values, unpack_hook_or_None). Only inputs that replay will
    actually READ from in_vals (edge is None — stop-gradient constants)
    are packed; differentiable inputs replay through their producer edges,
    so packing them would run side-effectful hooks for values never
    unpacked."""
    if not _saved_tensor_hooks:
        return vals, None
    from .core import Tensor
    pack, unpack = _saved_tensor_hooks[-1]
    stored = tuple(
        pack(Tensor(v, stop_gradient=True))
        if edges is None or edges[i] is None else v
        for i, v in enumerate(vals))
    return stored, unpack


def _run_unpack(unpack, packed):
    from .core import Tensor
    out = unpack(packed)
    return out._value if isinstance(out, Tensor) else jnp.asarray(out)


class AccumulationNode(GradNode):
    """Terminal node for a leaf tensor: writes into tensor.grad.

    Reference analog: eager/accumulation/accumulation_node.h.
    """

    __slots__ = ("tensor_ref",)

    def __init__(self, tensor):
        import weakref
        super().__init__("accumulation", None, (), ((tensor.shape, tensor._value.dtype),))
        self.tensor_ref = weakref.ref(tensor)

    def accumulate(self):
        t = self.tensor_ref()
        g = self.pending.get(0)
        self.pending = {}
        if t is None or g is None:
            return
        # paddle.grad() restricts accumulation to its requested inputs so
        # other leaves' .grad is not polluted (GeneralGrad semantics)
        allowed = getattr(_state, "grad_filter", None)
        if allowed is not None and id(t) not in allowed:
            return
        for hook in self.out_hooks.get(0, ()):
            newg = hook(g)
            if newg is not None:
                g = newg
        for hook in t._hooks:
            # tensor-level hooks registered via Tensor.register_hook receive
            # and may replace the grad (paddle semantics)
            from .core import Tensor
            res = hook(Tensor(g, stop_gradient=True))
            if res is not None:
                g = res._value if hasattr(res, "_value") else jnp.asarray(res)
        cur = t.grad    # snapshot: a concurrent clear_grad (hogwild
        # threads, multi_trainer.cc semantics) must not crash accumulation
        if cur is None:
            from .core import Tensor
            cur = Tensor(g, stop_gradient=True)
            cur.name = t.name + "@GRAD" if t.name else "grad"
            t.grad = cur
        else:
            cur._value = cur._value + g


def _count_dependencies(root: GradNode):
    """BFS the reachable subgraph; in_degree[node] = #edges into it from
    reachable nodes. Mirrors backward.cc:22 getInDegreeMap."""
    in_degree = {}
    seen = {root}
    q = deque([root])
    while q:
        node = q.popleft()
        for edge in node.edges:
            if edge is None:
                continue
            nxt = edge[0]
            in_degree[nxt] = in_degree.get(nxt, 0) + 1
            if nxt not in seen:
                seen.add(nxt)
                q.append(nxt)
    return in_degree, seen


def run_backward(root_node: GradNode, root_index: int, seed_grad,
                 retain_graph: bool = False):
    """Topo-ordered ready-queue walk from a single root output.

    Reference analog: egr::RunBackward (eager/backward.cc:105).
    """
    in_degree, reachable = _count_dependencies(root_node)
    root_node.add_grad(root_index, seed_grad)
    ready = deque([root_node])
    # nodes whose in-degree never reaches 0 cannot fire; with a DAG from a
    # single root this terminates with all reachable nodes fired.
    while ready:
        node = ready.popleft()
        if isinstance(node, AccumulationNode):
            node.accumulate()
            continue
        if isinstance(node, FusedChainNode):
            # flight recorder: the chain's single fused vjp fires here —
            # the backward half of the chain.fire the forward replay logged
            from ..profiler.events import EVENTS as _EVENTS
            _EVENTS.emit("chain.fire", node.name,
                         detail={"phase": "bwd",
                                 "ops": len(node.op_names)})
        grads = node.collect_input_grads(final=not retain_graph)
        if not retain_graph:
            node.release()
        for edge, g in zip(node.edges, grads):
            if edge is None or g is None:
                continue
            nxt, out_idx = edge
            nxt.add_grad(out_idx, g)
            in_degree[nxt] -= 1
            if in_degree[nxt] == 0:
                ready.append(nxt)


def _reachable_nodes(outputs):
    """(ids, nodes) of all GradNodes reachable from the outputs' nodes."""
    seen, nodes, q = set(), [], deque()
    for out in outputs:
        node = out._grad_node
        if node is not None and id(node) not in seen:
            seen.add(id(node))
            nodes.append(node)
            q.append(node)
    while q:
        node = q.popleft()
        for edge in node.edges:
            if edge is not None and id(edge[0]) not in seen:
                seen.add(id(edge[0]))
                nodes.append(edge[0])
                q.append(edge[0])
    return seen, nodes


def replay_pure(outputs, inputs):
    """Build a PURE function F(*input_values) -> tuple(output_values) by
    replaying the recorded op graph between `inputs` and `outputs`.

    This is the TPU-first route to higher-order autograd: instead of taping
    backward ops as the reference's double-grad nodes do
    (eager/general_grad.h), the captured graph is re-derived as one jax
    function, so any jax transform (vjp for double grad, jvp for
    forward-over-reverse) applies to it — and everything XLA-compiles.
    """
    import sys

    in_keys = [(id(t._ensure_grad_node()
                   if t._grad_node is None else t._grad_node), t._out_index)
               for t in inputs]

    def F(*in_vals):
        env = dict(zip(in_keys, in_vals))
        memo = {}

        def value_of(node, out_idx):
            key = (id(node), out_idx)
            if key in env:
                return env[key]
            if isinstance(node, AccumulationNode):
                t = node.tensor_ref()
                if t is None:
                    raise RuntimeError(
                        "a leaf tensor of the recorded graph was freed; "
                        "cannot replay for create_graph")
                return t._value
            return compute(node)[out_idx]

        def compute(node):
            outs = memo.get(id(node))
            if outs is not None:
                return outs
            if node.fwd_fn is _RELEASED:
                raise RuntimeError(
                    f"op '{node.name}' was released (backward already ran "
                    "without retain_graph); cannot replay for create_graph")
            if node.fwd_fn is None:
                raise RuntimeError(
                    f"op '{node.name}' did not record a replayable forward "
                    "(PyLayer / to_static subgraphs are not supported in "
                    "create_graph=True double grad yet)")
            args = []
            for i, edge in enumerate(node.edges):
                if edge is None:
                    v = node.in_vals[i]
                    if node.unpack_hook is not None:
                        v = _run_unpack(node.unpack_hook, v)
                    args.append(v)
                else:
                    args.append(value_of(*edge))
            outs = node.fwd_fn(*args)
            if not isinstance(outs, tuple):
                outs = (outs,)
            memo[id(node)] = outs
            return outs

        old = sys.getrecursionlimit()
        sys.setrecursionlimit(max(old, 20000))
        try:
            return tuple(
                value_of(out._grad_node, out._out_index)
                if out._grad_node is not None else out._value
                for out in outputs)
        finally:
            sys.setrecursionlimit(old)

    return F


def _leaves_of(rnodes, exclude_ids):
    """Live leaf tensors (AccumulationNodes) among `rnodes`, minus
    `exclude_ids`."""
    leaves = []
    for node in rnodes:
        if isinstance(node, AccumulationNode):
            t = node.tensor_ref()
            if t is not None and id(t) not in exclude_ids:
                leaves.append(t)
    return leaves


def reachable_leaves(outputs, exclude_ids=()):
    """Leaf tensors of the recorded subgraph under `outputs`, for callers
    (incubate forward_grad) that must thread them through dispatched replay
    ops to keep results differentiable w.r.t. them."""
    _, rnodes = _reachable_nodes(outputs)
    return _leaves_of(rnodes, set(exclude_ids))


def _grad_create_graph(outputs, inputs, grad_outputs, allow_unused):
    """grad(create_graph=True): differentiable gradients by replay + jax.vjp,
    dispatched through the op funnel so results carry their own GradNodes
    (and so third and higher orders recurse for free)."""
    from .core import Tensor
    from ..ops.dispatch import call_op_multi

    reachable, rnodes = _reachable_nodes(outputs)
    connected = []
    for t in inputs:
        node = t._ensure_grad_node() if t._grad_node is None \
            else t._grad_node
        connected.append(id(node) in reachable)
    if not all(connected) and not allow_unused:
        bad = [t.name for t, c in zip(inputs, connected) if not c]
        raise RuntimeError(
            f"differentiated tensors {bad} appear unused in the graph; "
            "set allow_unused=True to return None for them")
    conn = [t for t, c in zip(inputs, connected) if c]
    if not conn:
        return [None] * len(inputs)

    # every OTHER differentiable leaf in the subgraph (e.g. the model's
    # parameters when differentiating w.r.t. the input for a gradient
    # penalty) must be an argument of the dispatched op, not a baked
    # constant — otherwise the second backward cannot reach it
    leaves = _leaves_of(rnodes, {id(t) for t in conn})

    F = replay_pure(outputs, conn + leaves)
    seeds = []
    for out, gout in zip(outputs, grad_outputs):
        if gout is None:
            seeds.append(Tensor(jnp.ones(out.shape, out._value.dtype),
                                stop_gradient=True))
        elif isinstance(gout, Tensor):
            seeds.append(gout)
        else:
            seeds.append(Tensor(jnp.asarray(gout), stop_gradient=True))
    n_in, n_leaf = len(conn), len(leaves)

    def G(*vals):
        in_vals = vals[:n_in]
        leaf_vals = vals[n_in:n_in + n_leaf]
        seed_vals = vals[n_in + n_leaf:]
        _, vjp_fn = jax.vjp(lambda *iv: F(*iv, *leaf_vals), *in_vals)
        return tuple(vjp_fn(tuple(seed_vals)))

    grads = call_op_multi("double_grad_replay", G,
                          list(conn) + leaves + seeds, num_outputs=n_in)
    results, it = [], iter(grads)
    for c in connected:
        results.append(next(it) if c else None)
    return results


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False):
    """`paddle.grad` equivalent: grads of outputs w.r.t. inputs without touching
    .grad. Reference analog: eager/general_grad.h (GeneralGrad).

    Implementation: temporarily swap AccumulationNode capture — we hook input
    tensors' nodes by running a normal backward into fresh buffers. With
    create_graph=True the recorded graph is replayed as a pure jax function
    and differentiated with jax.vjp, so the returned grads are themselves
    differentiable (see replay_pure)."""
    from .core import Tensor
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]

    if create_graph:
        return _grad_create_graph(outputs, inputs, grad_outputs,
                                  allow_unused)

    # stash and clear existing grads on inputs; run backward; read; restore.
    # A grad filter keeps accumulation away from leaves outside `inputs`.
    stash = [(t, t.grad) for t in inputs]
    for t in inputs:
        t.grad = None
    _state.grad_filter = {id(t) for t in inputs}
    # shared nodes must survive across the per-output backward runs
    retain = bool(retain_graph) or len(outputs) > 1
    try:
        for out, gout in zip(outputs, grad_outputs):
            if out._grad_node is None:
                continue
            seed = (_one_cotangent(out._value.shape, out._value.dtype)
                    if gout is None else jnp.asarray(gout._value if isinstance(gout, Tensor) else gout))
            run_backward(out._grad_node, out._out_index, seed,
                         retain_graph=retain)
        results = []
        for t in inputs:
            if t.grad is None:
                if not allow_unused:
                    raise RuntimeError(
                        f"One of the differentiated tensors ({t.name}) appears "
                        "to not have been used in the graph; set allow_unused=True "
                        "to return None for it.")
                results.append(None)
            else:
                g = t.grad
                g.stop_gradient = True
                results.append(g)
        return results
    finally:
        _state.grad_filter = None
        for t, old in stash:
            t.grad = old
