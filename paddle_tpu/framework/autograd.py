"""Define-by-run autograd engine over jax VJPs.

Reference analog: paddle/fluid/eager/ — GradNodeBase/Edge (grad_node_info.h:168,50),
engine RunBackward (backward.cc:105, in-degree map + ready queue), accumulation
node (eager/accumulation/), hooks (hooks.h).

TPU-first design: instead of hand-written per-op grad kernels, each forward op
captures its VJP via `jax.vjp` at dispatch time (residuals live as jax arrays on
device). Backward is the same topo-ordered ready-queue walk as the reference,
but every node's backward is a single XLA-compiled callable.
"""
from __future__ import annotations

import threading
from collections import deque

import jax
import jax.numpy as jnp

__all__ = [
    "GradNode", "AccumulationNode", "run_backward", "grad",
    "no_grad", "enable_grad", "set_grad_enabled", "is_grad_enabled",
]

_state = threading.local()


def is_grad_enabled() -> bool:
    return getattr(_state, "grad_enabled", True)


def _set(flag: bool):
    _state.grad_enabled = flag


class set_grad_enabled:
    """Context manager / decorator toggling grad tracking."""

    def __init__(self, mode: bool):
        self._mode = mode
        self._prev = None

    def __enter__(self):
        self._prev = is_grad_enabled()
        _set(self._mode)
        return self

    def __exit__(self, *exc):
        _set(self._prev)
        return False


class _GradModeDecorator:
    mode = False

    def __init__(self, func=None):
        self._func = func

    def __call__(self, *args, **kwargs):
        if self._func is not None:
            with set_grad_enabled(self.mode):
                return self._func(*args, **kwargs)
        # `@no_grad()` usage: instance called with the function to wrap
        if len(args) == 1 and callable(args[0]) and not kwargs:
            return type(self)(args[0])
        raise TypeError("no_grad: expected a callable to wrap")

    def __enter__(self):
        self._ctx = set_grad_enabled(self.mode)
        return self._ctx.__enter__()

    def __exit__(self, *exc):
        return self._ctx.__exit__(*exc)


class no_grad(_GradModeDecorator):
    """`paddle.no_grad` — usable as context manager or decorator."""
    mode = False


class enable_grad(_GradModeDecorator):
    mode = True


class GradNode:
    """One node per forward op invocation.

    Holds the op's vjp callable, edges to producer nodes (one per tensor input),
    and output metadata so missing output grads can be zero-filled.
    """

    __slots__ = ("name", "vjp_fn", "edges", "out_avals", "pending",
                 "out_hooks", "retain_count")

    def __init__(self, name, vjp_fn, edges, out_avals):
        self.name = name
        self.vjp_fn = vjp_fn
        # edges[i] = (producer_node, producer_out_index) or None (stop_gradient)
        self.edges = edges
        # out_avals[j] = (shape, jnp dtype) of forward output j
        self.out_avals = out_avals
        self.pending = {}       # out_index -> accumulated incoming grad
        self.out_hooks = {}     # out_index -> [callable]
        self.retain_count = 0

    # -- engine interface ---------------------------------------------------
    def add_grad(self, out_index: int, g):
        cur = self.pending.get(out_index)
        self.pending[out_index] = g if cur is None else cur + g

    def collect_input_grads(self):
        """Run hooks, zero-fill missing output grads, call vjp; returns tuple of
        grads aligned with self.edges."""
        outs = []
        for j, (shape, dt) in enumerate(self.out_avals):
            g = self.pending.get(j)
            if g is None:
                g = jnp.zeros(shape, dt)
            else:
                for hook in self.out_hooks.get(j, ()):
                    newg = hook(g)
                    if newg is not None:
                        g = newg
            outs.append(g)
        self.pending = {}
        arg = tuple(outs) if len(outs) > 1 else outs[0]
        grads = self.vjp_fn(arg)
        if not isinstance(grads, tuple):
            grads = (grads,)
        return grads

    def release(self):
        self.vjp_fn = None
        self.pending = {}


class AccumulationNode(GradNode):
    """Terminal node for a leaf tensor: writes into tensor.grad.

    Reference analog: eager/accumulation/accumulation_node.h.
    """

    __slots__ = ("tensor_ref",)

    def __init__(self, tensor):
        import weakref
        super().__init__("accumulation", None, (), ((tensor.shape, tensor._value.dtype),))
        self.tensor_ref = weakref.ref(tensor)

    def accumulate(self):
        t = self.tensor_ref()
        g = self.pending.get(0)
        self.pending = {}
        if t is None or g is None:
            return
        # paddle.grad() restricts accumulation to its requested inputs so
        # other leaves' .grad is not polluted (GeneralGrad semantics)
        allowed = getattr(_state, "grad_filter", None)
        if allowed is not None and id(t) not in allowed:
            return
        for hook in self.out_hooks.get(0, ()):
            newg = hook(g)
            if newg is not None:
                g = newg
        for hook in t._hooks:
            # tensor-level hooks registered via Tensor.register_hook receive
            # and may replace the grad (paddle semantics)
            from .core import Tensor
            res = hook(Tensor(g, stop_gradient=True))
            if res is not None:
                g = res._value if hasattr(res, "_value") else jnp.asarray(res)
        if t.grad is None:
            from .core import Tensor
            t.grad = Tensor(g, stop_gradient=True)
            t.grad.name = t.name + "@GRAD" if t.name else "grad"
        else:
            t.grad._value = t.grad._value + g


def _count_dependencies(root: GradNode):
    """BFS the reachable subgraph; in_degree[node] = #edges into it from
    reachable nodes. Mirrors backward.cc:22 getInDegreeMap."""
    in_degree = {}
    seen = {root}
    q = deque([root])
    while q:
        node = q.popleft()
        for edge in node.edges:
            if edge is None:
                continue
            nxt = edge[0]
            in_degree[nxt] = in_degree.get(nxt, 0) + 1
            if nxt not in seen:
                seen.add(nxt)
                q.append(nxt)
    return in_degree, seen


def run_backward(root_node: GradNode, root_index: int, seed_grad,
                 retain_graph: bool = False):
    """Topo-ordered ready-queue walk from a single root output.

    Reference analog: egr::RunBackward (eager/backward.cc:105).
    """
    in_degree, reachable = _count_dependencies(root_node)
    root_node.add_grad(root_index, seed_grad)
    ready = deque([root_node])
    # nodes whose in-degree never reaches 0 cannot fire; with a DAG from a
    # single root this terminates with all reachable nodes fired.
    while ready:
        node = ready.popleft()
        if isinstance(node, AccumulationNode):
            node.accumulate()
            continue
        grads = node.collect_input_grads()
        if not retain_graph:
            node.release()
        for edge, g in zip(node.edges, grads):
            if edge is None or g is None:
                continue
            nxt, out_idx = edge
            nxt.add_grad(out_idx, g)
            in_degree[nxt] -= 1
            if in_degree[nxt] == 0:
                ready.append(nxt)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False):
    """`paddle.grad` equivalent: grads of outputs w.r.t. inputs without touching
    .grad. Reference analog: eager/general_grad.h (GeneralGrad).

    Implementation: temporarily swap AccumulationNode capture — we hook input
    tensors' nodes by running a normal backward into fresh buffers.
    """
    from .core import Tensor
    if create_graph:
        raise NotImplementedError(
            "create_graph=True (double grad) is not supported yet")
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]

    # stash and clear existing grads on inputs; run backward; read; restore.
    # A grad filter keeps accumulation away from leaves outside `inputs`.
    stash = [(t, t.grad) for t in inputs]
    for t in inputs:
        t.grad = None
    _state.grad_filter = {id(t) for t in inputs}
    # shared nodes must survive across the per-output backward runs
    retain = bool(retain_graph) or len(outputs) > 1
    try:
        for out, gout in zip(outputs, grad_outputs):
            if out._grad_node is None:
                continue
            seed = (jnp.ones(out.shape, out._value.dtype)
                    if gout is None else jnp.asarray(gout._value if isinstance(gout, Tensor) else gout))
            run_backward(out._grad_node, out._out_index, seed,
                         retain_graph=retain)
        results = []
        for t in inputs:
            if t.grad is None:
                if not allow_unused:
                    raise RuntimeError(
                        f"One of the differentiated tensors ({t.name}) appears "
                        "to not have been used in the graph; set allow_unused=True "
                        "to return None for it.")
                results.append(None)
            else:
                g = t.grad
                g.stop_gradient = True
                results.append(g)
        return results
    finally:
        _state.grad_filter = None
        for t, old in stash:
            t.grad = old
