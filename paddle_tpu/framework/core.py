"""Tensor: the user-facing array type, wrapping a `jax.Array`.

Reference analog: phi::DenseTensor (paddle/phi/core/dense_tensor.h:38) for
storage + meta, and the eager `paddle.Tensor` (pybind/eager.cc:1148 BindEager,
eager_method.cc for methods). TPU-first: storage is an immutable jax.Array;
"in-place" paddle semantics (`_`-suffixed methods, optimizer updates) are value
swaps on the wrapper, with buffer donation handled at the jit boundary.
"""
from __future__ import annotations

import itertools

import numpy as np
import jax
import jax.numpy as jnp

from . import dtype as dtype_mod
from .dtype import convert_dtype, to_jax_dtype, get_default_dtype, DType
from .autograd import AccumulationNode, is_grad_enabled, run_backward

__all__ = ["Tensor", "Parameter", "to_tensor", "is_tensor"]

_name_counter = itertools.count()


def _auto_name(prefix="tensor"):
    return f"{prefix}_{next(_name_counter)}"


class Place:
    """Thin device handle. Reference analog: phi::Place (phi/common/place.h)."""

    def __init__(self, device):
        self._device = device  # a jax.Device or None (for traced values)

    def __repr__(self):
        if self._device is None:
            return "Place(traced)"
        return f"Place({self._device.platform}:{self._device.id})"

    def is_gpu_place(self):
        return self._device is not None and self._device.platform == "gpu"

    def is_cpu_place(self):
        return self._device is not None and self._device.platform == "cpu"

    def is_tpu_place(self):
        return self._device is not None and self._device.platform in ("tpu", "axon")

    # paddle calls TPU-like pluggable backends "custom places"
    is_custom_place = is_tpu_place


class Tensor:
    """Eager tensor with paddle semantics over a jax.Array value."""

    __slots__ = ("_value", "stop_gradient", "grad", "_grad_node", "_out_index",
                 "name", "persistable", "_hooks", "_dist_attr", "__weakref__")

    def __init__(self, value, dtype=None, stop_gradient=True, name=None,
                 persistable=False):
        if isinstance(value, Tensor):
            value = value._value
        if dtype is not None:
            jd = to_jax_dtype(dtype)
            value = jnp.asarray(value, dtype=jd)
        elif not isinstance(value, (jax.Array, jax.core.Tracer)):
            value = jnp.asarray(value)
        self._value = value
        self.stop_gradient = stop_gradient
        self.grad = None
        self._grad_node = None
        self._out_index = 0
        self.name = name if name is not None else _auto_name()
        self.persistable = persistable
        self._hooks = []

    # -- meta ---------------------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def dtype(self) -> DType:
        return dtype_mod.to_paddle_dtype(self._value.dtype)

    @property
    def ndim(self):
        return self._value.ndim

    ndimension = dim = lambda self: self._value.ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self):
        devs = getattr(self._value, "devices", None)
        if devs is None:
            return Place(None)
        try:
            return Place(next(iter(self._value.devices())))
        except Exception:
            return Place(None)

    @property
    def is_leaf(self):
        return self._grad_node is None or isinstance(self._grad_node, AccumulationNode)

    @property
    def T(self):
        from .. import ops
        return ops.manipulation.transpose(self, list(range(self.ndim))[::-1])

    def numel(self):
        return self.size

    # -- conversion ---------------------------------------------------------
    def numpy(self):
        return np.asarray(self._value)

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        arr = self.numpy()
        return arr.astype(dtype) if dtype is not None else arr

    def __dlpack__(self, *a, **k):
        return self._value.__dlpack__(*a, **k)

    # -- autograd -----------------------------------------------------------
    def _ensure_grad_node(self):
        """Leaf tensors that require grad lazily get an accumulation node."""
        if self._grad_node is None:
            self._grad_node = AccumulationNode(self)
            self._out_index = 0
        return self._grad_node

    def backward(self, grad_tensor=None, retain_graph=False):
        # whole-step fusion (ops/step_fusion.py) may consume this backward
        # as part of a fused train-step replay — before anything touches
        # _grad_node, which would force a pending placeholder
        from ..ops.step_fusion import STEP as _step_fusion
        if _step_fusion.on_backward(self, grad_tensor, retain_graph):
            return
        if self.stop_gradient and self._grad_node is None:
            raise RuntimeError(
                "Tensor.backward() called on a tensor with stop_gradient=True "
                "and no grad graph")
        if grad_tensor is None:
            from .autograd import _one_cotangent
            seed = _one_cotangent(self._value.shape, self._value.dtype)
        else:
            seed = grad_tensor._value if isinstance(grad_tensor, Tensor) \
                else jnp.asarray(grad_tensor)
        node = self._grad_node
        if node is None:
            # leaf: grad of self wrt self
            self._ensure_grad_node()
            node = self._grad_node
        run_backward(node, self._out_index, seed, retain_graph=retain_graph)
        # guardian (FLAGS_check_numerics): the backward boundary resolves
        # the queued in-graph finite checks — one batched device->host
        # transfer; a no-op (empty queue) when the flag is off
        from ..ops.guardian import maybe_flush
        maybe_flush()

    def register_hook(self, hook):
        """Register a grad hook (fires at accumulation for leaves, at the
        producing node's output otherwise). Returns a removable handle."""
        if self.is_leaf:
            self._hooks.append(hook)
            hooks_list, item = self._hooks, hook
        else:
            node, idx = self._grad_node, self._out_index
            raw = lambda g: (lambda r: None if r is None else
                             (r._value if isinstance(r, Tensor) else r))(
                                 hook(Tensor(g, stop_gradient=True)))
            node.out_hooks.setdefault(idx, []).append(raw)
            hooks_list, item = node.out_hooks[idx], raw

        class _Handle:
            def remove(self_h):
                try:
                    hooks_list.remove(item)
                except ValueError:
                    pass
        return _Handle()

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def detach(self):
        t = Tensor(self._value, stop_gradient=True, name=self.name + ".detach")
        return t

    def clone(self):
        from ..ops.dispatch import call_op
        return call_op("clone", lambda x: x + 0, (self,))

    # -- dtype / value manipulation ------------------------------------------
    def astype(self, dtype):
        from ..ops.dispatch import call_op
        jd = to_jax_dtype(dtype)
        return call_op("cast", lambda x: x.astype(jd), (self,))

    cast = astype

    def _assign_value_(self, value):
        """Internal raw value swap (the in-place primitive)."""
        if isinstance(value, Tensor):
            value = value._value
        self._value = jnp.asarray(value, dtype=self._value.dtype)
        return self

    def set_value(self, value):
        return self._assign_value_(value)

    def copy_(self, other, blocking=True):
        return self._assign_value_(other)

    def fill_(self, value):
        self._value = jnp.full(self._value.shape, value, self._value.dtype)
        return self

    def zero_(self):
        return self.fill_(0)

    def scale_(self, scale=1.0, bias=0.0):
        self._value = self._value * scale + bias
        return self

    # -- misc ---------------------------------------------------------------
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-D tensor")
        return self._value.shape[0]

    def __repr__(self):
        grad_info = f", stop_gradient={self.stop_gradient}"
        try:
            val = np.asarray(self._value)
            body = np.array2string(val, precision=4, separator=", ")
        except Exception:
            body = f"<traced {self._value}>"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}"
                f"{grad_info},\n       {body})")

    def __bool__(self):
        if self.size != 1:
            raise ValueError("The truth value of a multi-element Tensor is ambiguous")
        return bool(self.numpy())

    def __int__(self):
        return int(self.numpy())

    def __float__(self):
        return float(self.numpy())

    def __index__(self):
        return int(self.numpy())

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __hash__(self):
        return id(self)

    # arithmetic dunders are attached by paddle_tpu.ops at import time
    # (mirrors eager_math_op_patch.cc)

    def __deepcopy__(self, memo):
        # jax arrays are immutable: share the buffer, copy the wrapper
        new = self.__class__.__new__(self.__class__)
        Tensor.__init__(new, self._value, stop_gradient=self.stop_gradient,
                        name=self.name, persistable=self.persistable)
        if isinstance(new, Parameter):
            new.trainable = not self.stop_gradient
            new.optimize_attr = dict(getattr(self, "optimize_attr",
                                             {"learning_rate": 1.0}))
            new.regularizer = getattr(self, "regularizer", None)
            new.do_model_average = getattr(self, "do_model_average", None)
            new.need_clip = getattr(self, "need_clip", True)
            new.is_distributed = getattr(self, "is_distributed", False)
        memo[id(self)] = new
        return new

    def pin_memory(self):
        return self

    def cuda(self, *a, **k):
        return self

    def cpu(self):
        return Tensor(jax.device_get(self._value), stop_gradient=self.stop_gradient)

    def to(self, *args, **kwargs):
        # supports .to(dtype) / .to(device) minimal forms
        for a in list(args) + list(kwargs.values()):
            try:
                return self.astype(a)
            except TypeError:
                continue
        return self

    def value(self):
        return self

    def get_tensor(self):
        return self


class Parameter(Tensor):
    """Trainable tensor. Reference analog: python Parameter over eager Tensor
    (python/paddle/fluid/framework.py EagerParamBase)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "do_model_average",
                 "need_clip", "is_distributed")

    def __init__(self, value, dtype=None, name=None, trainable=True):
        super().__init__(value, dtype=dtype, stop_gradient=not trainable,
                         name=name or _auto_name("param"), persistable=True)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.do_model_average = None
        self.need_clip = True
        self.is_distributed = False

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def is_tensor(x):
    return isinstance(x, Tensor)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """`paddle.to_tensor` equivalent."""
    if isinstance(data, Tensor):
        if dtype is not None and convert_dtype(dtype) != data.dtype.name:
            out = data.astype(dtype)
        else:
            out = data.clone() if not stop_gradient else Tensor(data._value)
        out.stop_gradient = stop_gradient
        return out
    if dtype is None:
        if isinstance(data, (bool, np.bool_)):
            pass  # keep bool
        elif isinstance(data, (int, np.integer)):
            dtype = "int64"
        elif isinstance(data, (float, np.floating)):
            dtype = get_default_dtype()
        elif isinstance(data, (list, tuple, np.ndarray)):
            arr = np.asarray(data)
            if arr.dtype == np.float64:
                dtype = get_default_dtype()
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)
