"""Dtype system: paddle-style dtype names over jax/numpy dtypes.

Reference analog: paddle/phi/common/data_type.h (DataType enum) and the
python-visible `paddle.float32`-style handles (python/paddle/framework/dtype.py).
TPU-first: bfloat16 is a first-class dtype; default float dtype is configurable
(paddle.set_default_dtype).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
import ml_dtypes

# paddle exposes float64/int64 as first-class dtypes (phi/common/data_type.h);
# jax needs x64 enabled for them. Kernels pick their compute dtype explicitly
# (bf16/f32 on TPU), so this only widens what users may request. NOTE: this is
# a process-wide jax config change — bare jnp.ones(...) elsewhere becomes
# float64 (which TPUs reject). Set PADDLE_TPU_X64=0 to opt out and forfeit
# float64 tensor support.
import os as _os

if _os.environ.get("PADDLE_TPU_X64", "1") != "0":
    jax.config.update("jax_enable_x64", True)

__all__ = [
    "DType", "convert_dtype", "to_jax_dtype", "to_paddle_dtype",
    "set_default_dtype", "get_default_dtype",
    "uint8", "int8", "int16", "int32", "int64",
    "float16", "bfloat16", "float32", "float64",
    "complex64", "complex128", "bool_",
    "is_floating_point_dtype", "is_integer_dtype", "is_complex_dtype",
]


class DType:
    """A paddle-style dtype handle wrapping a canonical numpy dtype."""

    __slots__ = ("name", "np_dtype")
    _registry: dict[str, "DType"] = {}

    def __init__(self, name: str, np_dtype):
        self.name = name
        self.np_dtype = np.dtype(np_dtype)
        DType._registry[name] = self

    def __repr__(self):
        return f"paddle_tpu.{self.name}"

    def __eq__(self, other):
        if isinstance(other, DType):
            return self.name == other.name
        try:
            return self.np_dtype == np.dtype(convert_dtype(other))
        except TypeError:
            return NotImplemented

    def __hash__(self):
        return hash(self.name)

    @property
    def itemsize(self):
        return self.np_dtype.itemsize


uint8 = DType("uint8", np.uint8)
int8 = DType("int8", np.int8)
int16 = DType("int16", np.int16)
int32 = DType("int32", np.int32)
int64 = DType("int64", np.int64)
float16 = DType("float16", np.float16)
bfloat16 = DType("bfloat16", ml_dtypes.bfloat16)
float32 = DType("float32", np.float32)
float64 = DType("float64", np.float64)
complex64 = DType("complex64", np.complex64)
complex128 = DType("complex128", np.complex128)
bool_ = DType("bool", np.bool_)

_NP_TO_NAME = {
    np.dtype(np.uint8): "uint8",
    np.dtype(np.int8): "int8",
    np.dtype(np.int16): "int16",
    np.dtype(np.int32): "int32",
    np.dtype(np.int64): "int64",
    np.dtype(np.float16): "float16",
    np.dtype(ml_dtypes.bfloat16): "bfloat16",
    np.dtype(np.float32): "float32",
    np.dtype(np.float64): "float64",
    np.dtype(np.complex64): "complex64",
    np.dtype(np.complex128): "complex128",
    np.dtype(np.bool_): "bool",
}

_FLOAT_NAMES = {"float16", "bfloat16", "float32", "float64"}
_INT_NAMES = {"uint8", "int8", "int16", "int32", "int64"}
_COMPLEX_NAMES = {"complex64", "complex128"}

_default_dtype = float32


def set_default_dtype(d) -> None:
    """Set default float dtype (accepts 'float32'/'bfloat16'/'float64'/'float16')."""
    global _default_dtype
    d = to_paddle_dtype(d)
    if d.name not in _FLOAT_NAMES:
        raise TypeError(
            f"set_default_dtype only supports float dtypes, got {d.name}")
    _default_dtype = d


def get_default_dtype() -> str:
    return _default_dtype.name


def convert_dtype(dtype) -> str:
    """Normalize any dtype spec (DType / str / np.dtype / jnp dtype) to its name."""
    if isinstance(dtype, DType):
        return dtype.name
    if isinstance(dtype, str):
        if dtype in DType._registry:
            return dtype
        # numpy-style aliases
        alias = {"float": "float32", "double": "float64", "half": "float16",
                 "int": "int32", "long": "int64", "bool_": "bool"}.get(dtype)
        if alias:
            return alias
        raise TypeError(f"Unsupported dtype string: {dtype!r}")
    npd = np.dtype(dtype)
    name = _NP_TO_NAME.get(npd)
    if name is None:
        raise TypeError(f"Unsupported dtype: {dtype!r}")
    return name


def to_paddle_dtype(dtype) -> DType:
    return DType._registry[convert_dtype(dtype)]


def to_jax_dtype(dtype):
    return to_paddle_dtype(dtype).np_dtype


def is_floating_point_dtype(dtype) -> bool:
    return convert_dtype(dtype) in _FLOAT_NAMES


def is_integer_dtype(dtype) -> bool:
    return convert_dtype(dtype) in _INT_NAMES


def is_complex_dtype(dtype) -> bool:
    return convert_dtype(dtype) in _COMPLEX_NAMES


class iinfo:
    """Integer type info (paddle.iinfo). Reference analog:
    python/paddle/framework exposing np.iinfo-backed machine limits."""

    def __init__(self, dtype):
        npd = to_jax_dtype(dtype)
        info = np.iinfo(npd)
        self.min = int(info.min)
        self.max = int(info.max)
        self.bits = int(info.bits)
        self.dtype = convert_dtype(dtype)

    def __repr__(self):
        return (f"paddle.iinfo(min={self.min}, max={self.max}, "
                f"bits={self.bits}, dtype={self.dtype})")


class finfo:
    """Float type info (paddle.finfo) — works for bfloat16 too (np.finfo
    supports ml_dtypes.bfloat16 via jax's numpy extension types)."""

    def __init__(self, dtype):
        npd = to_jax_dtype(dtype)
        try:
            info = np.finfo(npd)
        except ValueError:
            # np.finfo rejects the ml_dtypes extension types (bfloat16,
            # float8_*) — ml_dtypes ships its own finfo for them
            import ml_dtypes
            info = ml_dtypes.finfo(npd)
        self.min = float(info.min)
        self.max = float(info.max)
        self.eps = float(info.eps)
        self.tiny = float(info.tiny)
        self.smallest_normal = float(info.tiny)
        self.resolution = float(info.resolution)
        self.bits = int(info.bits)
        self.dtype = convert_dtype(dtype)

    def __repr__(self):
        return (f"paddle.finfo(min={self.min}, max={self.max}, "
                f"eps={self.eps}, bits={self.bits}, dtype={self.dtype})")
