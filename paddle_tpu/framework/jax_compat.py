"""Version-compat shims over the installed jax.

The repo targets the modern jax surface (`jax.shard_map` with an
`axis_names` kwarg, `jax.lax.axis_size`, `jax.lax.pcast`), but must also run
on jax 0.4.x where `shard_map` only exists under `jax.experimental` with a
different signature and no partial-manual support. Everything that needs one
of these symbols goes through this module; `install()` additionally patches
the missing attributes onto the `jax` module itself so test/user code
written against the modern spelling keeps working.

Fallback semantics on old jax (jax.experimental.shard_map):

  - `axis_names={...}` (partial-manual) is emulated by mapping ALL mesh axes
    manually: in/out specs that never mention the extra axes leave data
    replicated across them, so each device computes the same values it would
    have under partial-auto — numerically identical, possibly redundant
    compute across the unnamed axes (they are size-1 or small in every
    in-repo mesh).
  - replication checking (`check_vma`/`check_rep`) is disabled: 0.4.x's
    rep-checker predates `pcast` and rejects legal programs the modern
    checker accepts (e.g. psum-produced values returned through a
    `P(axis, ...)` out_spec).
  - `jax.lax.axis_size(name)` is `lax.psum(1, name)`, which constant-folds
    to a python int inside a manual-mapping trace.
  - `jax.lax.pcast(x, axis, to=...)` is the identity: with rep-checking
    disabled there is no varying/replicated type to cast between.
"""
from __future__ import annotations

import jax
from jax import lax

__all__ = ["shard_map", "axis_size", "pcast", "export_key_form", "install"]

_NATIVE_SHARD_MAP = getattr(jax, "shard_map", None)
if _NATIVE_SHARD_MAP is None:
    from jax.experimental.shard_map import shard_map as _EXPERIMENTAL_SHARD_MAP
else:
    _EXPERIMENTAL_SHARD_MAP = None

# natives resolved ONCE, before install() can alias the shims onto jax —
# a late getattr would find our own patch and recurse
_NATIVE_AXIS_SIZE = getattr(lax, "axis_size", None)
_NATIVE_PCAST = getattr(lax, "pcast", None)

_NATIVE_SM_PARAMS = None


def _native_sm_params():
    """Keyword names the installed jax.shard_map actually accepts: the
    replication-check kwarg was renamed check_rep → check_vma across jax
    generations and `axis_names` (partial-manual) appeared late; passing
    an unknown kwarg raises TypeError at every call site. Resolved once."""
    global _NATIVE_SM_PARAMS
    if _NATIVE_SM_PARAMS is None:
        import inspect
        try:
            _NATIVE_SM_PARAMS = frozenset(
                inspect.signature(_NATIVE_SHARD_MAP).parameters)
        except (TypeError, ValueError):
            _NATIVE_SM_PARAMS = frozenset()
    return _NATIVE_SM_PARAMS


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
              check_vma=None, check_rep=None):
    """`jax.shard_map` resolved against the installed jax.

    `axis_names` restricts manual mapping to a subset of mesh axes (modern
    jax); on 0.4.x it is emulated as documented in the module docstring.
    `check_vma`/`check_rep` are accepted from either API generation and
    forwarded under whichever name the installed jax knows.
    """
    if axis_names:
        unknown = set(axis_names) - set(mesh.axis_names)
        if unknown:
            raise ValueError(
                f"shard_map axis_names {sorted(unknown)} not in mesh axes "
                f"{tuple(mesh.axis_names)}")
    if _NATIVE_SHARD_MAP is not None:
        params = _native_sm_params()
        kwargs = {}
        if axis_names and "axis_names" in params:
            kwargs["axis_names"] = set(axis_names)
        check = check_vma if check_vma is not None else check_rep
        if check is not None:
            if "check_vma" in params:
                kwargs["check_vma"] = check
            elif "check_rep" in params:
                kwargs["check_rep"] = check
        return _NATIVE_SHARD_MAP(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, **kwargs)
    return _EXPERIMENTAL_SHARD_MAP(f, mesh=mesh, in_specs=in_specs,
                                   out_specs=out_specs, check_rep=False)


def axis_size(axis_name):
    """`jax.lax.axis_size` — size of a mapped mesh axis, as a python int
    inside shard_map/pmap traces."""
    if _NATIVE_AXIS_SIZE is not None:
        return _NATIVE_AXIS_SIZE(axis_name)
    return lax.psum(1, axis_name)


def pcast(x, axis_name, *, to):
    """`jax.lax.pcast` — varying/replicated cast. Identity on jax versions
    without VMA tracking (the fallback shard_map runs with rep-checking
    off, so there is nothing to cast)."""
    if _NATIVE_PCAST is not None:
        return _NATIVE_PCAST(x, axis_name, to=to)
    return x


_EXPORT_KEY_FORM = None


def export_key_form():
    """How a PRNG key must be threaded through `jax.export` so the artifact
    SERIALIZES on this jax: "typed" when the export serializer knows the
    typed key dtypes (`key<fry>`), "legacy" (raw uint32[2] `PRNGKey`)
    otherwise — 0.4.x's serializer has no dtype kind for typed keys, so a
    typed-key export traces fine but `Exported.serialize()` raises
    KeyError(key<fry>). Every `jax.random` op accepts both forms."""
    global _EXPORT_KEY_FORM
    if _EXPORT_KEY_FORM is None:
        try:
            from jax._src.export import serialization as _ser
            _EXPORT_KEY_FORM = "typed" if jax.random.key(0).dtype \
                in _ser._dtype_to_dtype_kind else "legacy"
        except Exception:
            _EXPORT_KEY_FORM = "legacy"
    return _EXPORT_KEY_FORM


def install():
    """Patch the modern spellings onto the jax module when missing, so code
    outside this repo (tests, notebooks) written against current jax runs
    unchanged. Idempotent; never overwrites a real implementation."""
    if getattr(jax, "shard_map", None) is None:
        jax.shard_map = shard_map
    if getattr(lax, "axis_size", None) is None:
        lax.axis_size = axis_size
    if getattr(lax, "pcast", None) is None:
        lax.pcast = pcast


install()
