"""Global flags. Reference analog: paddle/fluid/platform/flags.cc (76 exported
FLAGS via PADDLE_DEFINE_EXPORTED_*) + paddle.set_flags/get_flags
(global_value_getter_setter.cc). Env vars `FLAGS_*` seed initial values.
"""
from __future__ import annotations

import os
import threading

__all__ = ["define_flag", "set_flags", "get_flags", "FLAGS"]

_lock = threading.Lock()
_FLAGS: dict[str, object] = {}
_DEFS: dict[str, tuple] = {}
# bumped on every mutation: caches derived from flag values (the AOT
# store's environment fingerprint, ops/aot_cache.py) key on it so a
# mid-run set_flags can never leave them stale
_GENERATION = 0


def define_flag(name, default, help_str=""):
    env = os.environ.get(name)
    value = default
    if env is not None:
        if isinstance(default, bool):
            value = env.lower() in ("1", "true", "yes")
        elif isinstance(default, int):
            value = int(env)
        elif isinstance(default, float):
            value = float(env)
        else:
            value = env
    _DEFS[name] = (default, help_str)
    _FLAGS[name] = value
    return value


# Core flags mirroring the reference set (platform/flags.cc)
define_flag("FLAGS_check_nan_inf", False,
            "scan op outputs for NaN/Inf (nan_inf_utils.h analog). STRICT "
            "debug mode: forces per-op dispatch with a device sync per "
            "inexact output, flushing any chain/step fusion — use it to "
            "LOCALIZE a known blowup. For always-on production checking "
            "see FLAGS_check_numerics, which keeps the fusion stack "
            "engaged")
define_flag("FLAGS_check_nan_inf_level", 0, "0: fail on nan/inf")

# Non-finite step guardian (ops/guardian.py). Unlike FLAGS_check_nan_inf —
# which drops dispatch to the per-op debug path and flushes every chain —
# this mode compiles a cheap all-finite reduction INTO the cached
# executables of all three fusion tiers: a per-op launch, a fused chain
# launch, and a fused whole-step launch each emit ONE extra scalar. The
# scalars are checked lazily (a small per-thread queue flushed at backward
# / optimizer-step boundaries), so there is no per-op host sync and the
# chain/step fusion wins survive. A promoted whole-step executable
# additionally computes a global grads-finite predicate and applies the
# update as where(finite, new_state, old_state): a poisoned batch becomes
# a bitwise no-op step (`nonfinite_skip` in the fusion flight recorder)
# instead of corrupted parameters. The eager (unfused) optimizer path
# applies the same skip-step semantics for parity.
define_flag("FLAGS_check_numerics", False,
            "fused in-graph numerics guardian: compile an all-finite "
            "reduction into per-op/chain/step executables (one scalar per "
            "launch, no per-op sync, fusion stays engaged), raise/warn on "
            "non-finite forward outputs at the next backward/step "
            "boundary, and turn a non-finite-gradient step into a bitwise "
            "no-op update (skip-step rescue). FLAGS_check_nan_inf remains "
            "the strict per-op fallback and takes precedence when set")
define_flag("FLAGS_check_numerics_level", 0,
            "0: raise FloatingPointError on a non-finite forward output; "
            ">=1: warn and continue. Gradient non-finiteness never raises "
            "— it skips the step (and backs off the GradScaler loss scale "
            "when one is attached)")
define_flag("FLAGS_benchmark", False, "sync after each op for timing")

# Serving resilience (paddle_tpu/serving/resilience.py). The watchdog
# bounds every decode/prefill fire: the step's result futures are waited
# on through a monitored completion (spin-then-sleep readiness poll, no
# extra threads or host syncs beyond the step's own result read). A step
# that blows the budget emits `serve.hang`, marks the engine degraded and
# runs the recovery ladder: retry the step, rebuild the decode
# executable, then fail the active requests with attributed reasons —
# never wedging the process the way the raw TPU-tunnel hangs of bench
# rounds 3-4 did.
define_flag("FLAGS_serve_step_timeout_ms", 0,
            "hung-step watchdog budget for one serving decode/prefill "
            "step, in milliseconds. 0 (default) disarms the watchdog: "
            "the engine blocks on the step result exactly as before. "
            "Size it at ~100x the expected p99 step latency so a real "
            "hang is caught in well under a second of TPU time while a "
            "GC pause or host hiccup never trips it")
define_flag("FLAGS_use_flash_attention", True,
            "route eligible attention through the Pallas flash kernel")
define_flag("FLAGS_serve_attention_kernel", "blockwise",
            "paged decode attention variant for the serving engine: "
            "'pallas' (TPU Pallas kernel, one KV block in VMEM at a time, "
            "dequant fused into the block loads; falls back to blockwise "
            "off-TPU / on ineligible shapes with an attributed "
            "kernel.fallback event), 'blockwise' (pure-JAX lax.scan over "
            "blocks with online softmax — the CPU/parity fallback, still "
            "never materializes the [S, T, H, D] context), or 'reference' "
            "(the dense gather-by-block-table oracle). The value is keyed "
            "into the per-op dispatch cache key (the op fn closes over the "
            "resolved variant) and the AOT store's environment fingerprint, "
            "so flips re-key cleanly instead of replaying stale programs")
define_flag("FLAGS_use_fused_cross_entropy", False,
            "route large-vocab CE through the vocab-blocked Pallas kernel. "
            "Off by default: measured on v5e GPT-2 (V=50304), XLA's CE fused "
            "with the lm-head matmul wins end-to-end (86.7k vs 82.5k tok/s) "
            "because the kernel's vocab padding copies the logits; enable "
            "for memory-bound cases (very large vocab or long sequence)")
define_flag("FLAGS_use_fused_layer_norm", True,
            "route eligible bias+residual+LN through the Pallas row kernel")
define_flag("FLAGS_allocator_strategy", "xla",
            "memory is managed by XLA/PJRT (informational)")
define_flag("FLAGS_cudnn_deterministic", False, "determinism hint")
define_flag("FLAGS_embedding_deterministic", 0, "determinism hint")
define_flag("FLAGS_max_inplace_grad_add", 0, "compat no-op")
define_flag("FLAGS_eager_delete_tensor_gb", 0.0, "compat no-op (XLA GC)")

# Compiled eager dispatch (ops/dispatch.py). The cache key is
# (op name, fn token, input (shape, dtype, weak_type) avals, diff mask,
# AMP-state token, registry override token); values are jitted forward /
# forward+vjp executables, so a repeated eager op sequence stops re-tracing
# after its first iteration. Telemetry — hits, misses, bypasses, retraces,
# evictions, cumulative dispatch wall time — is read with
# paddle_tpu.profiler.dispatch_cache_stats() and lands in bench.py's
# headline record as the `dispatch_cache` block in `extra`.
define_flag("FLAGS_eager_op_cache", True,
            "per-op executable cache in eager dispatch: repeated ops reuse "
            "compiled forward and VJP executables instead of re-tracing. "
            "Un-keyable calls (fns closing over arrays/Tensors, tracer "
            "inputs, jit-incompatible ops) bypass the cache, so numerics "
            "never change — only whether jax re-traces")
define_flag("FLAGS_eager_op_cache_size", 512,
            "LRU capacity (entries) of the eager op executable cache; the "
            "least-recently-used entry is evicted past this size. 0 disables "
            "caching entirely (keyable calls take the uncached path and are "
            "counted as bypasses in telemetry). Bounds forward entries only "
            "— backward applier traces (keyed by vjp residual treedef) live "
            "for the process unless ops.dispatch.clear_dispatch_cache() is "
            "called")
define_flag("FLAGS_eager_op_cache_donate", False,
            "EXPERIMENTAL: donate VJP residual buffers to the cached "
            "backward executable on the final (non-retained) backward. Off "
            "by default because residuals commonly alias buffers that are "
            "still live — op inputs/outputs the caller holds (weights!), "
            "or the same buffer saved as a residual by a sibling node that "
            "has not fired yet in the same backward pass — and donation "
            "invalidates them. Only safe when the graph is a chain whose "
            "intermediates are not referenced after backward; donation is "
            "a warn-and-skip no-op on CPU")

# Eager chain fusion (ops/fusion.py), the layer above the per-op cache:
# repeated op *sequences* (matmul→add→gelu, ...) are detected from the
# dispatch stream and compiled into ONE fused executable per chain — one
# XLA launch instead of N, one fused GradNode instead of N tape nodes.
# Replay is speculative: ops matching a hot chain are deferred and the
# fused executable fires when the chain completes; any mid-chain mismatch
# or an intermediate escaping the chain (a `.numpy()`, an unrelated op, a
# mutated stop_gradient) splits the chain back onto the per-op cached
# path with identical numerics. Telemetry:
# paddle_tpu.profiler.chain_fusion_stats(); bench.py embeds it as the
# `chain_fusion` block.
define_flag("FLAGS_eager_chain_fusion", True,
            "fuse repeated eager op sequences into single compiled chain "
            "executables on top of the per-op cache. Chains are keyed by "
            "the constituent per-op cache keys plus the dataflow wiring "
            "between them, so every invalidation rule of the per-op cache "
            "(registry generation bump, AMP state, clear_dispatch_cache) "
            "applies to chains too. Falls back to per-op dispatch with "
            "bitwise-identical results whenever a chain breaks")
define_flag("FLAGS_eager_chain_fusion_min_count", 25,
            "hotness threshold: a candidate op sequence must repeat this "
            "many times before a fused chain executable is compiled for "
            "it. Compiling a chain costs O(seconds); a replay saves "
            "O(100us) — the default only fuses loops long enough to "
            "amortize the compile (any real training loop crosses it in "
            "the first second). Lower it in micro-benchmarks that want "
            "fusion to settle during a short warmup")
define_flag("FLAGS_eager_chain_cache_size", 128,
            "LRU capacity (chains) of the fused-chain executable cache; "
            "least-recently-replayed chains are evicted past this size. "
            "0 disables chain fusion (same semantics as the flag off)")
define_flag("FLAGS_eager_chain_stitching", True,
            "stitch adjacent hot chains whose boundary wiring matches into "
            "one longer chain: when chain B replays on the very next "
            "dispatch after chain A fired and B's external inputs wire to "
            "A's outputs, A+B is registered as a single chain — so "
            "sequences longer than the rolling detection window (whole "
            "transformer blocks) fuse into one launch without growing "
            "detection cost. Stitched chains obey every chain-fusion "
            "invalidation and fallback rule")

# Whole-step eager fusion (ops/step_fusion.py), the layer above chain
# fusion: a stable per-step cycle — forward ops, `loss.backward()`,
# optimizer `step()`/`clear_grad()` — repeated identically for
# FLAGS_eager_step_fusion_min_count iterations is promoted to ONE fused
# executable (forward + backward + grad clip/regularization + optimizer
# update) with donated optimizer-slot buffers: the auto-TrainStep. Replay
# is speculative and transactional exactly like chain fusion — any
# cycle-shape mismatch, a mid-step value peek, a changed optimizer/param
# set, or an execution fault splits back to chain/per-op dispatch with
# bitwise-identical numerics. The LR-schedule value and the optimizer step
# count are hoisted to scalar arguments, so schedulers never split.
# Telemetry: paddle_tpu.profiler.step_fusion_stats(); bench.py embeds it
# as the `step_fusion` block.
define_flag("FLAGS_eager_step_fusion", True,
            "promote a stable eager fwd+bwd+optimizer cycle to one fused "
            "whole-step executable (auto-TrainStep). Falls back to "
            "chain/per-op dispatch with identical numerics whenever the "
            "cycle diverges; requires the per-op cache "
            "(FLAGS_eager_op_cache with a nonzero cache size) to key the "
            "cycle's ops")
define_flag("FLAGS_eager_step_fusion_min_count", 40,
            "cycle-stability threshold: the per-step op/backward/optimizer "
            "cycle must repeat identically this many consecutive times "
            "before the whole-step executable is compiled. Whole-step "
            "compiles cost O(seconds) and the observation pass is cheap, "
            "so the default only promotes genuinely steady training loops; "
            "lower it in micro-benchmarks with a short warmup")
define_flag("FLAGS_eager_step_fusion_cache_size", 8,
            "LRU capacity (promoted step programs) kept per thread so a "
            "loop that temporarily diverges and re-stabilizes reuses its "
            "compiled whole-step executable instead of recompiling. 0 "
            "disables step fusion")
define_flag("FLAGS_eager_step_fusion_spmd", True,
            "distributed lowering of promoted steps (ops/spmd_fusion.py): "
            "when a cycle's batch lives sharded on a device mesh, compile "
            "the whole step through shard_map with the collectives fused "
            "in — gradient pmean over the batch axes, ZeRO-sharded "
            "optimizer update (slice/update/all-gather) when the slots "
            "carry a 'sharding' NamedSharding, and all-reduced guardian/"
            "GradScaler found-inf predicates. The first fire runs under "
            "probation (eager results commit, fused compared); a "
            "divergence demotes the program to the plain jit lowering. "
            "Off: sharded cycles promote through plain jit (GSPMD "
            "placement)")
# Fusion flight recorder (profiler/events.py): a bounded, thread-aware
# ring-buffer event log for the dispatch/fusion pipeline. Every decision
# point that bumps a telemetry counter — cache hit/miss/bypass, chain
# detect/compile/fire/split/stitch, step record/promote/fire/split/
# deactivate — also emits a typed event carrying the op name, a cache-key
# digest, and a machine-readable reason code, so a loop that silently
# never promotes (or splits mid-step) can be root-caused with
# paddle_tpu.profiler.explain / tools/fusion_doctor.py instead of staring
# at aggregate counters. Near-zero cost when off (one flag check per
# decision point); the profiler drains the ring into chrome-trace lanes.
define_flag("FLAGS_profiler_events", False,
            "record dispatch/chain/step fusion lifecycle events into the "
            "bounded in-process ring buffer (profiler/events.py). Off by "
            "default: every emission site degenerates to a single flag "
            "check. Enabled automatically inside a Profiler window and by "
            "tools/fusion_doctor.py")
define_flag("FLAGS_profiler_events_capacity", 65536,
            "ring-buffer capacity (events) of the fusion flight recorder; "
            "oldest events are dropped past this size. Applied when the "
            "ring is (re)created — clear_fusion_events() picks up a "
            "changed value")

# Production telemetry plane (profiler/metrics.py + profiler/goodput.py):
# a typed, thread-safe metrics registry (counters, gauges, bounded
# log-bucket streaming histograms with labels) plus a live training
# accountant deriving rolling MFU / tokens-per-second / goodput from the
# step stream. Follows the flight recorder's cost discipline: when off,
# every instrumentation site degenerates to a single flag check; when on,
# an observation is O(1) work against preallocated bucket arrays — memory
# never grows with run length. Exposed via registry.exposition()
# (Prometheus text format), tools/metrics_export.py (crash-safe JSONL
# sink, mergeable across processes), and `fusion_doctor --metrics`.
define_flag("FLAGS_metrics", False,
            "record production metrics (counters/gauges/histograms) into "
            "the in-process registry (profiler/metrics.py) and run the "
            "live MFU/goodput accountant (profiler/goodput.py). Off by "
            "default: every site is one flag check "
            "(tools/perf_smoke.py guards <3%/step off, <5%/step on)")
define_flag("FLAGS_metrics_window", 100_000,
            "sliding-window size (observations) of the registry's "
            "streaming histograms: percentiles are computed over the "
            "current + previous window bands, so a long-running process "
            "reports FRESH p50/p99 instead of an all-of-history average "
            "that froze hours ago. 0 = cumulative (never rotate)")

# Persistent AOT executable cache (ops/aot_cache.py): content-addressed
# on-disk store of `jax.export`-serialized fused executables — per-op
# forward / forward+vjp pairs, fused chains, promoted whole-step programs,
# the serving decode step — keyed by the existing cache-key digests plus an
# environment fingerprint (jax/jaxlib/numpy versions, backend, device
# kind, PRNG-key export form), so a restarting worker deserializes
# yesterday's executables instead of paying the full trace+compile warmup.
# Writes are atomic (tmp + fsync + rename, CRC-32 trailer shared with the
# checkpoint writer); torn or corrupt artifacts are detected on load,
# quarantined, and transparently recompiled — the store can never crash a
# training or serving process, only make its warmup cheaper.
# Live HTTP observability plane (profiler/telemetry_server.py). Off by
# default: 0 means no server thread, no socket, and every heartbeat site
# costs one module-bool check. A nonzero port starts the stdlib
# ThreadingHTTPServer at import (paddle_tpu/__init__) / engine build and
# serves /metrics, /metrics.json, /goodput, /doctor, /events, /healthz,
# /readyz on 127.0.0.1.
define_flag("FLAGS_telemetry_port", 0,
            "port for the zero-dependency telemetry HTTP server "
            "(profiler/telemetry_server.py). 0 (default) = off: no "
            "thread, no socket, heartbeats are one bool check. Seeded "
            "from the environment like every flag, so "
            "`FLAGS_telemetry_port=9100 python train.py` arms a live "
            "/metrics scrape surface")
define_flag("FLAGS_telemetry_host", "127.0.0.1",
            "bind address for the telemetry HTTP server. The loopback "
            "default keeps the surface node-local; set 0.0.0.0 (or a "
            "NIC address) for a cross-host Prometheus / fleet_metrics "
            "scrape")
define_flag("FLAGS_telemetry_stale_s", 120.0,
            "liveness window for /healthz heartbeat sources when the "
            "serving watchdog is disarmed: an open (un-finalized) "
            "training accountant or a busy engine whose last step is "
            "older than this reports unhealthy. Armed serving engines "
            "use the FLAGS_serve_step_timeout_ms budget instead")

# Performance regression sentinel (profiler/sentinel.py). Disarmed by
# default: every tick site costs one module-bool check. Armed, the
# sentinel snapshots the goodput accountant / metrics registry once per
# evaluation window, classifies drift against a checked-in per-leg
# baseline (tools/perf_baselines.json) — or against its own first clean
# window when no leg is named — and flips the /readyz degraded latch
# with the finding attached.
define_flag("FLAGS_sentinel", False,
            "arm the performance regression sentinel "
            "(profiler/sentinel.py): per-window drift verdicts "
            "(perf_drift / split_regression / compile_storm / "
            "latency_drift), a /sentinel endpoint on the telemetry "
            "server, and a /readyz flip on confirmed drift. Disarmed "
            "= one bool check per step")
define_flag("FLAGS_sentinel_window_s", 10.0,
            "sentinel evaluation window in seconds: drift is judged "
            "over whole windows (one registry/accountant snapshot per "
            "window), so smaller windows detect faster but judge "
            "noisier statistics")
define_flag("FLAGS_sentinel_baseline", "",
            "path to the per-leg perf baseline JSON for the sentinel "
            "and tools/perf_baseline.py; empty = the checked-in "
            "tools/perf_baselines.json")
define_flag("FLAGS_sentinel_leg", "",
            "baseline leg name the live sentinel compares against "
            "(e.g. 'fused', 'serve_8'); empty = self-calibrate: the "
            "first completed clean window becomes the reference band")

define_flag("FLAGS_aot_cache", False,
            "persist fused executables (per-op/chain/whole-step/serving "
            "decode) to a content-addressed on-disk store via jax.export "
            "and reload them on restart: a preempted worker re-promotes "
            "its fused train step on the first cycle with zero fresh "
            "traces (warm start). Off by default: storing exports each "
            "executable once at build time (extra trace cost in COLD "
            "processes); enable it for fleet workers that restart under "
            "traffic. Corrupt/version-skewed artifacts are quarantined "
            "and recompiled, never trusted")
define_flag("FLAGS_aot_cache_dir", "",
            "root directory of the AOT executable store. Empty (default): "
            "$PADDLE_TPU_CACHE_DIR/aot when the env var is set (tests "
            "share this root with the persistent XLA compile cache), "
            "else /tmp/paddle_tpu_cache/aot. Content addressing makes "
            "concurrent multi-process writers safe: same key -> same "
            "bytes, last atomic rename wins")
define_flag("FLAGS_aot_cache_max_bytes", 1 << 30,
            "size budget of the AOT store; past it, eviction removes "
            "oldest-mtime artifacts first (loads refresh mtime, so the "
            "policy is LRU-ish). Checked opportunistically after stores "
            "and by `fusion_doctor --cache --gc`. 0 disables the size "
            "bound")
define_flag("FLAGS_aot_cache_max_age_s", 14 * 86400,
            "age bound of the AOT store (seconds since last use); older "
            "artifacts and quarantined *.corrupt files are removed by "
            "eviction. 0 disables the age bound")

define_flag("FLAGS_eager_step_fusion_donate_params", False,
            "EXPERIMENTAL: donate parameter buffers (in addition to the "
            "optimizer-slot buffers, which are always donated exactly as "
            "the eager optimizer's own fused update donates them) to the "
            "whole-step executable. Off by default for the same aliasing "
            "hazard as jit.TrainStep's donate='all': user-held aliases of "
            "p._value (detach() shares storage) would be invalidated. "
            "Donation is a warn-and-skip no-op on CPU")


class _FlagsView:
    def __getattr__(self, name):
        full = name if name.startswith("FLAGS_") else f"FLAGS_{name}"
        try:
            return _FLAGS[full]
        except KeyError:
            raise AttributeError(name)

    def __setattr__(self, name, value):
        global _GENERATION
        full = name if name.startswith("FLAGS_") else f"FLAGS_{name}"
        with _lock:
            _FLAGS[full] = value
            _GENERATION += 1


FLAGS = _FlagsView()


def set_flags(flags: dict):
    global _GENERATION
    with _lock:
        for k, v in flags.items():
            _FLAGS[k] = v
        _GENERATION += 1


def get_flags(flags):
    if isinstance(flags, str):
        flags = [flags]
    return {k: _FLAGS.get(k) for k in flags}
