"""paddle.save / paddle.load. Reference analog:
python/paddle/framework/io.py:640 (save) / :882 (load) — pickle protocol with
tensors converted to numpy payloads; nested state dict structures preserved.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from .core import Tensor, Parameter

__all__ = ["save", "load"]

_PROTOCOL_KEY = "__paddle_tpu_tensor__"


def _pack(obj):
    if isinstance(obj, Parameter):
        return {_PROTOCOL_KEY: "parameter", "data": obj.numpy(),
                "name": obj.name, "trainable": obj.trainable}
    if isinstance(obj, Tensor):
        return {_PROTOCOL_KEY: "tensor", "data": obj.numpy(),
                "name": obj.name, "stop_gradient": obj.stop_gradient}
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        packed = [_pack(v) for v in obj]
        return packed if isinstance(obj, list) else tuple(packed)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, dict):
        tag = obj.get(_PROTOCOL_KEY)
        if tag == "parameter":
            if return_numpy:
                return obj["data"]
            p = Parameter(obj["data"], name=obj["name"],
                          trainable=obj.get("trainable", True))
            return p
        if tag == "tensor":
            if return_numpy:
                return obj["data"]
            t = Tensor(obj["data"], name=obj["name"])
            t.stop_gradient = obj.get("stop_gradient", True)
            return t
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_unpack(v, return_numpy) for v in obj]
    if isinstance(obj, tuple):
        return tuple(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    if hasattr(path, "write"):
        pickle.dump(_pack(obj), path, protocol=protocol)
        return
    dirname = os.path.dirname(path)
    if dirname and not os.path.isdir(dirname):
        os.makedirs(dirname, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, **configs):
    return_numpy = configs.get("return_numpy", False)
    if hasattr(path, "read"):
        data = pickle.load(path)
    else:
        with open(path, "rb") as f:
            data = pickle.load(f)
    return _unpack(data, return_numpy)
