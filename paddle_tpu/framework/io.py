"""paddle.save / paddle.load. Reference analog:
python/paddle/framework/io.py:640 (save) / :882 (load) — pickle protocol with
tensors converted to numpy payloads; nested state dict structures preserved.

Crash safety (PR 5): every path-targeted save — sync and async alike —
writes to a same-directory temp file, fsyncs, appends a CRC-32 trailer, and
`os.replace`s into place, so a crash or kill -9 mid-save can never leave a
torn checkpoint under the real name: the previous complete file survives.
`load()` verifies the trailer when present and raises
`CheckpointCorruptError` (an IOError) instead of unpickling junk.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from .core import Tensor, Parameter

__all__ = ["save", "load", "async_save", "AsyncSaveHandle",
           "CheckpointCorruptError"]


class CheckpointCorruptError(IOError):
    """A checkpoint file failed its integrity check (CRC mismatch,
    truncation, or an unreadable pickle stream). The file on disk is not a
    usable checkpoint — restore from an earlier one (EpochRange retains a
    rolling window) instead of training on partial state."""

_PROTOCOL_KEY = "__paddle_tpu_tensor__"


def _pack(obj):
    if isinstance(obj, Parameter):
        return {_PROTOCOL_KEY: "parameter", "data": obj.numpy(),
                "name": obj.name, "trainable": obj.trainable}
    if isinstance(obj, Tensor):
        return {_PROTOCOL_KEY: "tensor", "data": obj.numpy(),
                "name": obj.name, "stop_gradient": obj.stop_gradient}
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        packed = [_pack(v) for v in obj]
        return packed if isinstance(obj, list) else tuple(packed)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, dict):
        tag = obj.get(_PROTOCOL_KEY)
        if tag == "parameter":
            if return_numpy:
                return obj["data"]
            p = Parameter(obj["data"], name=obj["name"],
                          trainable=obj.get("trainable", True))
            return p
        if tag == "tensor":
            if return_numpy:
                return obj["data"]
            t = Tensor(obj["data"], name=obj["name"])
            t.stop_gradient = obj.get("stop_gradient", True)
            return t
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_unpack(v, return_numpy) for v in obj]
    if isinstance(obj, tuple):
        return tuple(_unpack(v, return_numpy) for v in obj)
    return obj


def _write_atomic(path, payload):
    """tmp + fsync + os.replace with the CRC-32 trailer: the destination
    either keeps its previous complete content or becomes the new complete
    content — never a torn mix (shared by save and the async fallback)."""
    import struct
    import zlib
    dirname = os.path.dirname(path)
    if dirname and not os.path.isdir(dirname):
        os.makedirs(dirname, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(payload)
            f.write(struct.pack("<QQQ", _TRAILER_MAGIC, len(payload),
                                zlib.crc32(payload)))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def save(obj, path, protocol=4, **configs):
    if hasattr(path, "write"):
        pickle.dump(_pack(obj), path, protocol=protocol)
        return
    _write_atomic(os.fspath(path),
                  pickle.dumps(_pack(obj), protocol=protocol))


class AsyncSaveHandle:
    """In-flight async save. wait() joins the native writer; done() polls."""

    _ERR = {1: "cannot open file", 2: "short write", 3: "trailer write failed",
            4: "rename failed"}

    def __init__(self, lib, native_handle, path):
        self._lib = lib
        self._handle = native_handle
        self.path = path
        self._finished = False

    def done(self):
        if self._finished:
            return True
        return self._lib.pd_ckpt_poll(self._handle) >= 0

    def wait(self):
        if self._finished:
            return
        status = self._lib.pd_ckpt_wait(self._handle)
        self._finished = True
        if status != 0:
            raise IOError(
                f"async_save to {self.path} failed: "
                f"{self._ERR.get(status, status)}")

    def __del__(self):
        # poll-only callers would otherwise leak the native job
        if not self._finished and self._lib is not None:
            try:
                self._lib.pd_ckpt_wait(self._handle)
            except Exception:
                pass
            self._finished = True


def async_save(obj, path, protocol=4):
    """Serialize on the calling thread, write + fsync + CRC on a native C++
    writer thread (csrc/ckpt_writer.cc) so training overlaps checkpoint IO.

    Reference analog: save ops + auto_checkpoint's background persistence.
    Returns an AsyncSaveHandle; call .wait() before relying on the file.
    Falls back to a synchronous save when the native runtime is unavailable.
    """
    import ctypes
    from ..core._build import load_library

    path = os.fspath(path)
    payload = pickle.dumps(_pack(obj), protocol=protocol)
    dirname = os.path.dirname(path)
    if dirname and not os.path.isdir(dirname):
        os.makedirs(dirname, exist_ok=True)

    lib = load_library()
    if lib is None:
        # synchronous fallback keeps the same guarantees: atomic tmp+rename
        # and the CRC trailer (pure-python zlib.crc32 == IEEE CRC-32)
        _write_atomic(path, payload)
        sync = AsyncSaveHandle(None, None, path)
        sync._finished = True
        return sync

    lib.pd_ckpt_async_write.restype = ctypes.c_void_p
    lib.pd_ckpt_async_write.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                        ctypes.c_uint64]
    lib.pd_ckpt_poll.argtypes = [ctypes.c_void_p]
    lib.pd_ckpt_wait.argtypes = [ctypes.c_void_p]
    handle = lib.pd_ckpt_async_write(path.encode(), payload, len(payload))
    return AsyncSaveHandle(lib, handle, path)


_TRAILER_MAGIC = 0x50445450434b5054  # "PDTPCKPT" (csrc/ckpt_writer.cc)


def _verify_trailer(path):
    """CRC-check files written by async_save; no-op for legacy files.

    Pure python (zlib.crc32 is the same IEEE CRC-32 the native writer uses),
    so verification never depends on a g++ toolchain at load time."""
    import struct
    import zlib
    path = os.fspath(path)
    size = os.path.getsize(path)
    if size < 24:
        return
    with open(path, "rb") as f:
        f.seek(size - 24)
        magic, payload_len, crc_stored = struct.unpack("<QQQ", f.read(24))
        if magic != _TRAILER_MAGIC or payload_len != size - 24:
            return  # legacy file without a trailer
        f.seek(0)
        crc = 0
        left = payload_len
        while left > 0:
            chunk = f.read(min(left, 1 << 20))
            if not chunk:
                raise CheckpointCorruptError(
                    f"checkpoint {path} is corrupt (truncated)")
            crc = zlib.crc32(chunk, crc)
            left -= len(chunk)
    if crc != crc_stored:
        raise CheckpointCorruptError(
            f"checkpoint {path} is corrupt (CRC mismatch — torn write?)")


def read_verified_payload(path, require_trailer=False):
    """Read a trailer-protected file and return its payload bytes (the
    content before the 24-byte CRC-32 trailer `_write_atomic` appends).

    Raises CheckpointCorruptError on truncation or a CRC mismatch. With
    `require_trailer=False` a file without a recognizable trailer is
    returned whole (legacy checkpoints); with True a missing trailer is
    itself corruption — used by the AOT executable store
    (ops/aot_cache.py), whose files are never legacy and must never be
    deserialized unverified."""
    import struct
    import zlib
    path = os.fspath(path)
    with open(path, "rb") as f:
        data = f.read()
    if len(data) >= 24:
        magic, payload_len, crc_stored = struct.unpack("<QQQ", data[-24:])
        if magic == _TRAILER_MAGIC and payload_len == len(data) - 24:
            payload = data[:-24]
            if zlib.crc32(payload) != crc_stored:
                raise CheckpointCorruptError(
                    f"{path} is corrupt (CRC mismatch — torn write?)")
            return payload
    if require_trailer:
        raise CheckpointCorruptError(
            f"{path} is corrupt (missing or damaged integrity trailer)")
    return data


def load(path, **configs):
    return_numpy = configs.get("return_numpy", False)
    if hasattr(path, "read"):
        data = pickle.load(path)
    else:
        _verify_trailer(path)
        try:
            with open(path, "rb") as f:
                # pickle.load stops at the end of the pickle stream, so the
                # 24-byte CRC trailer is transparently ignored
                data = pickle.load(f)
        except (pickle.UnpicklingError, EOFError, IndexError) as e:
            # a legacy (trailer-less) file that is nevertheless broken:
            # surface a checkpoint error, not a pickle internals traceback.
            # AttributeError/ImportError are NOT caught — a missing class
            # or module is code-version skew on a healthy file, and
            # EpochRange.restore() must not skip past it as "corrupt"
            raise CheckpointCorruptError(
                f"checkpoint {path} is corrupt (unreadable pickle stream: "
                f"{e})") from e
    return _unpack(data, return_numpy)
