"""Global RNG management over jax PRNG keys.

Reference analog: paddle/phi/core/generator.h (global Generator per device) and
python/paddle/fluid/framework.py seed handling. TPU-first: randomness is a
*stream* over a fixed base key — consumption i draws
`jax.random.fold_in(base_key, i)` — so a stream position is pure data:

  * eager sampling derives the key on the spot (one tiny fold_in),
  * the whole-step fusion promoter (ops/step_fusion.py) hoists
    (base-key data, first stream position) into the ONE fused executable as
    device scalar arguments — exactly the LR-scalar pattern — and derives
    every key IN-GRAPH, so dropout>0 loops promote with a per-step-advancing
    key whose bits match the eager stream exactly,
  * checkpoints snapshot (base key, position) and resume the stream
    bit-for-bit (incubate/checkpoint.py).

RNG-consuming ops request a stream position via `rng_key_input()`, which
returns a LAZY key tensor: the uint32 key data materializes only if some
non-fused path actually reads it, so a fused replay advances the stream
without launching anything. Under `to_static`/jit tracing, keys come from a
traced key context (threaded by the jitted step) so compiled random ops do
not bake in a constant key.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["seed", "get_rng_key", "split_key", "default_generator",
           "tracing_key_scope", "RNGKeyContext", "rng_epoch",
           "rng_checkpoint_state", "set_rng_checkpoint_state",
           "rng_key_input", "derive_key_data", "stream_base_data",
           "slot_sample_keys", "HoistedKeyTensor"]


class _GlobalGenerator:
    """Stateful generator: a fixed base jax PRNG key plus a monotonically
    advancing stream position; consumption i yields `fold_in(base, i)`.
    The base key materializes LAZILY on first use — creating it at import
    would initialize the jax backend as a side effect of `import
    paddle_tpu` (launch helpers and shell tools must be able to import the
    package without touching an accelerator)."""

    def __init__(self, seed_val: int = 0):
        self._lock = threading.Lock()
        self._key = None            # the BASE key (fixed between seedings)
        self.initial_seed = seed_val
        # total stream positions consumed (any path); checkpointed so a
        # resumed run continues the interrupted stream bit-for-bit
        self.epoch = 0
        # positions consumed through the STATEFUL next_key() path only:
        # dispatch reads this to attribute an un-keyable op to fresh
        # randomness (`rng_rekey` in the fusion flight recorder). Hoisted
        # consumption (rng_key_input) never bumps it — those ops key
        # cleanly and must not smear rng_rekey onto unrelated bypasses.
        self.legacy_epoch = 0
        # whether the user explicitly seeded (paddle.seed): consumers that
        # want "deterministic iff seeded" semantics (DataLoader worker
        # seeding) check this instead of guessing from the value
        self.seeded = False

    def _base(self):
        # callers hold self._lock
        if self._key is None:
            self._key = jax.random.key(self.initial_seed)
        return self._key

    def manual_seed(self, seed_val: int):
        with self._lock:
            self._key = jax.random.key(int(seed_val))
            self.initial_seed = int(seed_val)
            self.epoch = 0
            self.seeded = True
        return self

    def next_key(self):
        with self._lock:
            base = self._base()
            ep = self.epoch
            self.epoch += 1
            self.legacy_epoch += 1
        return jax.random.fold_in(base, ep)

    def reserve(self):
        """Reserve one stream position without deriving the key: (base
        key, position). The hoisted-consumption path — derivation happens
        lazily on read, or in-graph inside a fused step."""
        with self._lock:
            base = self._base()
            ep = self.epoch
            self.epoch += 1
        return base, ep


default_generator = _GlobalGenerator(0)

_tracing_ctx = threading.local()


class RNGKeyContext:
    """Context holding a (possibly traced) key that random ops consume.

    Used by jitted train steps: the step function receives an explicit key and
    installs it here so `dropout` etc. pull traced randomness instead of the
    global stateful generator (which would be baked as a constant under trace).
    """

    def __init__(self, key):
        self.key = key

    def next_key(self):
        self.key, sub = jax.random.split(self.key)
        return sub


class tracing_key_scope:
    def __init__(self, key):
        self._ctx = RNGKeyContext(key)

    def __enter__(self):
        stack = getattr(_tracing_ctx, "stack", None)
        if stack is None:
            stack = _tracing_ctx.stack = []
        stack.append(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        _tracing_ctx.stack.pop()
        return False


def rng_epoch():
    """Monotonic count of keys drawn through the STATEFUL path (an op
    closing over `get_rng_key()` output). An op whose fn is un-keyable AND
    whose dispatch follows an advance here consumed fresh un-hoistable
    randomness — the `rng_rekey` signature in ops/dispatch.py bypass
    attribution. Hoisted consumption (`rng_key_input`) does not count:
    those ops key on structure and promote."""
    return default_generator.legacy_epoch


def seed(seed_val: int):
    """`paddle.seed` equivalent — reseed the global generator (the stream
    restarts at position 0 over the new base key)."""
    return default_generator.manual_seed(seed_val)


def get_rng_key():
    """Return a fresh PRNG key: from the innermost tracing scope if active,
    else the next position of the global stream."""
    stack = getattr(_tracing_ctx, "stack", None)
    if stack:
        return stack[-1].next_key()
    return default_generator.next_key()


def split_key(n: int):
    return jax.random.split(get_rng_key(), n)


# ---------------------------------------------------------------------------
# hoisted consumption: stream positions as lazy dispatch inputs
# ---------------------------------------------------------------------------

# resolved lazily: framework/__init__ imports .core before .random, but a
# direct `import paddle_tpu.framework.random` must not force the order
_Tensor = None
_VALUE_SLOT = None
_NODE_SLOT = None
_IDX_SLOT = None
_UNMATERIALIZED = object()
_KD_AVAL = None     # (shape, dtype, weak_type) of key data, impl-dependent


def _tensor_cls():
    global _Tensor, _VALUE_SLOT, _NODE_SLOT, _IDX_SLOT
    if _Tensor is None:
        from .core import Tensor
        _Tensor = Tensor
        _VALUE_SLOT = Tensor.__dict__["_value"]
        _NODE_SLOT = Tensor.__dict__["_grad_node"]
        _IDX_SLOT = Tensor.__dict__["_out_index"]
    return _Tensor


def _key_data_aval():
    """Aval of the raw key data the default PRNG impl produces (threefry:
    uint32[2]); answered without deriving any stream key."""
    global _KD_AVAL
    if _KD_AVAL is None:
        kd = jax.random.key_data(jax.random.key(0))
        _KD_AVAL = (tuple(kd.shape), kd.dtype, False)
    return _KD_AVAL


def derive_key_data(base_data, epoch):
    """Key data for stream position `epoch` from raw base-key data — pure
    and traceable: the fused step derives every hoisted key IN-GRAPH from
    (base data, first position) device arguments with exactly these ops,
    so fused and eager key streams agree bit-for-bit."""
    key = jax.random.fold_in(jax.random.wrap_key_data(base_data), epoch)
    return jax.random.key_data(key)


def slot_sample_keys(seeds, positions):
    """Per-slot sampling keys `fold_in(PRNGKey(seed), position)` — pure and
    traceable over `[S]` uint32 seed and `[S]` int32 position arrays. The
    serving engine keys every stochastic token off (request seed, count of
    known context tokens), so a stream replays bit-for-bit across
    preemption, watchdog rebuild, and crash resume: re-prefilling the
    prompt+generated context restores exactly the positions the original
    stream consumed."""
    def one(seed, pos):
        return jax.random.fold_in(jax.random.PRNGKey(seed), pos)
    return jax.vmap(one)(seeds, positions)


def stream_base_data():
    """Raw uint32 data of the current base key (a device value suitable as
    a hoisted executable argument). The base is fixed between seedings, so
    the returned array stays valid for the life of a promoted program."""
    g = default_generator
    with g._lock:
        base = g._base()
    return jax.random.key_data(base)


def _make_hoisted_cls():
    Tensor = _tensor_cls()

    class HoistedKeyTensor(Tensor):
        """One reserved stream position as a dispatch input.

        The uint32 key data derives LAZILY on first `_value` read (eager
        dispatch, chain-tier replay, transactional splits); a fused
        whole-step replay never reads it — the executable derives the key
        in-graph from hoisted (base data, position) args — so promoted
        dropout loops launch nothing per key. `_fusion_aval` answers
        keying queries without forcing, the same contract as chain
        placeholders (ops/fusion.py)."""

        __slots__ = ("_rng_base", "_rng_epoch")

        def __init__(self, base, epoch):
            _VALUE_SLOT.__set__(self, _UNMATERIALIZED)
            _NODE_SLOT.__set__(self, None)
            _IDX_SLOT.__set__(self, 0)
            self.stop_gradient = True
            self.grad = None
            self.name = f"rng_key@{epoch}"
            self.persistable = False
            self._hooks = []
            self._rng_base = base
            self._rng_epoch = epoch

        @property
        def _value(self):
            v = _VALUE_SLOT.__get__(self)
            if v is _UNMATERIALIZED:
                v = derive_key_data(jax.random.key_data(self._rng_base),
                                    self._rng_epoch)
                _VALUE_SLOT.__set__(self, v)
            return v

        @_value.setter
        def _value(self, v):
            _VALUE_SLOT.__set__(self, v)

        @property
        def _fusion_aval(self):
            """Aval while lazy, else None — read by dispatch keying and
            the cycle recorder; never derives the key."""
            if _VALUE_SLOT.__get__(self) is _UNMATERIALIZED:
                return _key_data_aval()
            return None

        @property
        def shape(self):
            if _VALUE_SLOT.__get__(self) is _UNMATERIALIZED:
                return list(_key_data_aval()[0])
            return list(_VALUE_SLOT.__get__(self).shape)

        @property
        def ndim(self):
            return len(self.shape)

    return HoistedKeyTensor


HoistedKeyTensor = None     # resolved on first rng_key_input()


def rng_key_input():
    """A key-data tensor for ONE fresh stream position, to be passed as a
    dispatch INPUT of an RNG-consuming op (the op wraps it back into a
    typed key with `jax.random.wrap_key_data` inside its fn). Keyed on
    structure — the op's cache key carries only the stable key-data aval —
    so RNG consumption no longer bypasses the executable cache or poisons
    fusion cycles (`rng_rekey`). Under an active tracing scope the key
    comes from the traced context instead (a tracer value: the op is
    absorbed into the enclosing trace, exactly as before)."""
    global HoistedKeyTensor
    stack = getattr(_tracing_ctx, "stack", None)
    if stack:
        Tensor = _tensor_cls()
        return Tensor(jax.random.key_data(stack[-1].next_key()),
                      stop_gradient=True)
    if HoistedKeyTensor is None:
        HoistedKeyTensor = _make_hoisted_cls()
    base, ep = default_generator.reserve()
    return HoistedKeyTensor(base, ep)


def get_rng_state():
    """Snapshot of the global generator state: (base key, stream position)
    — list-of-states for parity with the reference's per-device
    GeneratorState list."""
    g = default_generator
    with g._lock:
        return [(g._key, g.epoch)]


def _is_state_pair(s):
    """(key, position) pair vs a legacy list of bare per-device keys."""
    import numbers
    return isinstance(s, (list, tuple)) and len(s) == 2 \
        and isinstance(s[1], numbers.Integral)


def set_rng_state(state):
    # accept every historical shape: [(key, pos)] (current get_rng_state),
    # (key, pos), [key] / [key, key, ...] (legacy list of per-device
    # GeneratorStates), a bare key, or None/[]
    if isinstance(state, (list, tuple)) and not _is_state_pair(state):
        state = state[0] if state else None
    g = default_generator
    with g._lock:
        if _is_state_pair(state):
            g._key = state[0]
            g.epoch = int(state[1])
        else:
            # a bare key (legacy callers): restart its stream
            g._key = state
            g.epoch = 0


# CUDA-named aliases kept for API parity (there is one logical generator
# here; reference: python/paddle/framework/random.py get_cuda_rng_state)
get_cuda_rng_state = get_rng_state
set_cuda_rng_state = set_rng_state


def rng_checkpoint_state():
    """Pickle-safe snapshot of the global generator for crash-safe
    checkpoints (incubate/checkpoint.py): the BASE key bits as numpy
    (typed jax keys don't pickle portably), the stream position (so a
    restored run resumes the interrupted key stream bit-for-bit — fused
    and eager alike, since both derive position i as fold_in(base, i)),
    and the seed bookkeeping."""
    import numpy as np
    g = default_generator
    with g._lock:
        key = g._key
        key_data = None if key is None \
            else np.asarray(jax.random.key_data(key))
        return {"key_data": key_data, "epoch": g.epoch,
                "legacy_epoch": g.legacy_epoch,
                "initial_seed": g.initial_seed, "seeded": g.seeded}


def set_rng_checkpoint_state(state):
    """Restore a `rng_checkpoint_state()` snapshot; resumed sampling
    continues the interrupted stream bit-for-bit."""
    g = default_generator
    kd = state.get("key_data")
    with g._lock:
        g._key = None if kd is None else jax.random.wrap_key_data(kd)
        g.epoch = int(state.get("epoch", 0))
        g.legacy_epoch = int(state.get("legacy_epoch",
                                       state.get("epoch", 0)))
        g.initial_seed = int(state.get("initial_seed", 0))
        g.seeded = bool(state.get("seeded", False))
