"""Global RNG management over jax PRNG keys.

Reference analog: paddle/phi/core/generator.h (global Generator per device) and
python/paddle/fluid/framework.py seed handling. TPU-first: a functional PRNG key
is split per sampling call; under `to_static`/jit tracing, keys come from a
traced key context (threaded by the jitted step) so compiled random ops do not
bake in a constant key.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["seed", "get_rng_key", "split_key", "default_generator",
           "tracing_key_scope", "RNGKeyContext", "rng_epoch",
           "rng_checkpoint_state", "set_rng_checkpoint_state"]


class _GlobalGenerator:
    """Stateful generator: holds a jax PRNG key, splits off a fresh subkey
    per use. The key materializes LAZILY on first use — creating it at
    import would initialize the jax backend as a side effect of
    `import paddle_tpu` (launch helpers and shell tools must be able to
    import the package without touching an accelerator)."""

    def __init__(self, seed_val: int = 0):
        self._lock = threading.Lock()
        self._key = None
        self.initial_seed = seed_val
        # bumped on every key split: dispatch reads this to attribute an
        # un-keyable op to fresh randomness (`rng_rekey` in the fusion
        # flight recorder) instead of a generic un-keyable closure
        self.epoch = 0
        # whether the user explicitly seeded (paddle.seed): consumers that
        # want "deterministic iff seeded" semantics (DataLoader worker
        # seeding) check this instead of guessing from the value
        self.seeded = False

    def manual_seed(self, seed_val: int):
        with self._lock:
            self._key = jax.random.key(int(seed_val))
            self.initial_seed = int(seed_val)
            self.seeded = True
        return self

    def next_key(self):
        with self._lock:
            if self._key is None:
                self._key = jax.random.key(self.initial_seed)
            self._key, sub = jax.random.split(self._key)
            self.epoch += 1
        return sub


default_generator = _GlobalGenerator(0)

_tracing_ctx = threading.local()


class RNGKeyContext:
    """Context holding a (possibly traced) key that random ops consume.

    Used by jitted train steps: the step function receives an explicit key and
    installs it here so `dropout` etc. pull traced randomness instead of the
    global stateful generator (which would be baked as a constant under trace).
    """

    def __init__(self, key):
        self.key = key

    def next_key(self):
        self.key, sub = jax.random.split(self.key)
        return sub


class tracing_key_scope:
    def __init__(self, key):
        self._ctx = RNGKeyContext(key)

    def __enter__(self):
        stack = getattr(_tracing_ctx, "stack", None)
        if stack is None:
            stack = _tracing_ctx.stack = []
        stack.append(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        _tracing_ctx.stack.pop()
        return False


def rng_epoch():
    """Monotonic count of keys split off the global generator. An op whose
    fn is un-keyable AND whose dispatch follows an epoch advance consumed
    fresh randomness this call — the `rng_rekey` signature (dropout et
    al.) in ops/dispatch.py bypass attribution."""
    return default_generator.epoch


def seed(seed_val: int):
    """`paddle.seed` equivalent — reseed the global generator."""
    return default_generator.manual_seed(seed_val)


def get_rng_key():
    """Return a fresh PRNG key: from the innermost tracing scope if active,
    else from the global stateful generator."""
    stack = getattr(_tracing_ctx, "stack", None)
    if stack:
        return stack[-1].next_key()
    return default_generator.next_key()


def split_key(n: int):
    return jax.random.split(get_rng_key(), n)


def get_rng_state():
    """Snapshot of the global generator state (list-of-states for parity
    with the reference's per-device GeneratorState list)."""
    with default_generator._lock:
        return [default_generator._key]


def set_rng_state(state):
    if isinstance(state, (list, tuple)):
        state = state[0] if state else None
    with default_generator._lock:
        default_generator._key = state


# CUDA-named aliases kept for API parity (there is one logical generator
# here; reference: python/paddle/framework/random.py get_cuda_rng_state)
get_cuda_rng_state = get_rng_state
set_cuda_rng_state = set_rng_state


def rng_checkpoint_state():
    """Pickle-safe snapshot of the global generator for crash-safe
    checkpoints (incubate/checkpoint.py): the raw key bits as numpy (typed
    jax keys don't pickle portably), the epoch counter (so `rng_rekey`
    attribution and any epoch-derived seeding resume exactly), and the
    seed bookkeeping."""
    import numpy as np
    g = default_generator
    with g._lock:
        key = g._key
        key_data = None if key is None \
            else np.asarray(jax.random.key_data(key))
        return {"key_data": key_data, "epoch": g.epoch,
                "initial_seed": g.initial_seed, "seeded": g.seeded}


def set_rng_checkpoint_state(state):
    """Restore a `rng_checkpoint_state()` snapshot; resumed sampling
    continues the interrupted stream bit-for-bit."""
    g = default_generator
    kd = state.get("key_data")
    with g._lock:
        g._key = None if kd is None else jax.random.wrap_key_data(kd)
        g.epoch = int(state.get("epoch", 0))
        g.initial_seed = int(state.get("initial_seed", 0))
        g.seeded = bool(state.get("seeded", False))
