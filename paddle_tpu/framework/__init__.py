from . import jax_compat  # noqa: F401  (installs jax version-compat shims)
from .dtype import (  # noqa: F401
    DType, convert_dtype, set_default_dtype, get_default_dtype,
    uint8, int8, int16, int32, int64, float16, bfloat16, float32, float64,
    complex64, complex128, bool_,
)
from .core import Tensor, Parameter, to_tensor, is_tensor, Place  # noqa: F401
from .autograd import (  # noqa: F401
    no_grad, enable_grad, set_grad_enabled, is_grad_enabled, grad,
)
from .random import seed, get_rng_key, default_generator  # noqa: F401
