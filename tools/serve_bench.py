#!/usr/bin/env python
"""Serving-engine benchmark: tokens/s + p50/p99 decode-step latency at N
concurrent streams through `paddle_tpu.serving.LLMEngine`.

The workload is the continuous-batching steady state the engine is built
for: N requests with MIXED prompt lengths enqueued at once, churning
through a fixed slot layout — requests join and leave at token
boundaries while the ONE compiled decode executable serves every step.
The measured window starts AFTER warmup (decode program + every prefill
bucket the workload uses compiled), so:

  * `decode_compiles` in the record is the number of decode traces INSIDE
    the measured window — the zero-retrace acceptance criterion is this
    field staying 0 while streams churn;
  * p50/p99 step times are steady-state numbers, not compile spikes
    (the serving target: compiled decode step <= 0.08 ms on TPU);
  * batch occupancy under saturation proves continuous batching is
    actually packing the slots (target >= 0.75, guarded by
    tools/perf_smoke.py).

Usage:

    JAX_PLATFORMS=cpu python tools/serve_bench.py --streams 8
    python tools/serve_bench.py --streams 64 --json
    python tools/serve_bench.py --streams 8 --trace /tmp/serve_trace

bench.py wires `serve_1` / `serve_8` / `serve_64` legs through
run_serve_bench() in its hang-proof subprocess harness; the fusion
flight recorder is armed for the run, so the record embeds the serve.*
event summary and the fusion-doctor verdict.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))


def _build_model(on_tpu):
    import paddle_tpu as paddle
    from paddle_tpu.incubate.models import GPTForCausalLM, GPTConfig

    paddle.seed(0)
    if on_tpu:
        from paddle_tpu.incubate.models import gpt2_124m
        cfg = gpt2_124m(hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0,
                        max_position_embeddings=512)
    else:
        cfg = GPTConfig(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                        num_attention_heads=4, intermediate_size=128,
                        max_position_embeddings=256,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0,
                        use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model


def _workload(streams, vocab, max_prompt, seed=0, shared_prefix=0):
    import numpy as np
    rng = np.random.default_rng(seed)
    prefix = (rng.integers(0, vocab, shared_prefix).tolist()
              if shared_prefix else [])
    lens = rng.integers(4, max_prompt + 1 - shared_prefix, streams)
    return [prefix + rng.integers(0, vocab, int(n)).tolist()
            for n in lens]


def _sampling_block(reqs, vocab, temperature, top_k, top_p, seed, snap):
    """The `sampling` headline: distinct-token fraction and normalized
    entropy over every emitted token. Greedy tiny-model streams loop
    hard (both numbers sit near 0); a working stochastic sampler spreads
    mass — the block is the cheap end-to-end sanity that temperature
    actually reached the compiled program."""
    import collections
    import math
    toks = [t for r in reqs for t in r.generated]
    block = {"temperature": float(temperature), "top_k": int(top_k),
             "top_p": float(top_p), "seed": seed,
             "sampled_tokens": snap["sampled_tokens"],
             "distinct_frac": 0.0, "entropy_norm": 0.0}
    if len(toks) > 1:
        counts = collections.Counter(toks)
        total = len(toks)
        ent = -sum((c / total) * math.log(c / total)
                   for c in counts.values())
        denom = math.log(min(total, vocab))
        block["distinct_frac"] = round(len(counts) / total, 4)
        block["entropy_norm"] = round(ent / denom if denom > 0 else 0.0,
                                      4)
    return block


def run_serve_bench(streams, on_tpu, max_new_tokens=None, trace_dir=None,
                    model=None, kernel=None, kv_dtype=None,
                    prefix_cache=False, temperature=0.0, top_k=0,
                    top_p=1.0, seed=None, pipeline=False):
    """One serving bench leg; returns a bench.py-style record dict.

    `kernel` pins the attention variant (default: the engine resolves
    FLAGS_serve_attention_kernel); `kv_dtype="int8"` runs the quantized
    KV pool. Both land in the record's extra so a bench trajectory always
    says WHICH kernel tier produced its numbers. `prefix_cache` runs the
    multi-tenant shared-prefix workload (PR 17): every stream carries
    the same leading system prompt, so the record's prefix-hit counters
    show the aliasing economy instead of zeros.

    Sampler knobs (PR 18) ride per-request: `temperature > 0` turns the
    legs stochastic (per-stream seeds derive from `seed`), and the
    record grows a `sampling` block — distinct-token fraction +
    normalized entropy over the emitted streams, the sanity check that
    the compiled sampler actually explores (greedy loops collapse both
    toward 0). `pipeline=True` runs the software-pipelined decode loop
    (launch N+1 / commit N) — same contract, overlap measured by the
    tokens/s headline."""
    import jax
    import numpy as np
    from paddle_tpu.framework.flags import get_flags, set_flags
    from paddle_tpu.profiler.events import clear_fusion_events
    from paddle_tpu.profiler import events_summary, fusion_events
    from paddle_tpu.profiler.explain import explain
    from paddle_tpu.profiler.metrics import (reset_metrics,
                                             serve_live_summary)
    from paddle_tpu.serving import LLMEngine

    if model is None:
        model = _build_model(on_tpu)
    cfg = model.config
    if max_new_tokens is None:
        max_new_tokens = 32 if on_tpu else 24
    # the serving target is decode latency at batch 8 (BASELINE serving
    # config); more streams than slots is the point — they churn through
    max_batch = min(streams, 8)
    max_prompt = 48 if on_tpu else 24
    clear_fusion_events()
    # telemetry plane armed (PR 12): the p50/p99/TTFT numbers below come
    # off the engine's bounded histograms — the same computation a
    # production scrape of the registry reports
    reset_metrics()
    prev = get_flags(["FLAGS_profiler_events", "FLAGS_metrics"])
    set_flags({"FLAGS_profiler_events": True, "FLAGS_metrics": True})
    try:
        # build the engine with the recorder already armed: construction
        # is where the kernel-tier attribution fires (kernel.fallback on
        # a demoted variant, kernel.quantized for an int8 pool) and the
        # bench's event record must contain it
        engine = LLMEngine(model, max_batch_size=max_batch,
                           block_size=16 if on_tpu else 8,
                           max_context=max_prompt + max_new_tokens + 8,
                           # bounded queue sized generously for the leg:
                           # the backpressure counters below stay 0 in a
                           # healthy run and move in the trajectory when
                           # admission or deadline behavior regresses
                           max_queue_depth=4 * streams,
                           attention_kernel=kernel, kv_dtype=kv_dtype,
                           enable_prefix_cache=prefix_cache,
                           pipeline_decode=pipeline)
        prompts = _workload(streams, cfg.vocab_size, max_prompt,
                            shared_prefix=(max_prompt // 2
                                           if prefix_cache else 0))
        # warmup: compile the decode program and every prefill bucket the
        # workload will hit (one representative prompt per bucket)
        buckets = {}
        for p in prompts:
            buckets.setdefault(engine._bucket_for(len(p)), p)
        for p in buckets.values():
            engine.generate([p], max_new_tokens=2)
        engine.reset_stats()

        reqs = []
        for i, p in enumerate(prompts):
            reqs.append(engine.add_request(
                p, max_new_tokens=max_new_tokens,
                temperature=temperature, top_k=top_k, top_p=top_p,
                seed=(None if seed is None else seed + i)))
        engine.run()
        snap = engine.stats()
        sampling = _sampling_block(reqs, cfg.vocab_size, temperature,
                                   top_k, top_p, seed, snap)

        tdir = None
        if trace_dir:
            # trace a few steady-state decode steps (programs are warm)
            os.makedirs(trace_dir, exist_ok=True)
            try:
                with jax.profiler.trace(trace_dir):
                    engine.generate(prompts[:max_batch], max_new_tokens=4)
                tdir = trace_dir
            except Exception as e:       # tracing must never sink the bench
                print(json.dumps({"event": "trace_failed",
                                  "error": str(e)[:200]}), flush=True)
        ev = fusion_events()
        doctor = explain(ev)
        live = serve_live_summary()
        # sentinel-comparable leg record — captured HERE, while the
        # engine is still registered (its per-engine tallies die with
        # it); bench.py re-stamps the leg name with its config name
        from paddle_tpu.profiler.sentinel import capture_record
        sentinel_rec = capture_record(
            f"serve_{streams}" + ("_prefix" if prefix_cache else ""),
            kind="serve")
    finally:
        set_flags(prev)

    platform = jax.devices()[0].platform
    return {
        "metric": (f"serve_{streams}_prefix_tokens_per_sec" if prefix_cache
                   else f"serve_{streams}_tokens_per_sec"),
        "value": round(snap["tokens_per_sec"], 1),
        "unit": "tokens/s",
        # serving target: compiled decode step <= 0.08 ms (TPU); CPU runs
        # report the same harness's number without claiming the target
        "vs_baseline": (round(0.08 / snap["p50_step_ms"], 4)
                        if on_tpu and snap["p50_step_ms"] else 0.0),
        "platform": platform,
        "extra": {
            "streams": streams,
            "max_batch": max_batch,
            "max_new_tokens": max_new_tokens,
            # kernel tier (PR 11): which attention variant + KV dtype
            # produced these numbers — a perf trajectory without this is
            # uninterpretable once the flag matrix exists
            "attention_kernel": snap["attention_kernel"],
            "kv_dtype": snap["kv_dtype"],
            "p50_step_ms": round(snap["p50_step_ms"], 4),
            "p99_step_ms": round(snap["p99_step_ms"], 4),
            # per-request latency story (PR 12): TTFT / inter-token /
            # queue-wait percentiles from the bounded windowed histograms
            "ttft_p50_ms": round(snap["ttft_p50_ms"], 4),
            "ttft_p99_ms": round(snap["ttft_p99_ms"], 4),
            "inter_token_p50_ms": round(snap["inter_token_p50_ms"], 4),
            "inter_token_p99_ms": round(snap["inter_token_p99_ms"], 4),
            "queue_wait_p99_ms": round(snap["queue_wait_p99_ms"], 4),
            # live registry view — same numbers a production scrape sees
            "metrics_live": live,
            "sentinel_record": sentinel_rec,
            "decode_steps": snap["steps"],
            # decode traces INSIDE the measured window — must stay 0
            "decode_compiles": snap["decode_compiles"],
            "prefill_compiles": snap["prefill_compiles"],
            "occupancy_mean": round(snap["occupancy_mean"], 4),
            "occupancy_saturated": round(snap["occupancy_saturated"], 4),
            "admitted": snap["admitted"],
            "evictions": snap["evictions"],
            "completed": snap["completed"],
            # resilience counters (PR 7): refusal/timeout/cancel/preempt
            # behavior is part of the trajectory, not just throughput —
            # a backpressure regression shows here before it shows in
            # tokens/s
            "refused": snap["refused"],
            "refused_queue_full": snap["refused_queue_full"],
            "refused_deadline": snap["refused_deadline"],
            "cancelled": snap["cancelled"],
            "expired": snap["expired"],
            "hangs": snap["hangs"],
            "eager_fallbacks": snap["eager_fallbacks"],
            "resumed": snap["resumed"],
            # multi-tenant counters (PR 17): zeros on a plain engine;
            # with --prefix-cache the hit-rate line IS the aliasing
            # economy (prefill work the shared system prompt avoided)
            "prefix_cache": prefix_cache,
            "prefix_hit_tokens": snap["prefix_hit_tokens"],
            "prefix_hit_rate": round(snap["prefix_hit_rate"], 4),
            "cow_copies": snap["cow_copies"],
            "adapter_switches": snap["adapter_switches"],
            "weight_swaps": snap["weight_swaps"],
            # compiled sampling + pipelined decode (PR 18): the headline
            # sanity block — a stochastic leg whose streams collapse to
            # repeats (distinct/entropy near 0) is broken sampling even
            # when tokens/s looks fine
            "pipeline": pipeline,
            "sampled_tokens": snap["sampled_tokens"],
            "commit_rollbacks": snap["commit_rollbacks"],
            "sampling": sampling,
            "platform": platform,
            "trace": tdir,
            "fusion_events": events_summary(ev),
            "fusion_doctor": {"verdict": doctor["verdict"],
                              "headline": doctor["headline"]},
        },
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="serve_bench",
        description="continuous-batching serving benchmark "
                    "(paddle_tpu.serving.LLMEngine)")
    ap.add_argument("--streams", type=int, default=8,
                    help="concurrent request streams (default 8)")
    ap.add_argument("--kernel", default=None,
                    choices=("pallas", "blockwise", "reference"),
                    help="attention kernel variant (default: "
                         "FLAGS_serve_attention_kernel)")
    ap.add_argument("--kv-dtype", default=None, choices=("int8",),
                    help="quantized KV cache mode (default: model dtype)")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="multi-tenant shared-prefix workload: every "
                         "stream carries the same system prompt and the "
                         "engine aliases its KV blocks (PR 17)")
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="per-stream sampling temperature (0 = greedy, "
                         "the compiled program is the SAME either way)")
    ap.add_argument("--top-k", type=int, default=0,
                    help="per-stream top-k filter (0 disables)")
    ap.add_argument("--top-p", type=float, default=1.0,
                    help="per-stream nucleus mass (1.0 disables)")
    ap.add_argument("--seed", type=int, default=None,
                    help="base sampling seed; stream i uses seed+i "
                         "(default: per-request crc32(rid) seeds)")
    ap.add_argument("--pipeline", action="store_true",
                    help="software-pipelined decode: launch step N+1 "
                         "while step N's host commit overlaps (PR 18)")
    ap.add_argument("--max-new-tokens", type=int, default=None)
    ap.add_argument("--trace", default=None,
                    help="directory for a jax profiler trace of a few "
                         "steady-state decode steps")
    ap.add_argument("--telemetry-port", type=int, default=None,
                    help="arm the live HTTP observability plane "
                         "(profiler/telemetry_server.py) on this port "
                         "for the run — scrape /metrics /goodput "
                         "/healthz while the bench churns (0 = an "
                         "ephemeral port, printed)")
    ap.add_argument("--json", action="store_true",
                    help="print the raw record as JSON")
    args = ap.parse_args(argv)

    if args.telemetry_port is not None:
        from paddle_tpu.profiler import telemetry_server
        srv = telemetry_server.start(port=args.telemetry_port)
        print(f"serve_bench: telemetry server at {srv.url} "
              "(/metrics /goodput /doctor /healthz /readyz)",
              file=sys.stderr)

    import jax
    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    t0 = time.perf_counter()
    rec = run_serve_bench(args.streams, on_tpu,
                          max_new_tokens=args.max_new_tokens,
                          trace_dir=args.trace, kernel=args.kernel,
                          kv_dtype=args.kv_dtype,
                          prefix_cache=args.prefix_cache,
                          temperature=args.temperature,
                          top_k=args.top_k, top_p=args.top_p,
                          seed=args.seed, pipeline=args.pipeline)
    rec["elapsed_s"] = round(time.perf_counter() - t0, 1)
    if args.json:
        print(json.dumps(rec, indent=2))
    else:
        ex = rec["extra"]
        print(f"serve_bench: {args.streams} stream(s) on {rec['platform']} "
              f"[{ex['attention_kernel']}, kv {ex['kv_dtype']}] "
              f"-> {rec['value']} tok/s, p50 {ex['p50_step_ms']} ms, "
              f"p99 {ex['p99_step_ms']} ms, "
              f"ttft p50 {ex['ttft_p50_ms']} ms, "
              f"inter-token p50 {ex['inter_token_p50_ms']} ms, "
              f"occupancy {ex['occupancy_mean']} "
              f"(saturated {ex['occupancy_saturated']}), "
              f"decode_compiles {ex['decode_compiles']} (window), "
              f"evictions {ex['evictions']}, refused {ex['refused']}, "
              f"expired {ex['expired']}, hangs {ex['hangs']}")
        if ex["prefix_cache"]:
            print(f"prefix: hit_rate {ex['prefix_hit_rate']} "
                  f"({ex['prefix_hit_tokens']} tokens aliased), "
                  f"cow_copies {ex['cow_copies']}")
        sb = ex["sampling"]
        if args.temperature > 0 or args.pipeline:
            print(f"sampling: T={sb['temperature']} top_k={sb['top_k']} "
                  f"top_p={sb['top_p']} "
                  f"-> distinct {sb['distinct_frac']}, "
                  f"entropy {sb['entropy_norm']}, "
                  f"sampled_tokens {sb['sampled_tokens']}, "
                  f"pipelined {ex['pipeline']}, "
                  f"commit_rollbacks {ex['commit_rollbacks']}")
        print(f"doctor: {ex['fusion_doctor']['headline']}")
    return 0 if rec["extra"]["decode_compiles"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
