#!/usr/bin/env python
"""Fast perf guard for the compiled eager dispatch stack (PR 1 + PR 2).

Runs a tiny eager matmul→add→gelu→sum fwd+bwd loop on CPU and fails
(exit 1) when the dispatch telemetry shows either optimization silently
regressed:

  * post-warmup retraces — the per-op executable cache (ops/dispatch.py)
    must stop tracing after the first few iterations; any later trace means
    cache keying broke (a PR 1 regression);
  * zero chain-fusion replay rate with fusion enabled — the hot sequence
    must be detected and replayed as one fused executable (ops/fusion.py);
    a 0% replay rate means detection or replay broke (a PR 2 regression).

Runs in a few seconds; wired into tier-1 as the `perf_smoke`-marked tests
in tests/test_chain_fusion.py — this CLI is the same guard for CI scripts
and manual bisection:

    JAX_PLATFORMS=cpu python tools/perf_smoke.py
"""
from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# runnable from a source checkout without an install
sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

WARMUP = 12
MEASURE = 40


def main() -> int:
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.ops.dispatch import clear_dispatch_cache
    from paddle_tpu.profiler import chain_fusion_stats, dispatch_cache_stats

    set_flags({"FLAGS_eager_op_cache": True,
               "FLAGS_eager_chain_fusion": True,
               # fuse within the short warmup (the default threshold is
               # sized for training loops, not a 52-iteration smoke)
               "FLAGS_eager_chain_fusion_min_count": 4})
    clear_dispatch_cache()

    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((16, 32)).astype(np.float32))
    w = paddle.to_tensor(rng.standard_normal((32, 32)).astype(np.float32),
                         stop_gradient=False)
    b = paddle.to_tensor(rng.standard_normal(32).astype(np.float32),
                         stop_gradient=False)

    def step():
        y = F.gelu(paddle.add(paddle.matmul(x, w), b))
        loss = y.sum()
        loss.backward()
        w.clear_grad()
        b.clear_grad()

    for _ in range(WARMUP):
        step()
    d0 = dispatch_cache_stats()
    c0 = chain_fusion_stats()
    for _ in range(MEASURE):
        step()
    d1 = dispatch_cache_stats()
    c1 = chain_fusion_stats()

    failures = []
    retraces = (d1["retraces"] - d0["retraces"]) \
        + (c1["retraces"] - c0["retraces"])
    if retraces:
        failures.append(
            f"{retraces} post-warmup retrace(s): the executable cache is "
            "re-tracing a hot loop (PR 1 regression)")
    attempts = (c1["fused_replays"] - c0["fused_replays"]) \
        + (c1["fallback_splits"] - c0["fallback_splits"])
    replays = c1["fused_replays"] - c0["fused_replays"]
    if replays == 0:
        failures.append(
            "chain-fusion replay rate is zero with fusion enabled "
            f"(attempts={attempts}, detected={c1['chains_detected']}): the "
            "hot sequence is not being fused (PR 2 regression)")

    print(f"perf_smoke: post-warmup retraces={retraces}, "
          f"fused replays={replays}/{MEASURE} iterations, "
          f"launches_saved={c1['launches_saved'] - c0['launches_saved']}")
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    print("perf_smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
