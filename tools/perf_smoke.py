#!/usr/bin/env python
"""Fast perf guard for the compiled eager dispatch stack (PR 1 + 2 + 3).

Runs a tiny eager matmul→add→gelu→sum fwd+bwd+SGD loop on CPU and fails
(exit 1) when the dispatch telemetry shows any layer of the optimization
stack silently regressed:

  * post-warmup retraces — the per-op executable cache (ops/dispatch.py)
    must stop tracing after the first few iterations; any later trace means
    cache keying broke (a PR 1 regression);
  * zero chain-fusion replay rate with fusion enabled — the hot sequence
    must be detected and replayed as one fused executable (ops/fusion.py);
    a 0% replay rate means detection or replay broke (a PR 2 regression);
  * zero whole-step fusion replays, a post-warmup step retrace, or a
    fused-step speedup below the guard — the stable fwd+bwd+optimizer
    cycle must be promoted to ONE fused executable (ops/step_fusion.py)
    and beat the chain-fusion path (a PR 3 regression);
  * unexplained splits — with the fusion flight recorder armed
    (FLAGS_profiler_events), every chain.split/step.split event must
    carry a known reason code, and the steady-state loop must report
    ZERO splits (a PR 4 attribution regression);
  * events-off overhead — the recorder's disabled path (one flag check
    per emission site) must cost <3% of a fused step at the observed
    events-per-step rate (a PR 4 hot-path regression);
  * guardian overhead — FLAGS_check_numerics compiles its finite checks
    INTO the fused executables (one scalar per launch, one batched sync
    per step), so the guarded fused loop must stay within 5% of the
    unguarded one AND keep replaying fused (a PR 5 regression);
  * AMP promotion — a dynamic-loss-scaled GradScaler loop under the
    guardian must reach whole-step zero-retrace steady state (scale and
    growth-tracker ride as hoisted scalar args; promotion is no longer
    poisoned by the mid-step grad read — a PR 5 regression);
  * serving decode zero-retrace + occupancy — 64 mixed-length streams
    churning through a 4-slot continuous batch (paddle_tpu/serving) must
    compile the decode executable exactly ONCE, and saturated batch
    occupancy must stay >= 0.75 — the paged KV cache + slot layout keep
    every tenant mix on one program (a PR 6 regression);
  * serving resilience cost + churn — with the hung-step watchdog and
    per-request deadlines ARMED, the serve_8-style loop must stay under
    2x the disarmed engine on best-window-vs-best-window (the monitored
    completion's spin-poll must never sleep or sync on a healthy step —
    that regression class multiplies the window), and the decode executable must
    STILL compile exactly once while requests are cancelled, expired,
    refused, and crash-resumed around it — resilience is value edits to
    the fixed slot layout, never shapes (a PR 7 regression);
  * AOT warm start — a fresh subprocess against a WARM persistent
    executable store (FLAGS_aot_cache, ops/aot_cache.py) must reach a
    promoted fused step with ZERO compile activity (no dispatch
    retraces, no chain compiles, no whole-step retrace — everything
    deserializes) and measurably faster time-to-first-promoted-step
    than the cold subprocess that populated the store (a PR 9
    regression);
  * kernel tier — blockwise paged decode attention (online softmax
    streamed over the KV block table) must beat the dense [S, T, H, D]
    gather at seq >= 1k on the serve-shaped CPU microbench, and a
    serving engine with the int8 KV cache must still compile its decode
    step exactly once under stream churn (a PR 11 regression);
  * telemetry plane — the metrics registry (profiler/metrics.py) must
    record NOTHING with FLAGS_metrics off at one-flag-check cost
    (<3%/step at the observed sites-per-step rate), stay within 5%/step
    armed on BOTH the fused train loop and the serve_8 workload
    (interleaved min-of-ratios), and its histogram hot path must never
    grow memory with observations (a PR 12 regression);
  * telemetry server — the live HTTP observability plane
    (profiler/telemetry_server.py) must cost one module-bool check per
    heartbeat site with no server running (<3%/step, nothing recorded),
    and with the server armed plus a scraper hitting /metrics +
    /healthz every 100 ms, the fused train loop and the serve_8
    workload must stay within 5%/step while every scrape is answered
    (a PR 13 regression);
  * distributed step fusion — a dp=N sharded-batch loop over the
    emulated device mesh must auto-promote into ONE shard_map-wrapped
    executable (ops/spmd_fusion.py; zero retraces after promotion) and
    beat the same loop on unfused eager dispatch (per-op GSPMD
    collectives) by >= 1.3x (a PR 10 regression);
  * multi-tenant serving — 64 streams over 8 tenants (shared system
    prompt through the prefix cache, batched LoRA adapter slots, one
    live weight hot-swap landing mid-run) must keep the decode
    executable at exactly ONE compile — adapter churn and the swap are
    VALUE edits to fixed shapes — and the steady-state prefix-hit
    prefill must beat the cold prefill by >= 3x on interleaved
    min-of-ratios (a PR 17 regression).

Runs in a few seconds; wired into tier-1 as the `perf_smoke`-marked tests
in tests/test_chain_fusion.py and tests/test_step_fusion.py — this CLI is
the same guard for CI scripts and manual bisection:

    JAX_PLATFORMS=cpu python tools/perf_smoke.py
"""
from __future__ import annotations

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# runnable from a source checkout without an install
sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

WARMUP = 14
MEASURE = 40
# promoted DP step vs unfused eager collectives (ops/spmd_fusion.py)
DP_SPEEDUP_GUARD = 1.3
# promoted pp pipeline cycle (ops/spmd_fusion.py pipeline registry) vs the
# unfused eager schedule (forward_backward_pipeline: sequential micro-batch
# accumulation, per-op dispatch). Same bound as the pytest acceptance
# (1.3x) — the whole fill/steady/drain cycle fusing into one executable is
# worth an order of magnitude even on a loaded box, so no CLI loosening
PP_SPEEDUP_GUARD = 1.3
# warm-start guard: a warm store must reach the first PROMOTED FUSED step
# in at most this fraction of the cold process's time-to-first-fire (the
# cold path pays per-op traces + the whole-step trace + XLA compiles; the
# warm path only deserializes) — loose enough for loaded CI boxes, tight
# enough that "the store stopped eliminating the warmup" fails loudly
AOT_WARM_RATIO_GUARD = 0.85
# CLI guard is looser than the pytest acceptance bound (1.3x): the smoke
# must stay green on loaded CI boxes while still catching a real loss of
# whole-step fusion (which is worth ~1.9x on an idle machine)
STEP_SPEEDUP_GUARD = 1.15
# steady-state prefix-hit prefill vs cold prefill on the shared-prefix
# serve workload (serving/tenancy.py): aliasing every full block of the
# shared prompt turns a whole-prompt prefill into a short tail prefill,
# worth far more than 3x even on a loaded box
PREFIX_SPEEDUP_GUARD = 3.0
# sampled decode vs greedy decode per step (serving/sampling.py): the
# sampler head (one shared sort + gumbel) must stay a rounding error next
# to the transformer forward, so the guard runs on a forward-dominated
# model (hidden 640) where the head's fixed cost cannot hide a regression
# behind model FLOPs it doesn't have
SAMPLED_OVERHEAD_GUARD = 0.05
# lag-1 pipelined decode vs unpipelined on the serve_8 workload whose
# per-token commit blocks the host (a stream-write stand-in): the pipeline
# overlaps host WAIT with device compute — on a 1-core CI box CPU-bound
# host work cannot overlap anything, but blocked-host time (client
# sockets, log fsync) can, and on a real accelerator ALL host work can.
# If the launch path ever re-synchronizes (dispatch blocking on the
# in-flight step), both sides degenerate to D+H and the ratio collapses
PIPELINE_SPEEDUP_GUARD = 1.15


def _loop(step_fused, check_numerics=False, use_scaler=False):
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.ops.dispatch import clear_dispatch_cache

    set_flags({"FLAGS_eager_op_cache": True,
               "FLAGS_eager_chain_fusion": True,
               # fuse within the short warmup (the default thresholds are
               # sized for training loops, not a 54-iteration smoke)
               "FLAGS_eager_chain_fusion_min_count": 4,
               "FLAGS_eager_step_fusion": step_fused,
               "FLAGS_eager_step_fusion_min_count": 5,
               "FLAGS_check_numerics": check_numerics})
    clear_dispatch_cache()

    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((16, 32)).astype(np.float32))
    w = paddle.to_tensor(rng.standard_normal((32, 32)).astype(np.float32),
                         stop_gradient=False)
    b = paddle.to_tensor(rng.standard_normal(32).astype(np.float32),
                         stop_gradient=False)
    opt = paddle.optimizer.SGD(learning_rate=1e-3, parameters=[w, b])
    scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0) \
        if use_scaler else None

    def step():
        y = F.gelu(paddle.add(paddle.matmul(x, w), b))
        loss = y.sum()
        if scaler is None:
            loss.backward()
            opt.step()
        else:
            scaler.scale(loss).backward()
            scaler.step(opt)
            scaler.update()
        opt.clear_grad()

    def sync():
        # drain the async dispatch queue (measurement-boundary hygiene:
        # without it, one leg's enqueued-but-unexecuted work bleeds into
        # the next leg's timed window)
        w._value.block_until_ready()

    step.sync = sync
    return step


def _dp_loop(step_fused):
    """A dp=N data-parallel MLP loop: batch sharded over a mesh spanning
    every device (8 emulated on CPU via tests/conftest-style XLA flags).
    With step fusion on, the cycle must promote through the SPMD lowering
    (ops/spmd_fusion.py) — ONE shard_map executable per step."""
    import numpy as np
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.distributed.mesh import build_mesh, set_global_mesh
    from paddle_tpu.ops.dispatch import clear_dispatch_cache

    set_flags({"FLAGS_eager_op_cache": True,
               "FLAGS_eager_chain_fusion": True,
               "FLAGS_eager_chain_fusion_min_count": 4,
               "FLAGS_eager_step_fusion": step_fused,
               "FLAGS_eager_step_fusion_min_count": 5,
               "FLAGS_check_numerics": False})
    clear_dispatch_cache()

    n = jax.device_count()
    mesh = build_mesh(dp=n, pp=1, sharding=1, sep=1, mp=1)
    set_global_mesh(mesh)
    sharding = NamedSharding(mesh, P("data"))
    rng = np.random.default_rng(0)
    x = paddle.Tensor(jax.device_put(
        rng.standard_normal((8 * n, 32)).astype(np.float32), sharding),
        stop_gradient=True)
    y = paddle.Tensor(jax.device_put(
        rng.standard_normal((8 * n, 16)).astype(np.float32), sharding),
        stop_gradient=True)
    w1 = paddle.to_tensor(
        (rng.standard_normal((32, 64)) * 0.1).astype(np.float32),
        stop_gradient=False)
    b1 = paddle.to_tensor(np.zeros(64, np.float32), stop_gradient=False)
    w2 = paddle.to_tensor(
        (rng.standard_normal((64, 16)) * 0.1).astype(np.float32),
        stop_gradient=False)
    opt = paddle.optimizer.Momentum(learning_rate=1e-3, momentum=0.9,
                                    parameters=[w1, b1, w2])

    def step():
        h = F.relu(paddle.add(paddle.matmul(x, w1), b1))
        out = paddle.matmul(h, w2)
        diff = paddle.subtract(out, y)
        loss = paddle.mean(paddle.multiply(diff, diff))
        loss.backward()
        opt.step()
        opt.clear_grad()

    def sync():
        w1._value.block_until_ready()

    step.sync = sync
    return step


def aot_child_main(aot_dir, out_path, steps=12) -> int:
    """Warm-start measurement child (`perf_smoke.py --aot-child`): a tiny
    fwd+bwd+SGD loop with the AOT executable store armed. Reports the
    wall time from loop start to the FIRST fused whole-step fire plus the
    compile/AOT counters — the parent runs it once cold (empty store) and
    again warm (populated store) and guards the ratio. Shared with
    tests/test_aot_cache.py so the pytest guard and this CLI can never
    drift."""
    import json
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.profiler import (dispatch_cache_stats,
                                     chain_fusion_stats,
                                     step_fusion_stats, aot_cache_stats)

    set_flags({"FLAGS_aot_cache": True,
               "FLAGS_aot_cache_dir": aot_dir,
               "FLAGS_eager_chain_fusion_min_count": 3,
               "FLAGS_eager_step_fusion_min_count": 5})
    paddle.seed(0)
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((16, 32)).astype(np.float32))
    w = paddle.to_tensor(rng.standard_normal((32, 32)).astype(np.float32),
                         stop_gradient=False)
    b = paddle.to_tensor(rng.standard_normal(32).astype(np.float32),
                         stop_gradient=False)
    opt = paddle.optimizer.SGD(learning_rate=1e-3, parameters=[w, b])
    opt.clear_grad()        # steady-state cycle signature from cycle 1
    t0 = time.perf_counter()
    t_first_fire = None
    for _ in range(steps):
        loss = F.gelu(paddle.add(paddle.matmul(x, w), b)).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
        if t_first_fire is None \
                and step_fusion_stats()["fused_steps"] > 0:
            t_first_fire = time.perf_counter() - t0
    report = {
        "t_first_fire_s": t_first_fire,
        "dispatch_retraces": dispatch_cache_stats()["retraces"],
        "chain_retraces": chain_fusion_stats()["retraces"],
        "step_retraces": step_fusion_stats()["retraces"],
        "steps_promoted": step_fusion_stats()["steps_promoted"],
        "fused_steps": step_fusion_stats()["fused_steps"],
        "aot": aot_cache_stats(),
    }
    with open(out_path, "w") as f:
        json.dump(report, f)
    return 0


def _aot_warm_start_leg(failures):
    """Leg (h), PR 9: a fresh subprocess against a WARM store must reach
    a promoted fused step with zero compile activity — no dispatch
    retraces, no chain compiles, no whole-step retrace — and measurably
    faster than the cold subprocess that populated the store (min over
    two warm runs, same best-window hygiene as the guardian leg)."""
    import json
    import subprocess
    import tempfile

    def run(aot_dir, out):
        cmd = [sys.executable, os.path.abspath(__file__), "--aot-child",
               "--aot-dir", aot_dir, "--out", out]
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=300, env=env)
        if r.returncode != 0:
            raise RuntimeError(f"aot child failed: {r.stderr[-800:]}")
        with open(out) as f:
            rep = json.load(f)
        if rep["t_first_fire_s"] is None:
            # a child that never fired must FAIL the guard below, not
            # crash the ratio math / report formatting with a TypeError
            rep["t_first_fire_s"] = float("nan")
        return rep

    with tempfile.TemporaryDirectory() as tmp:
        store = os.path.join(tmp, "store")
        cold = run(store, os.path.join(tmp, "cold.json"))
        warms = [run(store, os.path.join(tmp, f"warm{i}.json"))
                 for i in range(2)]
    warm = min(warms, key=lambda r: r["t_first_fire_s"] or 1e9)
    if cold["fused_steps"] == 0 or cold["aot"]["stores"] == 0:
        failures.append(
            "cold AOT child never promoted/stored — the warm-start leg "
            "has nothing to measure (PR 9 guard bug)")
        return cold, warm
    for r in warms:
        if r["fused_steps"] == 0:
            failures.append("warm AOT child never fired a fused step "
                            "(PR 9 regression)")
        for k in ("dispatch_retraces", "chain_retraces", "step_retraces"):
            if r[k] != 0:
                failures.append(
                    f"warm AOT child paid {r[k]} {k}: the store stopped "
                    "eliminating the warmup (PR 9 regression)")
        if r["aot"]["hits"] == 0:
            failures.append("warm AOT child loaded no artifacts "
                            "(PR 9 regression)")
    ratio = warm["t_first_fire_s"] / cold["t_first_fire_s"] \
        if cold["t_first_fire_s"] else float("inf")
    if ratio >= AOT_WARM_RATIO_GUARD:
        failures.append(
            f"warm-store time-to-first-promoted-step is {ratio:.2f}x the "
            f"cold run ({warm['t_first_fire_s']:.2f}s vs "
            f"{cold['t_first_fire_s']:.2f}s, guard "
            f"{AOT_WARM_RATIO_GUARD}): the AOT store lost its win "
            "(PR 9 regression)")
    return cold, warm


def main() -> int:
    from paddle_tpu.profiler import (chain_fusion_stats,
                                     dispatch_cache_stats,
                                     step_fusion_stats)

    def timed(step):
        """Best-of-3 measurement windows: single-shot wall times on a
        loaded CI box swing 2-3x; the best window is the signal."""
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(MEASURE):
                step()
            best = min(best, (time.perf_counter() - t0) / MEASURE)
        return best

    # ---- chain-fusion leg (step fusion off, PR 1 + PR 2 guards) ----------
    step = _loop(step_fused=False)
    for _ in range(WARMUP):
        step()
    d0, c0 = dispatch_cache_stats(), chain_fusion_stats()
    t_chain = timed(step)
    d1, c1 = dispatch_cache_stats(), chain_fusion_stats()

    failures = []
    retraces = (d1["retraces"] - d0["retraces"]) \
        + (c1["retraces"] - c0["retraces"])
    if retraces:
        failures.append(
            f"{retraces} post-warmup retrace(s): the executable cache is "
            "re-tracing a hot loop (PR 1 regression)")
    chain_replays = c1["fused_replays"] - c0["fused_replays"]
    chain_replays = min(chain_replays, MEASURE)   # 3 timed windows ran
    if chain_replays == 0:
        failures.append(
            "chain-fusion replay rate is zero with fusion enabled "
            f"(detected={c1['chains_detected']}): the hot sequence is not "
            "being fused (PR 2 regression)")

    # ---- whole-step fusion leg (PR 3 guards) -----------------------------
    step = _loop(step_fused=True)
    for _ in range(WARMUP):
        step()
    s0 = step_fusion_stats()
    t_step = timed(step)
    s1 = step_fusion_stats()

    step_replays = min(s1["fused_steps"] - s0["fused_steps"], MEASURE)
    step_retraces = s1["retraces"] - s0["retraces"]
    if step_replays == 0:
        failures.append(
            "whole-step fusion replay rate is zero with the flag enabled "
            f"(promoted={s1['steps_promoted']}, "
            f"splits={s1['fallback_splits']}): the stable cycle is not "
            "being promoted (PR 3 regression)")
    if step_retraces:
        failures.append(
            f"{step_retraces} post-warmup whole-step retrace(s): the step "
            "executable is re-tracing a stable cycle (PR 3 regression)")
    speedup = t_chain / t_step if t_step > 0 else 0.0
    if step_replays and speedup < STEP_SPEEDUP_GUARD:
        failures.append(
            f"whole-step fusion speedup {speedup:.2f}x is below the "
            f"{STEP_SPEEDUP_GUARD}x guard (chain {t_chain*1e6:.0f}us vs "
            f"fused step {t_step*1e6:.0f}us): the fused path lost its win "
            "(PR 3 regression)")

    # ---- flight-recorder legs (PR 4 guards) ------------------------------
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.profiler.events import (EVENTS, REASON_CODES,
                                            clear_fusion_events)

    # (a) no unexplained splits + steady-state zero splits: re-run the
    # fused loop with the recorder armed; warmup splits must all carry a
    # known reason code and the measured window must contain none at all
    step = _loop(step_fused=True)
    clear_fusion_events()
    set_flags({"FLAGS_profiler_events": True})
    for _ in range(WARMUP):
        step()
    steady_seq = EVENTS.total
    for _ in range(MEASURE):
        step()
    set_flags({"FLAGS_profiler_events": False})
    split_events = [e for e in EVENTS.snapshot()
                    if e["cat"] in ("chain.split", "step.split")]
    unexplained = [e for e in split_events
                   if e["reason"] not in REASON_CODES]
    if unexplained:
        failures.append(
            f"{len(unexplained)} split event(s) without a known reason "
            f"code (first: {unexplained[0]}): split attribution broke "
            "(PR 4 regression)")
    steady_splits = [e for e in split_events if e["seq"] > steady_seq]
    if steady_splits:
        failures.append(
            f"{len(steady_splits)} steady-state split(s) in the smoke "
            f"loop (first: {steady_splits[0]['cat']}:"
            f"{steady_splits[0]['reason']}): the stable cycle should "
            "replay without splitting (PR 4 regression)")
    events_per_step = (EVENTS.total - steady_seq) / MEASURE
    clear_fusion_events()

    # (b) events-off overhead: the disabled emit path is one flag check;
    # at the observed events-per-step rate its total cost must stay <3%
    # of a fused step (timing the loop against a never-instrumented
    # binary is impossible in-process, so guard the unit cost directly)
    N_EMIT = 200_000
    t0 = time.perf_counter()
    for _ in range(N_EMIT):
        EVENTS.emit("dispatch.hit", "x")
    emit_off_ns = (time.perf_counter() - t0) / N_EMIT * 1e9
    if len(EVENTS):
        failures.append(
            f"{len(EVENTS)} event(s) recorded with FLAGS_profiler_events "
            "off: the gate is broken (PR 4 regression)")
    overhead_frac = emit_off_ns * events_per_step / max(t_step * 1e9, 1.0)
    if overhead_frac >= 0.03:
        failures.append(
            f"events-off emit cost {emit_off_ns:.0f}ns x "
            f"{events_per_step:.1f} events/step is "
            f"{overhead_frac * 100:.2f}% of a fused step (>=3%): the "
            "disabled path got expensive (PR 4 regression)")

    # ---- guardian legs (PR 5 guards) -------------------------------------
    # (c) FLAGS_check_numerics cost: the checks compile INTO the fused
    # executables, so the guarded loop must stay within 5% of the
    # unguarded fused step (and must still replay fused at all). The
    # the baseline and the guarded loop are measured in INTERLEAVED
    # windows (flag flipped per window — each loop's promoted program
    # re-arms from the per-thread library without retracing) and compared
    # on best-window times: a load spike hits both legs alike instead of
    # faking (or masking) a few-percent regression. The earlier t_step is
    # minutes old by now; process drift dwarfs the effect guarded here.
    base_step = _loop(step_fused=True)
    for _ in range(WARMUP):
        base_step()
    step = _loop(step_fused=True, check_numerics=True)
    for _ in range(WARMUP):
        step()
    # _loop() above cleared the caches, so the base leg's promoted program
    # is gone: re-warm it or window 0's baseline pays full re-record +
    # re-promote + XLA compile, its ratio craters, and min-of-ratios would
    # wave through ANY real guardian regression
    set_flags({"FLAGS_check_numerics": False})
    for _ in range(WARMUP):
        base_step()
    # the guard statistic is the MIN over paired window ratios: a real
    # guardian regression (an added per-step sync costs 2x+) inflates
    # EVERY pair, while a CI-box load spike only inflates the pairs it
    # lands on — so min-of-ratios tracks the true marginal cost even when
    # single-window times swing 2-3x
    ratios = []
    t_base = t_guard = float("inf")
    for _ in range(6):
        set_flags({"FLAGS_check_numerics": False})
        base_step.sync()
        t0 = time.perf_counter()
        for _ in range(MEASURE):
            base_step()
        base_step.sync()
        tb = (time.perf_counter() - t0) / MEASURE
        set_flags({"FLAGS_check_numerics": True})
        step.sync()
        t0 = time.perf_counter()
        for _ in range(MEASURE):
            step()
        step.sync()
        tg = (time.perf_counter() - t0) / MEASURE
        t_base, t_guard = min(t_base, tb), min(t_guard, tg)
        ratios.append(tg / tb if tb > 0 else float("inf"))
    # (flag is still on) the guarded loop must actually be REPLAYING fused
    g0 = step_fusion_stats()
    for _ in range(8):
        step()
    g1 = step_fusion_stats()
    if g1["fused_steps"] - g0["fused_steps"] == 0:
        failures.append(
            "whole-step fusion stopped replaying under "
            "FLAGS_check_numerics: the guardian un-fused the loop "
            "(PR 5 regression)")
    guard_overhead = min(ratios) - 1.0
    guard_median = sorted(ratios)[len(ratios) // 2] - 1.0
    if guard_overhead >= 0.05:
        failures.append(
            f"FLAGS_check_numerics costs {guard_overhead * 100:.1f}%/step "
            f"(best guarded window {t_guard * 1e6:.0f}us vs base "
            f"{t_base * 1e6:.0f}us, >=5%): the in-graph checks stopped "
            "amortizing (PR 5 regression)")

    # (d) dynamic-loss-scaled AMP promotion: scale/growth-tracker ride as
    # hoisted args, unscale/found-inf/backoff fold into the ONE fused
    # executable — the GradScaler loop must reach zero-retrace steady
    # state instead of splitting on the mid-step grad read
    step = _loop(step_fused=True, check_numerics=True, use_scaler=True)
    for _ in range(WARMUP):
        step()
    a0 = step_fusion_stats()
    for _ in range(MEASURE):
        step()
    a1 = step_fusion_stats()
    amp_replays = min(a1["fused_steps"] - a0["fused_steps"], MEASURE)
    amp_retraces = a1["retraces"] - a0["retraces"]
    if amp_replays == 0:
        failures.append(
            "GradScaler AMP loop did not promote under the guardian "
            f"(promoted={a1['steps_promoted']}, "
            f"splits={a1['fallback_splits']}): scaled training lost "
            "whole-step fusion (PR 5 regression)")
    if amp_retraces:
        failures.append(
            f"{amp_retraces} post-warmup retrace(s) in the guarded AMP "
            "loop: the scaler state is no longer a hoisted arg "
            "(PR 5 regression)")
    # legs (c)/(d) armed the guardian and the eager fusion tiers; the
    # serving legs below measure the ENGINE (its decode/prefill programs
    # are compiled outside the eager tiers) — leaked per-launch
    # finite-check syncs and chain/step-fusion detection bookkeeping on
    # the engine's host-side ops would turn leg (f)'s watchdog ratio
    # into a measurement of guardian + detector jitter instead
    set_flags({"FLAGS_check_numerics": False,
               "FLAGS_eager_chain_fusion": False,
               "FLAGS_eager_step_fusion": False})

    # ---- serving legs (PR 6 guards) --------------------------------------
    # (e) 64 mixed-length streams churn through a 4-slot continuous
    # batch: requests join/leave at token boundaries, yet the decode
    # executable must compile exactly once (slot layout + paged block
    # tables keep shapes fixed), and saturated occupancy must stay
    # >= 0.75 (continuous batching actually packs freed slots)
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.incubate.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import LLMEngine

    paddle.seed(0)
    scfg = GPTConfig(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                     num_attention_heads=4, intermediate_size=64,
                     max_position_embeddings=64, hidden_dropout_prob=0.0,
                     attention_probs_dropout_prob=0.0,
                     use_flash_attention=False)
    smodel = GPTForCausalLM(scfg)
    smodel.eval()
    engine = LLMEngine(smodel, max_batch_size=4, block_size=4)
    srng = np.random.default_rng(0)
    sprompts = [srng.integers(0, 128, int(n)).tolist()
                for n in srng.integers(3, 20, 64)]
    engine.generate(sprompts, max_new_tokens=6)
    sstats = engine.stats()
    if sstats["decode_compiles"] != 1:
        failures.append(
            f"serving decode compiled {sstats['decode_compiles']}x across "
            "64 churning streams (must be exactly 1): batch composition "
            "leaked into the decode shapes (PR 6 regression)")
    if sstats["occupancy_saturated"] < 0.75:
        failures.append(
            f"saturated batch occupancy {sstats['occupancy_saturated']:.2f} "
            "< 0.75 with 64 streams over 4 slots: continuous batching is "
            "not refilling freed slots (PR 6 regression)")

    # ---- serving resilience legs (PR 7 guards) ---------------------------
    # (f) watchdog + deadline checks armed must stay cheap on a healthy
    # engine: interleaved disarmed/armed windows over the serve_8-style
    # workload, compared best-window vs best-window (the timed() best-of
    # statistic — each side's min discards the windows a load spike or a
    # GC pause landed on; paired ratios proved bistable on a 3 ms window
    # where the armed poll loop contends with XLA's own compute threads).
    # The bound is a 2x catastrophe guard, not a few-percent one: the
    # armed yield-poll's cost on a ~0.3 ms CPU decode step swings tens
    # of percent with process-wide thread pressure even on healthy code,
    # while the regression class this leg exists to catch — the monitor
    # falling into its millisecond coarse-sleep rung (or an extra device
    # sync) on every healthy step — multiplies the window several-fold
    sprompts8 = [srng.integers(0, 128, int(n)).tolist()
                 for n in srng.integers(3, 20, 8)]
    rengine = LLMEngine(smodel, max_batch_size=4, block_size=4)
    rengine.generate(sprompts8, max_new_tokens=6)          # warm programs

    def serve_window(ttl):
        for p in sprompts8:
            rengine.add_request(p, max_new_tokens=6, ttl_s=ttl)
        rengine.run()

    t_serve_off = t_serve_on = float("inf")
    for _ in range(6):
        set_flags({"FLAGS_serve_step_timeout_ms": 0})
        t0 = time.perf_counter()
        serve_window(None)
        t_serve_off = min(t_serve_off, time.perf_counter() - t0)
        set_flags({"FLAGS_serve_step_timeout_ms": 5000})
        t0 = time.perf_counter()
        serve_window(60.0)
        t_serve_on = min(t_serve_on, time.perf_counter() - t0)
    set_flags({"FLAGS_serve_step_timeout_ms": 0})
    resil_overhead = (t_serve_on / t_serve_off - 1.0) if t_serve_off > 0 \
        else float("inf")
    if resil_overhead >= 1.0:
        failures.append(
            f"armed watchdog + deadlines cost "
            f"{resil_overhead * 100:.1f}%/step on the serve_8 loop "
            f"(best armed window {t_serve_on * 1e3:.1f}ms vs disarmed "
            f"{t_serve_off * 1e3:.1f}ms, >=100%): the monitored "
            "completion is sleeping or syncing on healthy steps "
            "(PR 7 regression)")
    if rengine.stats()["decode_compiles"] != 1:
        failures.append(
            "the resilience timing windows retraced the decode program "
            "(PR 7 regression)")

    # (g) decode compiles exactly once while requests are cancelled,
    # expired, refused, and crash-resumed around the running batch
    from paddle_tpu.serving import ServeRefusal
    churn = LLMEngine(smodel, max_batch_size=4, block_size=4,
                      max_queue_depth=6)
    churn.generate(sprompts8[:4], max_new_tokens=4)        # warm programs
    churn.reset_stats()
    set_flags({"FLAGS_serve_step_timeout_ms": 5000})
    try:
        live = [churn.add_request(p, max_new_tokens=6)
                for p in sprompts8[:4]]
        doomed = churn.add_request(sprompts8[4], max_new_tokens=6,
                                   ttl_s=60.0)
        # deterministic queued-expiry: rewind the deadline instead of
        # racing a tiny TTL against the admission-time feasibility check
        doomed.deadline_ns = 0
        refused = 0
        try:
            for _ in range(16):
                churn.add_request(sprompts8[5], max_new_tokens=6)
        except ServeRefusal:
            refused = 1
        for _ in range(2):
            churn.step()
        churn.cancel(live[0].rid)
        mid = churn.state_payload()                        # live streams
        churn.run()
        # resume: re-admit a mid-flight snapshot (ids are free again)
        resumed = churn.restore_state(mid)
        churn.run()
    finally:
        set_flags({"FLAGS_serve_step_timeout_ms": 0})
    cstats = churn.stats()
    if cstats["decode_compiles"] != 0:
        failures.append(
            f"decode retraced {cstats['decode_compiles']}x under "
            "cancel/expire/refuse/resume churn — resilience edits leaked "
            "into the compiled shapes (PR 7 regression)")
    if not (refused and cstats["cancelled"] >= 1
            and cstats["expired"] >= 1 and len(resumed) >= 1):
        failures.append(
            f"churn leg did not exercise every lifecycle edge "
            f"(refused={refused}, cancelled={cstats['cancelled']}, "
            f"expired={cstats['expired']}, resumed={len(resumed)}) "
            "(PR 7 guard bug)")

    # ---- kernel tier legs (PR 11 guards) ---------------------------------
    # (j) blockwise paged decode attention (online softmax over the block
    # table, kernels/pallas/paged_attention.py) must beat the dense
    # [S, T, H, D] gather at seq >= 1k on the serve-shaped CPU
    # microbench — the whole point of the kernel tier is that the dense
    # context never materializes — and an int8-KV engine must still
    # compile its decode step exactly ONCE under stream churn (the
    # scale side-tables are value edits, never shapes)
    import jax
    import jax.numpy as jnp
    from paddle_tpu.nn.functional.attention import paged_decode_attention

    KS, KH, KD, KBS, KM = 8, 4, 32, 16, 64         # seq = 1024
    knb = KS * KM + 1
    krng = np.random.default_rng(2)
    kmk = lambda sh: jnp.asarray(krng.standard_normal(sh).astype(np.float32))
    kq, kkn, kvn = kmk((KS, 1, KH, KD)), kmk((KS, 1, KH, KD)), \
        kmk((KS, 1, KH, KD))
    kkp, kvp = kmk((knb, KBS, KH, KD)), kmk((knb, KBS, KH, KD))
    ktables = jnp.asarray(np.stack(
        [1 + i * KM + np.arange(KM) for i in range(KS)]).astype(np.int32))
    klens = jnp.full((KS,), KM * KBS - KBS, jnp.int32)
    kactive = jnp.ones((KS,), bool)

    def _paged_fn(kernel):
        @jax.jit
        def f(q, kn, vn, kp, vp):
            return paged_decode_attention(q, kn, vn, kp, vp, ktables,
                                          klens, kactive, KBS,
                                          kernel=kernel)[0]
        f(kq, kkn, kvn, kkp, kvp).block_until_ready()
        return f

    def _paged_window(f, iters=10):
        t0 = time.perf_counter()
        for _ in range(iters):
            f(kq, kkn, kvn, kkp, kvp).block_until_ready()
        return (time.perf_counter() - t0) / iters

    f_dense, f_block = _paged_fn("reference"), _paged_fn("blockwise")
    # INTERLEAVED paired windows, guard on the MAX ratio: a real loss of
    # the streaming win deflates EVERY pair, while a CI-box load spike
    # only hits the pairs it lands on (the same statistic the guardian/
    # resilience overhead legs use, mirrored for a >= floor)
    kratios, kt_dense, kt_block = [], float("inf"), float("inf")
    for _ in range(6):
        tdw = _paged_window(f_dense)
        tbw = _paged_window(f_block)
        kt_dense, kt_block = min(kt_dense, tdw), min(kt_block, tbw)
        kratios.append(tdw / tbw if tbw > 0 else 0.0)
    paged_speedup = max(kratios)
    if paged_speedup < 1.0:
        failures.append(
            f"blockwise paged attention never beat the dense gather at "
            f"seq 1k across {len(kratios)} paired windows (best ratio "
            f"{paged_speedup:.2f}x; dense {kt_dense * 1e3:.2f}ms vs "
            f"blockwise {kt_block * 1e3:.2f}ms): the kernel tier lost "
            "its win (PR 11 regression)")

    int8_engine = LLMEngine(smodel, max_batch_size=4, block_size=4,
                            kv_dtype="int8")
    int8_engine.generate(sprompts[:16], max_new_tokens=6)
    int8_stats = int8_engine.stats()
    if int8_stats["decode_compiles"] != 1:
        failures.append(
            f"int8-KV decode compiled {int8_stats['decode_compiles']}x "
            "across 16 churning streams (must be exactly 1): the scale "
            "side-tables leaked into the compiled shapes "
            "(PR 11 regression)")

    # ---- telemetry plane legs (PR 12 guards) -----------------------------
    # (k) the metrics registry must honor the flight recorder's cost
    # discipline: with FLAGS_metrics OFF every site is one flag check
    # (<3%/step at the observed sites-per-step rate, and NOTHING is
    # recorded); with it ON, the fused train loop and the serve_8-style
    # workload must stay within 5%/step (interleaved min-of-paired-ratio
    # windows, the guardian leg's statistic); and the histogram hot path
    # must not grow memory with observations (bounded bucket bands)
    from paddle_tpu.profiler import metrics as _pm

    _pm.reset_metrics()
    mh = _pm.TRAIN.step_s
    mc = _pm.SERVE.tokens
    N_OBS = 100_000
    t0 = time.perf_counter()
    for _ in range(N_OBS):
        mh.observe(0.001)
        mc.inc()
    obs_off_ns = (time.perf_counter() - t0) / (2 * N_OBS) * 1e9
    if mh.count != 0 or mc.value != 0:
        failures.append(
            f"metrics recorded with FLAGS_metrics off (hist count="
            f"{mh.count}, counter={mc.value}): the gate is broken "
            "(PR 12 regression)")
    # ~6 instrumented sites fire per fused train step (boundary + step
    # hist + gauges); be generous and budget 10
    m_overhead_off = obs_off_ns * 10 / max(t_step * 1e9, 1.0)
    if m_overhead_off >= 0.03:
        failures.append(
            f"metrics-off site cost {obs_off_ns:.0f}ns x 10 sites/step is "
            f"{m_overhead_off * 100:.2f}% of a fused step (>=3%): the "
            "disabled path got expensive (PR 12 regression)")

    # histogram hot path: zero allocation growth (bounded bucket bands)
    set_flags({"FLAGS_metrics": True})
    gh = _pm.LogHistogram(window=5_000)
    gh.observe(0.001)
    import sys as _sys
    band_len0 = len(gh._cur)
    size0 = _sys.getsizeof(gh._cur)
    for i in range(50_000):
        gh.observe(0.0001 * (1 + (i % 97)))
    if len(gh._cur) != band_len0 or _sys.getsizeof(gh._cur) != size0 \
            or (gh._prev is not None and len(gh._prev) != band_len0):
        failures.append(
            "histogram hot path grew its bucket storage under sustained "
            "observation: the bands are no longer preallocated/bounded "
            "(PR 12 regression)")

    # metrics-on cost, fused train loop: interleaved paired windows
    m_step = _loop(step_fused=True)
    for _ in range(WARMUP):
        m_step()
    set_flags({"FLAGS_metrics": False})
    for _ in range(WARMUP):
        m_step()
    mratios = []
    for _ in range(6):
        set_flags({"FLAGS_metrics": False})
        m_step.sync()
        t0 = time.perf_counter()
        for _ in range(MEASURE):
            m_step()
        m_step.sync()
        t_moff = time.perf_counter() - t0
        set_flags({"FLAGS_metrics": True})
        m_step.sync()
        t0 = time.perf_counter()
        for _ in range(MEASURE):
            m_step()
        m_step.sync()
        t_mon = time.perf_counter() - t0
        mratios.append(t_mon / t_moff if t_moff > 0 else float("inf"))
    set_flags({"FLAGS_metrics": False})
    m_overhead_on = min(mratios) - 1.0
    if m_overhead_on >= 0.05:
        failures.append(
            f"FLAGS_metrics costs {m_overhead_on * 100:.1f}%/step on the "
            "fused train loop (>=5%): the armed telemetry plane stopped "
            "being cheap (PR 12 regression)")

    # metrics-on cost, serve_8-style workload (same engine pattern as
    # the resilience leg; programs warm before the windows)
    mengine = LLMEngine(smodel, max_batch_size=4, block_size=4)
    mengine.generate(sprompts8, max_new_tokens=6)
    msratios = []
    for _ in range(6):
        set_flags({"FLAGS_metrics": False})
        t0 = time.perf_counter()
        for p in sprompts8:
            mengine.add_request(p, max_new_tokens=6)
        mengine.run()
        t_soff = time.perf_counter() - t0
        set_flags({"FLAGS_metrics": True})
        t0 = time.perf_counter()
        for p in sprompts8:
            mengine.add_request(p, max_new_tokens=6)
        mengine.run()
        t_son = time.perf_counter() - t0
        msratios.append(t_son / t_soff if t_soff > 0 else float("inf"))
    set_flags({"FLAGS_metrics": False})
    ms_overhead_on = min(msratios) - 1.0
    if ms_overhead_on >= 0.05:
        failures.append(
            f"FLAGS_metrics costs {ms_overhead_on * 100:.1f}%/step on the "
            "serve_8 loop (>=5%): the serving instrumentation stopped "
            "being cheap (PR 12 regression)")
    _pm.reset_metrics()

    # ---- telemetry server leg (PR 13 guard) ------------------------------
    # (l) the live HTTP observability plane: with NO server running,
    # every heartbeat site must be one module-bool check (<3%/step at a
    # generous 4 sites/step) that records NOTHING; with the server armed
    # AND a scraper hitting /metrics + /healthz every 100 ms, the fused
    # train loop and the serve_8 workload must stay within 5%/step
    # (interleaved scraper-paused vs scraping windows, min-of-ratios —
    # the guardian leg's statistic)
    import threading
    import urllib.error
    import urllib.request
    from paddle_tpu.profiler import telemetry_server as _tsrv

    N_BEAT = 200_000
    t0 = time.perf_counter()
    for _ in range(N_BEAT):
        _tsrv.beat("train")
    beat_off_ns = (time.perf_counter() - t0) / N_BEAT * 1e9
    if _tsrv._HEART:
        failures.append(
            "telemetry heartbeat recorded with no server running: the "
            "module-bool gate is broken (PR 13 regression)")
    tel_overhead_off = beat_off_ns * 4 / max(t_step * 1e9, 1.0)
    if tel_overhead_off >= 0.03:
        failures.append(
            f"server-off heartbeat cost {beat_off_ns:.0f}ns x 4 "
            f"sites/step is {tel_overhead_off * 100:.2f}% of a fused "
            "step (>=3%): the disarmed liveness path got expensive "
            "(PR 13 regression)")

    srv = _tsrv.start(port=0)
    scrape_on = threading.Event()
    scrape_stop = threading.Event()
    scrape_errs = []
    scrape_n = [0]

    def _scraper():
        while not scrape_stop.is_set():
            if not scrape_on.is_set():
                time.sleep(0.005)
                continue
            for ep in ("/metrics", "/healthz"):
                try:
                    with urllib.request.urlopen(srv.url + ep,
                                                timeout=5) as r:
                        r.read()
                    scrape_n[0] += 1
                except urllib.error.HTTPError:
                    scrape_n[0] += 1   # 503 healthz is a served scrape
                except Exception as e:
                    scrape_errs.append(repr(e)[:120])
            time.sleep(0.1)

    _sthr = threading.Thread(target=_scraper, daemon=True)
    _sthr.start()
    set_flags({"FLAGS_metrics": True})
    ts_step = _loop(step_fused=True)
    for _ in range(WARMUP):
        ts_step()
    tratios = []
    for _ in range(6):
        scrape_on.clear()
        ts_step.sync()
        t0 = time.perf_counter()
        for _ in range(MEASURE):
            ts_step()
        ts_step.sync()
        t_plain = time.perf_counter() - t0
        scrape_on.set()
        ts_step.sync()
        t0 = time.perf_counter()
        for _ in range(MEASURE):
            ts_step()
        ts_step.sync()
        t_scraped = time.perf_counter() - t0
        tratios.append(t_scraped / t_plain if t_plain > 0
                       else float("inf"))
    tel_train_overhead = min(tratios) - 1.0
    if tel_train_overhead >= 0.05:
        failures.append(
            f"a 100ms-cadence scraper costs "
            f"{tel_train_overhead * 100:.1f}%/step on the fused train "
            "loop (>=5%): the scrape path is taxing the step it watches "
            "(PR 13 regression)")
    tsratios = []
    for _ in range(6):
        scrape_on.clear()
        t0 = time.perf_counter()
        for p in sprompts8:
            mengine.add_request(p, max_new_tokens=6)
        mengine.run()
        t_plain = time.perf_counter() - t0
        scrape_on.set()
        t0 = time.perf_counter()
        for p in sprompts8:
            mengine.add_request(p, max_new_tokens=6)
        mengine.run()
        t_scraped = time.perf_counter() - t0
        tsratios.append(t_scraped / t_plain if t_plain > 0
                        else float("inf"))
    tel_serve_overhead = min(tsratios) - 1.0
    if tel_serve_overhead >= 0.05:
        failures.append(
            f"a 100ms-cadence scraper costs "
            f"{tel_serve_overhead * 100:.1f}%/step on the serve_8 loop "
            "(>=5%) (PR 13 regression)")
    scrape_stop.set()
    scrape_on.set()
    _sthr.join(timeout=10)
    _tsrv.stop()
    set_flags({"FLAGS_metrics": False})
    if scrape_n[0] == 0:
        failures.append(
            "the telemetry scraper never completed a scrape — the leg "
            "guarded nothing (PR 13 guard bug)")
    if len(scrape_errs) > 5:
        failures.append(
            f"{len(scrape_errs)} scrape failures under churn (first: "
            f"{scrape_errs[0]}): the server stopped answering while the "
            "process works (PR 13 regression)")
    _pm.reset_metrics()

    # ---- AOT warm-start leg (PR 9 guard) ---------------------------------
    # (h) a fresh subprocess with a warm executable store must promote its
    # fused step with zero compile activity and beat the cold subprocess's
    # time-to-first-promoted-step
    aot_cold, aot_warm = _aot_warm_start_leg(failures)

    # ---- distributed step fusion leg (PR 10 guard) -----------------------
    # (i) a dp=N sharded-batch loop must promote into ONE shard_map
    # executable (zero retraces after promotion) and beat the same loop on
    # unfused eager dispatch (per-op GSPMD collectives) by the guard ratio
    import jax as _jax
    dp_speedup = 0.0
    dp_retraces = 0
    dp_mesh = None
    if _jax.device_count() >= 2:
        dp_step = _dp_loop(step_fused=False)
        for _ in range(WARMUP):
            dp_step()
        dp_step.sync()
        t_dp_eager = timed(dp_step)
        dp_step = _dp_loop(step_fused=True)
        for _ in range(WARMUP):
            dp_step()
        dp_step.sync()
        s0 = step_fusion_stats()
        t_dp_fused = timed(dp_step)
        s1 = step_fusion_stats()
        from paddle_tpu.ops.step_fusion import step_cache_info
        dp_mesh = next((p["spmd"] for p in step_cache_info()["programs"]
                        if p["spmd"] and not p["dead"]), None)
        dp_replays = min(s1["fused_steps"] - s0["fused_steps"], MEASURE)
        dp_retraces = s1["retraces"] - s0["retraces"]
        dp_speedup = t_dp_eager / t_dp_fused if t_dp_fused > 0 else 0.0
        if dp_mesh is None:
            failures.append(
                "dp sharded-batch loop did not promote through the SPMD "
                f"lowering (promoted={s1['steps_promoted']}, "
                f"splits={s1['fallback_splits']}): the mesh plan was "
                "refused or demoted (PR 10 regression)")
        if dp_replays == 0:
            failures.append(
                "promoted DP step replay rate is zero "
                "(PR 10 regression)")
        if dp_retraces:
            failures.append(
                f"{dp_retraces} post-warmup retrace(s) in the promoted DP "
                "step: the shard_map executable is re-tracing a stable "
                "sharded cycle (PR 10 regression)")
        if dp_replays and dp_speedup < DP_SPEEDUP_GUARD:
            failures.append(
                f"promoted DP step speedup {dp_speedup:.2f}x over unfused "
                f"eager collectives is below the {DP_SPEEDUP_GUARD}x guard "
                f"(eager {t_dp_eager*1e6:.0f}us vs fused "
                f"{t_dp_fused*1e6:.0f}us) (PR 10 regression)")

    # ---- universal promotion leg (PR 14 guards) --------------------------
    # (m) dropout>0 must promote with ZERO steady-state retraces (the
    # hoisted-key path) and beat the chain tier like any promoted step;
    # a k=4 micro-batch accumulation loop must run as a super-cycle —
    # exactly TWO executables (one sub trace + one update trace), zero
    # retraces at steady state, zero splits
    import numpy as _np
    import paddle_tpu as _pd
    import paddle_tpu.nn.functional as _F
    from paddle_tpu.ops.dispatch import clear_dispatch_cache as _cdc
    from paddle_tpu.profiler import reset_step_fusion_stats as _rsfs

    def _drop_loop(step_fused):
        set_flags({"FLAGS_eager_step_fusion": step_fused,
                   "FLAGS_eager_step_fusion_min_count": 5})
        _cdc()
        _pd.seed(0)
        _rng = _np.random.default_rng(0)
        x = _pd.to_tensor(_rng.standard_normal((16, 32))
                          .astype(_np.float32))
        w = _pd.to_tensor(_rng.standard_normal((32, 32))
                          .astype(_np.float32), stop_gradient=False)
        b = _pd.to_tensor(_rng.standard_normal(32).astype(_np.float32),
                          stop_gradient=False)
        opt = _pd.optimizer.SGD(learning_rate=1e-3, parameters=[w, b])

        def step():
            y = _F.dropout(_F.gelu(_pd.add(_pd.matmul(x, w), b)), 0.2)
            y.sum().backward()
            opt.step()
            opt.clear_grad()

        step.sync = lambda: w._value.block_until_ready()
        return step

    drop_chain = _drop_loop(step_fused=False)
    for _ in range(WARMUP):
        drop_chain()
    t_drop_chain = timed(drop_chain)
    drop_step = _drop_loop(step_fused=True)
    for _ in range(WARMUP):
        drop_step()
    s0 = step_fusion_stats()
    t_drop_step = timed(drop_step)
    s1 = step_fusion_stats()
    drop_replays = min(s1["fused_steps"] - s0["fused_steps"], MEASURE)
    drop_retraces = s1["retraces"] - s0["retraces"]
    drop_speedup = t_drop_chain / t_drop_step if t_drop_step > 0 else 0.0
    if drop_replays == 0:
        failures.append(
            "the dropout>0 loop never promoted (hoisted-key regression: "
            f"promoted={s1['steps_promoted']}, "
            f"splits={s1['fallback_splits']}) (PR 14)")
    if drop_retraces:
        failures.append(
            f"{drop_retraces} post-warmup retrace(s) in the promoted "
            "dropout step: the hoisted key is re-tracing (PR 14)")
    if drop_replays and drop_speedup < STEP_SPEEDUP_GUARD:
        failures.append(
            f"promoted dropout step speedup {drop_speedup:.2f}x below "
            f"the {STEP_SPEEDUP_GUARD}x guard (chain "
            f"{t_drop_chain*1e6:.0f}us vs fused "
            f"{t_drop_step*1e6:.0f}us) (PR 14)")

    set_flags({"FLAGS_eager_step_fusion": True,
               "FLAGS_eager_step_fusion_min_count": 5})
    _cdc()
    _rsfs()
    _pd.seed(0)
    _rng = _np.random.default_rng(0)
    ax = _pd.to_tensor(_rng.standard_normal((16, 32)).astype(_np.float32))
    aw = _pd.to_tensor(_rng.standard_normal((32, 32)).astype(_np.float32),
                       stop_gradient=False)
    ab = _pd.to_tensor(_rng.standard_normal(32).astype(_np.float32),
                       stop_gradient=False)
    aopt = _pd.optimizer.SGD(learning_rate=1e-3, parameters=[aw, ab])

    def _accum_cycle(k=4):
        for _ in range(k):
            y = _F.gelu(_pd.add(_pd.matmul(ax, aw), ab))
            y.sum().backward()
        aopt.step()
        aopt.clear_grad()

    for _ in range(12):
        _accum_cycle()
    sa = step_fusion_stats()
    accum_fused0 = sa["fused_steps"]
    accum_retraces = sa["retraces"]
    for _ in range(8):
        _accum_cycle()
    sb = step_fusion_stats()
    if sa["steps_promoted"] != 1 or sb["fused_steps"] - accum_fused0 < 8:
        failures.append(
            "the k=4 accumulation loop did not promote as a super-cycle "
            f"(promoted={sb['steps_promoted']}, "
            f"fused={sb['fused_steps']}, splits={sb['fallback_splits']}) "
            "(PR 14)")
    if accum_retraces > 2:
        failures.append(
            f"the super-cycle compiled {accum_retraces} executables "
            "(> 2: sub + update) (PR 14)")
    if sb["retraces"] != accum_retraces:
        failures.append(
            f"{sb['retraces'] - accum_retraces} steady-state retrace(s) "
            "in the super-cycle (PR 14)")
    if sb["fallback_splits"]:
        failures.append(
            f"{sb['fallback_splits']} split(s) in the steady accumulation "
            "loop (PR 14)")

    # ---- hybrid pipeline promotion leg (PR 16 guard) ---------------------
    # (n) a pp=2 x virtual=2 interleaved pipeline cycle must promote
    # through the ops/spmd_fusion pipeline registry (ONE ppermute-handoff
    # executable spanning fill/steady/drain + update), replay it on every
    # train_batch with zero steady-state retraces, and beat the same
    # schedule run unfused and eager (forward_backward_pipeline:
    # sequential micro-batch accumulation) by the guard ratio
    pp_speedup = 0.0
    pp_retraces = 0
    pp_promoted = 0
    if _jax.device_count() >= 2:
        import jax.numpy as _jnp
        from paddle_tpu.distributed.mesh import build_mesh, set_global_mesh
        from paddle_tpu.distributed.fleet.meta_parallel import (
            PipelineLayer, PipelineParallel)
        from paddle_tpu.incubate.models import (
            GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
            gpt_pipeline_layers)
        from paddle_tpu.ops.spmd_fusion import clear_pipeline_programs

        # eager tiers off both sides: the registry owns promotion on the
        # fused side, and the eager side is the pure per-op schedule
        set_flags({"FLAGS_eager_op_cache": False,
                   "FLAGS_eager_chain_fusion": False,
                   "FLAGS_eager_step_fusion": False})
        _cdc()
        clear_pipeline_programs()
        _ppcfg = GPTConfig(vocab_size=128, hidden_size=32,
                           num_hidden_layers=8, num_attention_heads=4,
                           intermediate_size=64,
                           max_position_embeddings=32,
                           hidden_dropout_prob=0.0,
                           attention_probs_dropout_prob=0.0,
                           use_flash_attention=False)
        _pprng = _np.random.default_rng(0)
        pids = _jnp.asarray(_pprng.integers(0, 128, (4, 32)), _jnp.int32)
        plab = _jnp.asarray(_pprng.integers(0, 128, (4, 32)), _jnp.int32)

        def _pp_runner():
            _pd.seed(0)
            model = GPTForCausalLM(_ppcfg)
            pl = PipelineLayer(gpt_pipeline_layers(model), num_stages=2,
                               loss_fn=GPTPretrainingCriterion(),
                               num_virtual_pipeline_stages=2)
            runner = PipelineParallel(pl, hcg=None)
            runner.accumulate_steps = 4
            opt = _pd.optimizer.AdamW(learning_rate=1e-3,
                                      parameters=model.parameters())
            return runner, opt

        PP_STEPS = 6
        set_global_mesh(None)                 # unfused eager schedule
        runner, opt = _pp_runner()
        float(runner.train_batch((pids, plab), opt))
        t0 = time.perf_counter()
        for _ in range(2):
            float(runner.train_batch((pids, plab), opt))
        t_pp_eager = (time.perf_counter() - t0) / 2

        set_global_mesh(build_mesh(dp=1, pp=2, sharding=1, sep=1, mp=1,
                                   devices=_jax.devices()[:2]))
        runner, opt = _pp_runner()
        s0 = step_fusion_stats()
        for _ in range(3):                    # warmup: trace + compile
            float(runner.train_batch((pids, plab), opt))
        s1 = step_fusion_stats()
        pp_promoted = s1["steps_promoted"] - s0["steps_promoted"]
        t0 = time.perf_counter()
        for _ in range(PP_STEPS):
            float(runner.train_batch((pids, plab), opt))
        t_pp_fused = (time.perf_counter() - t0) / PP_STEPS
        s2 = step_fusion_stats()
        pp_retraces = s2["retraces"] - s1["retraces"]
        pp_fires = s2["fused_steps"] - s1["fused_steps"]
        pp_speedup = t_pp_eager / t_pp_fused if t_pp_fused > 0 else 0.0
        set_global_mesh(None)
        clear_pipeline_programs()
        if pp_promoted != 1:
            failures.append(
                f"the pp=2 interleaved cycle promoted {pp_promoted} "
                "pipeline program(s) (expected exactly 1) — train_batch "
                "fell off the registry path (PR 16 regression)")
        if pp_fires != PP_STEPS:
            failures.append(
                f"only {pp_fires}/{PP_STEPS} train_batch calls fired the "
                "promoted pipeline executable (PR 16 regression)")
        if pp_retraces:
            failures.append(
                f"{pp_retraces} steady-state retrace(s) in the promoted "
                "pipeline cycle: the handoff program is re-tracing a "
                "stable schedule (PR 16 regression)")
        if pp_promoted and pp_speedup < PP_SPEEDUP_GUARD:
            failures.append(
                f"promoted pipeline cycle speedup {pp_speedup:.2f}x over "
                "the unfused eager schedule is below the "
                f"{PP_SPEEDUP_GUARD}x guard (eager "
                f"{t_pp_eager*1e3:.1f}ms vs fused {t_pp_fused*1e3:.1f}ms) "
                "(PR 16 regression)")

    # ---- multi-tenant serving leg (PR 17 guards) -------------------------
    # (o) 64 streams over 8 tenants (base + 7 LoRA slots) share a system
    # prompt through the prefix cache while a tenant departs, a new one
    # lands in the freed slot, and ONE live weight hot-swap cuts over
    # mid-run: the decode executable must still compile exactly once —
    # the adapter stacks and the swapped params are VALUE edits to fixed
    # shapes, never new programs
    paddle.seed(0)
    tmodel = GPTForCausalLM(scfg)
    tmodel.eval()
    teng = LLMEngine(tmodel, max_batch_size=4, block_size=4,
                     enable_prefix_cache=True, max_adapters=7,
                     adapter_rank=2, hot_swap=True)
    tnames = [None] + [f"t{i}" for i in range(1, 8)]
    for i in range(1, 8):
        teng.register_adapter(f"t{i}", seed=i, scale=4.0)
    trng = np.random.default_rng(17)
    tsys = trng.integers(0, 128, 12).tolist()
    ttails = [trng.integers(0, 128, int(n)).tolist()
              for n in trng.integers(3, 8, 64)]
    for i, tail in enumerate(ttails[:32]):
        teng.add_request(tsys + tail, max_new_tokens=6,
                         adapter=tnames[i % 8])
    teng.run()
    # tenant churn between phases: a drained tenant departs, a new one
    # takes the freed slot
    teng.unregister_adapter("t7")
    teng.register_adapter("t8", seed=11, scale=4.0)
    for i, tail in enumerate(ttails[32:]):
        name = tnames[i % 8]
        teng.add_request(tsys + tail, max_new_tokens=6,
                         adapter="t8" if name == "t7" else name)
    for _ in range(3):                       # streams mid-flight
        teng.step()
    teng.swap_weights([np.asarray(p._value) * np.float32(1.0001)
                       for p in tmodel.parameters()])
    teng.run()
    tstats = teng.stats()
    if tstats["decode_compiles"] != 1:
        failures.append(
            f"tenant decode compiled {tstats['decode_compiles']}x across "
            "64 streams / 8 tenants with adapter churn and a live weight "
            "swap (must be exactly 1): tenancy leaked into the decode "
            "shapes (PR 17 regression)")
    if tstats["weight_swaps"] != 1:
        failures.append(
            f"{tstats['weight_swaps']} weight swap(s) committed "
            "(expected 1): the staged cutover did not land "
            "(PR 17 regression)")
    if tstats["adapter_switches"] < 1:
        failures.append(
            "zero adapter switches across a round-robin 8-tenant mix: "
            "slot routing is not reaching the decode batch "
            "(PR 17 regression)")
    if tstats["prefix_hit_tokens"] <= 0:
        failures.append(
            "zero prefix-hit tokens with a 12-token shared system "
            "prompt across 64 streams: the prefix cache never aliased "
            "(PR 17 regression)")

    # prefix-hit steady state vs cold prefill: interleaved windows over
    # the SAME prompt (min-of-paired-ratios, the guardian-leg statistic —
    # a load spike hits both engines, a real regression inflates every
    # pair). A prefix hit skips prefill ENTIRELY — the stream joins the
    # decode batch at cached_len = hit — so the guarded quantity is a
    # whole prefill vs slot bookkeeping. Measured on a wider model with
    # a long shared prompt so prefill compute dominates the window, and
    # the prompt is 1 past a block boundary (4*64+1) so the hit covers
    # exactly the full blocks and the first KV write lands in a fresh
    # private block — a block-interior hit would COW the tail block
    # every window and measure pool copies instead of aliasing
    paddle.seed(0)
    pcfg = GPTConfig(vocab_size=128, hidden_size=128,
                     num_hidden_layers=2, num_attention_heads=4,
                     intermediate_size=256, max_position_embeddings=272,
                     hidden_dropout_prob=0.0,
                     attention_probs_dropout_prob=0.0,
                     use_flash_attention=False)
    pmodel = GPTForCausalLM(pcfg)
    pmodel.eval()
    pprompt = srng.integers(0, 128, 257).tolist()
    hot_eng = LLMEngine(pmodel, max_batch_size=4, block_size=4,
                        num_blocks=512, enable_prefix_cache=True)
    cold_eng = LLMEngine(pmodel, max_batch_size=4, block_size=4,
                         num_blocks=512)

    def _prefill_window(eng):
        for _ in range(4):
            eng.add_request(pprompt, max_new_tokens=1)
        eng.run()

    _prefill_window(hot_eng)      # compiles + publishes the prefix
    _prefill_window(cold_eng)
    pratios = []
    for _ in range(6):
        t0 = time.perf_counter()
        _prefill_window(cold_eng)
        t_cold = time.perf_counter() - t0
        t0 = time.perf_counter()
        _prefill_window(hot_eng)
        t_hot = time.perf_counter() - t0
        pratios.append(t_cold / t_hot if t_hot > 0 else float("inf"))
    prefix_speedup = min(pratios)
    if prefix_speedup < PREFIX_SPEEDUP_GUARD:
        failures.append(
            f"prefix-hit prefill is only {prefix_speedup:.2f}x the cold "
            f"prefill (>= {PREFIX_SPEEDUP_GUARD}x required): shared-"
            "prefix streams are re-running prefill compute they should "
            "alias (PR 17 regression)")
    if hot_eng.stats()["prefix_hit_rate"] <= 0:
        failures.append(
            "hot engine reports a zero prefix hit rate on a repeated "
            "identical prompt (PR 17 regression)")

    # ---- compiled sampling + pipelined decode legs (PR 18 guards) --------
    # (p1) 64 streams churn through 4 slots with HETEROGENEOUS sampler
    # configs — greedy, temperature-only, top-k, top-p, penalties, per-
    # request seeds, all mixed in the same running batch — and the decode
    # executable must still compile exactly once: sampler params are VALUE
    # buffers of the one program, never structure
    paddle.seed(0)
    samp_eng = LLMEngine(smodel, max_batch_size=4, block_size=4)
    samp_cfgs = [dict(),                                     # greedy slot
                 dict(temperature=0.7),
                 dict(temperature=0.9, top_k=20),
                 dict(temperature=0.8, top_p=0.9),
                 dict(temperature=1.0, top_k=12, top_p=0.95,
                      repetition_penalty=1.2)]
    for i, p in enumerate(sprompts):
        kw = dict(samp_cfgs[i % len(samp_cfgs)])
        if kw:
            kw["seed"] = 1000 + i
        samp_eng.add_request(p, max_new_tokens=6, **kw)
    samp_eng.run()
    samp_stats = samp_eng.stats()
    if samp_stats["decode_compiles"] != 1:
        failures.append(
            f"decode compiled {samp_stats['decode_compiles']}x across 64 "
            "churning streams with mixed sampler configs (must be exactly "
            "1): sampler params leaked into the decode structure "
            "(PR 18 regression)")
    if samp_stats["sampled_tokens"] <= 0:
        failures.append(
            "zero sampled tokens across a mixed greedy/stochastic stream "
            "churn: the stochastic path never ran (PR 18 regression)")

    # (p2) the sampler head must stay cheap: interleaved greedy/sampled
    # windows on a forward-dominated model (hidden 640 — the head's fixed
    # sort+gumbel cost has real FLOPs to amortize against), min-of-paired-
    # ratios (the prefix-leg statistic: a load spike lands on both
    # windows, a real regression inflates every pair)
    paddle.seed(0)
    samp_cfg2 = GPTConfig(vocab_size=128, hidden_size=640,
                          num_hidden_layers=2, num_attention_heads=4,
                          intermediate_size=1280,
                          max_position_embeddings=128,
                          hidden_dropout_prob=0.0,
                          attention_probs_dropout_prob=0.0,
                          use_flash_attention=False)
    samp_model2 = GPTForCausalLM(samp_cfg2)
    samp_model2.eval()
    ov_eng = LLMEngine(samp_model2, max_batch_size=8, block_size=8,
                       max_context=96)
    ov_prompts = [srng.integers(0, 128, 4).tolist() for _ in range(8)]
    ov_eng.generate(ov_prompts, max_new_tokens=3)          # warm greedy

    def _sampler_window(temp, n_new=16):
        for i, p in enumerate(ov_prompts):
            kw = dict(max_new_tokens=n_new)
            if temp > 0:
                kw.update(temperature=temp, top_k=20, top_p=0.9,
                          seed=11 + i)
            ov_eng.add_request(p, **kw)
        t0 = time.perf_counter()
        ov_eng.run()
        return time.perf_counter() - t0

    _sampler_window(0.9, 4)                                # warm sampled
    sratios = []
    for _ in range(5):
        t_greedy = _sampler_window(0.0)
        t_sampled = _sampler_window(0.9)
        sratios.append(t_sampled / t_greedy if t_greedy > 0
                       else float("inf"))
    sampled_overhead = min(sratios) - 1.0
    if sampled_overhead > SAMPLED_OVERHEAD_GUARD:
        failures.append(
            f"sampled decode costs {sampled_overhead * 100:.1f}%/step "
            f"over greedy (> {SAMPLED_OVERHEAD_GUARD * 100:.0f}%): the "
            "sampler head is no longer a rounding error next to the "
            "forward — a sort fell out of the shared pass or the "
            "stochastic branch runs for greedy batches "
            "(PR 18 regression)")
    if ov_eng.stats()["decode_compiles"] != 1:
        failures.append(
            "the sampled-overhead windows retraced the decode program "
            "(PR 18 regression)")

    # (p3) lag-1 pipelined decode vs unpipelined, serve_8 windows whose
    # per-token commit BLOCKS the host (time.sleep — a stream-write /
    # slow-client stand-in that frees the core, which is the only thing a
    # 1-core CI box can genuinely overlap; on an accelerator the same
    # pipeline overlaps ALL host work with off-host device compute).
    # Interleaved min-of-ratios: every round must clear the bar
    paddle.seed(0)
    pipe_cfg = GPTConfig(vocab_size=128, hidden_size=256,
                         num_hidden_layers=2, num_attention_heads=4,
                         intermediate_size=512,
                         max_position_embeddings=128,
                         hidden_dropout_prob=0.0,
                         attention_probs_dropout_prob=0.0,
                         use_flash_attention=False)
    pipe_model = GPTForCausalLM(pipe_cfg)
    pipe_model.eval()

    def _blocking_sink(req, tok, text):
        time.sleep(0.0006)

    def _mk_pipe_eng(pipelined):
        e = LLMEngine(pipe_model, max_batch_size=8, block_size=8,
                      num_blocks=256, max_context=96,
                      pipeline_decode=pipelined)
        e.generate(ov_prompts, max_new_tokens=3)           # warm programs
        return e

    unpipe_eng = _mk_pipe_eng(False)
    pipe_eng = _mk_pipe_eng(True)

    def _pipe_window(eng, n_new=20):
        for i, p in enumerate(ov_prompts):
            eng.add_request(p, max_new_tokens=n_new, temperature=0.9,
                            top_k=20, top_p=0.9, seed=31 + i,
                            on_token=_blocking_sink)
        t0 = time.perf_counter()
        eng.run()
        return time.perf_counter() - t0

    _pipe_window(unpipe_eng)
    _pipe_window(pipe_eng)
    pipe_ratios = []
    for _ in range(6):
        t_unpipe = _pipe_window(unpipe_eng)
        t_pipe = _pipe_window(pipe_eng)
        pipe_ratios.append(t_unpipe / t_pipe if t_pipe > 0
                           else float("inf"))
    pipe_speedup = min(pipe_ratios)
    if pipe_speedup < PIPELINE_SPEEDUP_GUARD:
        failures.append(
            f"pipelined decode is only {pipe_speedup:.2f}x the "
            f"unpipelined engine on the blocked-host serve_8 windows "
            f"(>= {PIPELINE_SPEEDUP_GUARD}x required): the launch path "
            "re-synchronized — commit work no longer overlaps the "
            "in-flight step (PR 18 regression)")
    pipe_stats = pipe_eng.stats()
    if pipe_stats["decode_compiles"] != 1:
        failures.append(
            f"pipelined decode compiled {pipe_stats['decode_compiles']}x "
            "(must be exactly 1): the feedback path leaked into the "
            "decode structure (PR 18 regression)")
    if pipe_stats["commit_rollbacks"] != 0:
        failures.append(
            f"{pipe_stats['commit_rollbacks']} commit rollback(s) on a "
            "cancel-free pipelined workload (expected 0): the lag-1 "
            "boundary is discarding healthy streams (PR 18 regression)")

    # ---- regression sentinel leg (PR 19 guards) --------------------------
    # (q) the perf regression sentinel must honor the flight recorder's
    # cost discipline: DISARMED, every tick site is one module-bool check
    # (<3%/step at a generous 4 sites/step, and no windows are opened);
    # ARMED (short evaluation windows, so the probe/classify path really
    # runs inside the measured loops), the fused train loop and the
    # serve_8 workload must each stay within 3%/step — interleaved
    # disarmed-vs-armed min-of-paired-ratio windows with the metrics +
    # events planes ON in both (their cost is budgeted by legs (d)/(k);
    # this measures the sentinel's MARGINAL cost). Finally the leg gates
    # its own whole-run record against the checked-in perf baseline —
    # perf_smoke is itself a baselined leg.
    import json

    from paddle_tpu.profiler import sentinel as _snt

    _snt.disarm()
    N_TICK = 200_000
    t0 = time.perf_counter()
    for _ in range(N_TICK):
        _snt.tick()
    tick_off_ns = (time.perf_counter() - t0) / N_TICK * 1e9
    if _snt.SENTINEL.snapshot()["windows"] != 0:
        failures.append(
            "disarmed sentinel ticks opened evaluation windows: the "
            "module-bool gate is broken (PR 19 regression)")
    snt_overhead_off = tick_off_ns * 4 / max(t_step * 1e9, 1.0)
    if snt_overhead_off >= 0.03:
        failures.append(
            f"disarmed sentinel tick cost {tick_off_ns:.0f}ns x 4 "
            f"sites/step is {snt_overhead_off * 100:.2f}% of a fused "
            "step (>=3%): the disarmed watcher got expensive "
            "(PR 19 regression)")

    set_flags({"FLAGS_metrics": True, "FLAGS_profiler_events": True})
    q_step = _loop(step_fused=True)
    for _ in range(WARMUP):
        q_step()
    qratios = []
    for _ in range(6):
        _snt.disarm()
        q_step.sync()
        t0 = time.perf_counter()
        for _ in range(MEASURE):
            q_step()
        q_step.sync()
        t_qoff = time.perf_counter() - t0
        _snt.arm(window_s=0.2)
        q_step.sync()
        t0 = time.perf_counter()
        for _ in range(MEASURE):
            q_step()
        q_step.sync()
        t_qon = time.perf_counter() - t0
        qratios.append(t_qon / t_qoff if t_qoff > 0 else float("inf"))
    _snt.disarm()
    snt_train_overhead = min(qratios) - 1.0
    if snt_train_overhead >= 0.03:
        failures.append(
            f"the armed sentinel costs {snt_train_overhead * 100:.1f}%"
            "/step on the fused train loop (>=3%): the window "
            "probe/classify path is taxing the step it watches "
            "(PR 19 regression)")

    qsratios = []
    for _ in range(6):
        _snt.disarm()
        t0 = time.perf_counter()
        for p in sprompts8:
            mengine.add_request(p, max_new_tokens=6)
        mengine.run()
        t_qsoff = time.perf_counter() - t0
        _snt.arm(window_s=0.2)
        t0 = time.perf_counter()
        for p in sprompts8:
            mengine.add_request(p, max_new_tokens=6)
        mengine.run()
        t_qson = time.perf_counter() - t0
        qsratios.append(t_qson / t_qsoff if t_qsoff > 0
                        else float("inf"))
    _snt.disarm()
    set_flags({"FLAGS_metrics": False, "FLAGS_profiler_events": False})
    snt_serve_overhead = min(qsratios) - 1.0
    if snt_serve_overhead >= 0.03:
        failures.append(
            f"the armed sentinel costs {snt_serve_overhead * 100:.1f}%"
            "/step on the serve_8 loop (>=3%) (PR 19 regression)")

    # the self-gate: this very run's whole-process record must sit inside
    # the checked-in perf_smoke bands (tools/perf_baselines.json — the
    # same add/match/expire hygiene as the fusion-lint baseline)
    smoke_rec = _snt.capture_record("perf_smoke", kind="mixed")
    print(json.dumps({"event": "sentinel_record", "record": smoke_rec}),
          flush=True)
    from paddle_tpu.profiler.sentinel import (DEFAULT_PERF_BASELINE,
                                              PerfBaseline)
    if not os.path.exists(DEFAULT_PERF_BASELINE):
        failures.append(
            "tools/perf_baselines.json is missing: the perf_smoke leg "
            "has no bands to gate against (PR 19 regression)")
    else:
        _blq = PerfBaseline.load(DEFAULT_PERF_BASELINE)
        _viol, _passed, _unb = _blq.split([smoke_rec])
        for _rec, _fs in _viol:
            failures.append(
                f"perf_smoke's own sentinel record violates its "
                f"checked-in bands: {_fs[0]['reason']} — "
                f"{_fs[0]['message']} (PR 19 regression — or a real "
                "drift; re-seed deliberately with tools/perf_baseline.py "
                "--write-baseline)")
        if _unb:
            failures.append(
                "perf_smoke has no entry in tools/perf_baselines.json: "
                "seed it with tools/perf_baseline.py --write-baseline "
                "(PR 19 regression)")

    print(f"perf_smoke: post-warmup retraces={retraces}, "
          f"chain replays={chain_replays}/{MEASURE}, "
          f"fused steps={step_replays}/{MEASURE} "
          f"(step retraces={step_retraces}), "
          f"step-vs-chain speedup={speedup:.2f}x, "
          f"launches_saved={s1['launches_saved'] - s0['launches_saved']}, "
          f"splits={len(split_events)} (steady={len(steady_splits)}, "
          f"unexplained={len(unexplained)}), "
          f"events-off emit={emit_off_ns:.0f}ns "
          f"({overhead_frac * 100:.3f}%/step), "
          f"guardian overhead={guard_median * 100:.1f}%/step (median; "
          f"min {guard_overhead * 100:.1f}%), "
          f"AMP fused steps={amp_replays}/{MEASURE} "
          f"(retraces={amp_retraces}), "
          f"serve decode compiles={sstats['decode_compiles']} "
          f"occupancy={sstats['occupancy_saturated']:.2f} "
          f"({sstats['completed']} streams), "
          f"resilience overhead={resil_overhead * 100:.1f}%/step "
          f"(churn compiles={cstats['decode_compiles']}, "
          f"cancelled={cstats['cancelled']} expired={cstats['expired']} "
          f"refused={refused} resumed={len(resumed)}), "
          f"paged blockwise-vs-dense={paged_speedup:.2f}x "
          f"(int8 decode compiles={int8_stats['decode_compiles']}), "
          f"metrics off={obs_off_ns:.0f}ns/site "
          f"({m_overhead_off * 100:.2f}%/step) "
          f"on={m_overhead_on * 100:.1f}%/step train "
          f"{ms_overhead_on * 100:.1f}%/step serve, "
          f"telemetry beat-off={beat_off_ns:.0f}ns "
          f"scraped={tel_train_overhead * 100:.1f}%/step train "
          f"{tel_serve_overhead * 100:.1f}%/step serve "
          f"({scrape_n[0]} scrapes), "
          f"aot warm-start={aot_warm['t_first_fire_s']:.2f}s vs "
          f"cold={aot_cold['t_first_fire_s']:.2f}s "
          f"(warm hits={aot_warm['aot']['hits']} "
          f"retraces={aot_warm['dispatch_retraces']}"
          f"+{aot_warm['step_retraces']}), "
          f"dp mesh={dp_mesh} speedup={dp_speedup:.2f}x "
          f"(retraces={dp_retraces}), "
          f"dropout fused={drop_replays}/{MEASURE} "
          f"speedup={drop_speedup:.2f}x (retraces={drop_retraces}), "
          f"accum super-cycle fused={sb['fused_steps']} "
          f"executables={accum_retraces} splits={sb['fallback_splits']}, "
          f"pp pipeline promotes={pp_promoted} "
          f"speedup={pp_speedup:.2f}x (retraces={pp_retraces}), "
          f"tenant decode compiles={tstats['decode_compiles']} "
          f"(swaps={tstats['weight_swaps']} "
          f"switches={tstats['adapter_switches']} "
          f"prefix hit_tokens={tstats['prefix_hit_tokens']}), "
          f"prefix prefill speedup={prefix_speedup:.2f}x, "
          f"mixed-sampler churn compiles={samp_stats['decode_compiles']} "
          f"(sampled_tokens={samp_stats['sampled_tokens']}), "
          f"sampled overhead={sampled_overhead * 100:.1f}%/step, "
          f"pipelined speedup={pipe_speedup:.2f}x "
          f"(rollbacks={pipe_stats['commit_rollbacks']}), "
          f"sentinel tick-off={tick_off_ns:.0f}ns "
          f"armed={snt_train_overhead * 100:.1f}%/step train "
          f"{snt_serve_overhead * 100:.1f}%/step serve "
          f"(record leg={smoke_rec['leg']} kind={smoke_rec['kind']})")
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    print("perf_smoke: OK")
    return 0


if __name__ == "__main__":
    # the distributed leg needs the emulated multi-device mesh; must land
    # before the first jax import (tests/conftest.py does the same for
    # the pytest-marked legs)
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = \
            (_flags + " --xla_force_host_platform_device_count=8").strip()
    if "--aot-child" in sys.argv:
        import argparse
        ap = argparse.ArgumentParser()
        ap.add_argument("--aot-child", action="store_true")
        ap.add_argument("--aot-dir", required=True)
        ap.add_argument("--out", required=True)
        ap.add_argument("--steps", type=int, default=12)
        a = ap.parse_args()
        sys.exit(aot_child_main(a.aot_dir, a.out, a.steps))
    sys.exit(main())
