#!/usr/bin/env python
"""Fusion lint: the promotion-safety static analyzer CLI.

Proves the fusion-stack promotion contracts hold at CI time — before any
op ever runs — in the same REASON_CODES vocabulary the fusion doctor
speaks at runtime (paddle_tpu/analysis/):

  R1 unkeyable-closure       op fn captures a Tensor/array off the
                             dispatch-input list    [unkeyable_closure]
  R2 stateful-rng            op body bypasses rng_key_input()
                             stream hoisting        [rng_rekey]
  R3 host-sync-in-hot-path   .numpy()/.item()/float() force before
                             dispatch               [mid_step_peek]
  R4 unkeyed-collective      pg call without dispatch.mark_collective
                                                    [collective_unkeyed]
  R5 contract-coverage       REASON_CODES/HINTS, METRIC_NAMES/MERGE,
                             CATEGORIES, FLAGS registry drift
                                                    [contract_drift]
  R6 lock-discipline         blocking I/O / callbacks / inversions
                             under registry locks   [lock_discipline]

Usage:

    # the repo gate (tier-1 wires exactly this; exit 1 on any
    # unsuppressed finding, exit 0 clean)
    python tools/fusion_lint.py --baseline

    # a subset of paths / rules, with actionable fix hints
    python tools/fusion_lint.py paddle_tpu/ops --rules R1,R2 --fix-hints

    # machine-readable (schema frozen by tests/test_fusion_lint.py)
    python tools/fusion_lint.py --json

    # regenerate the baseline after triaging (every entry then needs a
    # human note — edit the JSON)
    python tools/fusion_lint.py --baseline --write-baseline
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fusion_lint",
        description="static analyzer proving the fusion promotion "
                    "contracts (R1-R6) before anything runs")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to scan (default: the "
                         "package + tools + bench.py)")
    ap.add_argument("--root", default=None,
                    help="repo root for relative paths/reporting "
                         "(default: the checkout containing this tool)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset, e.g. R1,R5")
    ap.add_argument("--json", action="store_true",
                    help="print the JSON report instead of text")
    ap.add_argument("--baseline", nargs="?", const="", default=None,
                    metavar="FILE",
                    help="apply the suppression baseline (default file: "
                         "tools/fusion_lint_baseline.json)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="with --baseline: rewrite the file from the "
                         "current findings (then fill in the notes)")
    ap.add_argument("--fix-hints", action="store_true",
                    help="print the actionable fix hint under each "
                         "finding")
    args = ap.parse_args(argv)

    from paddle_tpu.analysis import (Baseline, load_project, run_rules,
                                     validate_findings)
    from paddle_tpu.analysis.baseline import DEFAULT_BASELINE
    from paddle_tpu.analysis.report import render_json, render_text

    rules = None
    if args.rules:
        rules = [r.strip().upper() for r in args.rules.split(",") if r]

    try:
        project = load_project(root=args.root, paths=args.paths or None)
        findings = run_rules(project, rules=rules)
    except (FileNotFoundError, ValueError) as e:
        print(f"fusion_lint: {e}", file=sys.stderr)
        return 2
    bad_parse = project.parse_errors()
    if bad_parse:
        for rel, err in bad_parse:
            print(f"fusion_lint: cannot parse {rel}: {err}",
                  file=sys.stderr)
        print(f"fusion_lint: {len(bad_parse)} unparsable file(s) — "
              "these files are NOT covered by any rule", file=sys.stderr)
        return 2

    bad = validate_findings(findings)
    if bad:
        print(f"fusion_lint: INTERNAL ERROR — rule emitted reason "
              f"code(s) off the REASON_CODES/REASON_HINTS contract: "
              f"{bad}", file=sys.stderr)
        return 2

    suppressed, stale = [], []
    if args.baseline is not None:
        path = args.baseline or DEFAULT_BASELINE
        bl = Baseline.load(path)
        if args.write_baseline:
            bl.expire(findings)
            for f in findings:
                bl.add(f)
            bl.save(path)
            print(f"fusion_lint: wrote {len(bl.entries)} suppression(s) "
                  f"to {path} — add a human note to each new entry")
            return 0
        findings, suppressed = bl.split(findings)
        stale = bl.stale(findings + suppressed)

    if args.json:
        print(render_json(findings, suppressed, stale))
    else:
        print(render_text(findings, suppressed, stale,
                          fix_hints=args.fix_hints))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
