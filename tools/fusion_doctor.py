#!/usr/bin/env python
"""Fusion doctor: explain WHY a training loop didn't promote (or split).

Runs a training script (or a built-in demo loop) with the fusion flight
recorder armed, then aggregates the event timeline into a root-cause
report: which op poisoned the step cycle, with which reason code, how many
times — e.g.

    verdict : never_promoted
    headline: step never promoted: `dropout` rng_rekey ×40
    findings:
      - cycle poison rng_rekey ×40 (`dropout`×40) — the op consumes fresh
        global randomness every call ...

Usage:

    # any training script (its own argv after --)
    JAX_PLATFORMS=cpu python tools/fusion_doctor.py train.py -- --epochs 1

    # built-in demos (acceptance fixtures): a tiny GPT-ish loop
    python tools/fusion_doctor.py --demo dropout   # never promotes: rng_rekey
    python tools/fusion_doctor.py --demo masked    # clean promotion

    # machine-readable
    python tools/fusion_doctor.py --demo dropout --json

The doctor only ARMS the recorder (FLAGS_profiler_events); it does not
change the fusion configuration of a user script — if the script runs with
caching/fusion off, the report says so instead of inventing activity.
"""
from __future__ import annotations

import argparse
import json
import os
import runpy
import sys

# runnable from a source checkout without an install
sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))


def _demo(variant, steps):
    """Tiny single-head GPT-ish loop (embedding → attention → [dropout] →
    projection → cross_entropy → SGD). `dropout` never promotes (the
    rng_rekey acceptance fixture); `masked` feeds an attention mask — now
    a dispatch input — and promotes cleanly."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.ops import manipulation as manip

    set_flags({"FLAGS_eager_op_cache": True,
               "FLAGS_eager_chain_fusion": True,
               "FLAGS_eager_chain_fusion_min_count": 4,
               "FLAGS_eager_step_fusion": True,
               "FLAGS_eager_step_fusion_min_count": 5})
    paddle.seed(0)
    rng = np.random.default_rng(0)
    B, T, D, V = 2, 8, 16, 32
    ids = paddle.to_tensor(rng.integers(0, V, (B, T)))
    labels = paddle.to_tensor(rng.integers(0, V, (B * T,)))
    emb_w = paddle.to_tensor(
        (rng.standard_normal((V, D)) * 0.1).astype(np.float32),
        stop_gradient=False)
    wq, wk, wv, wo = (
        paddle.to_tensor((rng.standard_normal((D, D)) * 0.1)
                         .astype(np.float32), stop_gradient=False)
        for _ in range(4))
    w_out = paddle.to_tensor(
        (rng.standard_normal((D, V)) * 0.1).astype(np.float32),
        stop_gradient=False)
    mask = None
    if variant == "masked":
        causal = np.tril(np.ones((T, T), bool))
        mask = paddle.to_tensor(causal[None, None])   # [1, 1, T, T]
    params = [emb_w, wq, wk, wv, wo, w_out]
    opt = paddle.optimizer.SGD(learning_rate=1e-2, parameters=params)

    for _ in range(steps):
        h = F.embedding(ids, emb_w)                       # [B, T, D]
        q = manip.reshape(paddle.matmul(h, wq), [B, T, 1, D])
        k = manip.reshape(paddle.matmul(h, wk), [B, T, 1, D])
        v = manip.reshape(paddle.matmul(h, wv), [B, T, 1, D])
        a = F.scaled_dot_product_attention(
            q, k, v, attn_mask=mask, is_causal=(mask is None))
        h = paddle.matmul(manip.reshape(a, [B, T, D]), wo)
        if variant == "dropout":
            h = F.dropout(h, 0.1)
        logits = manip.reshape(paddle.matmul(h, w_out), [B * T, V])
        loss = F.cross_entropy(logits, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()


def _demo_serve(steps):
    """Tiny continuous-batching serving run (paddle_tpu/serving): a small
    GPT over a deliberately tight KV pool AND a bounded queue, so the
    report shows the full serve.* lifecycle — kv_exhausted evictions plus
    the PR 7 resilience codes (queue_full refusal, client_cancel,
    deadline_expired). `--steps` is the number of requests churned
    through the batch."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.incubate.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import LLMEngine, ServeRefusal

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=64,
                    max_position_embeddings=64, hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0,
                    use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    engine = LLMEngine(model, max_batch_size=3, block_size=4,
                       num_blocks=10, watermark_blocks=1,
                       max_queue_depth=max(4, steps))
    rng = np.random.default_rng(0)
    base = (11, 12, 10, 5, 7, 9)
    prompts = [rng.integers(0, 128, base[i % len(base)]).tolist()
               for i in range(max(len(base), steps))]
    reqs = [engine.add_request(p, max_new_tokens=8) for p in prompts]
    # one stream the client abandons, one with a TTL the queue ahead of
    # it will outlast (it expires while QUEUED, at an iteration boundary)
    engine.cancel(reqs[-1].rid)
    engine.add_request(prompts[0], max_new_tokens=8, ttl_s=0.01)
    # fill the bounded queue until admission refuses
    try:
        for _ in range(2 * len(prompts)):
            engine.add_request(prompts[1], max_new_tokens=8)
    except ServeRefusal:
        pass
    engine.run()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fusion_doctor",
        description="explain why a training loop didn't promote/split "
                    "(fusion flight-recorder root-cause report)")
    ap.add_argument("script", nargs="?",
                    help="training script to run under the recorder")
    ap.add_argument("script_args", nargs=argparse.REMAINDER,
                    help="arguments passed to the script (after --)")
    ap.add_argument("--demo", choices=("dropout", "masked", "serve"),
                    help="run a built-in tiny GPT-ish demo loop instead "
                         "of a script (`serve`: a continuous-batching "
                         "serving run over a tight KV pool)")
    ap.add_argument("--steps", type=int, default=20,
                    help="demo loop steps (requests, for --demo serve; "
                         "default 20)")
    ap.add_argument("--json", action="store_true",
                    help="print the report as JSON instead of text")
    args = ap.parse_args(argv)
    if not args.demo and not args.script:
        ap.error("either a script or --demo is required")

    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.profiler.events import EVENTS, clear_fusion_events
    from paddle_tpu.profiler.explain import explain, format_report

    clear_fusion_events()
    set_flags({"FLAGS_profiler_events": True})
    try:
        if args.demo == "serve":
            _demo_serve(args.steps)
        elif args.demo:
            _demo(args.demo, args.steps)
        else:
            sa = args.script_args
            if sa and sa[0] == "--":
                sa = sa[1:]
            old_argv = sys.argv
            sys.argv = [args.script] + sa
            try:
                runpy.run_path(args.script, run_name="__main__")
            except SystemExit as e:
                if e.code not in (0, None):
                    print(f"fusion_doctor: script exited with {e.code} "
                          "(reporting on the events recorded so far)",
                          file=sys.stderr)
            finally:
                sys.argv = old_argv
    finally:
        set_flags({"FLAGS_profiler_events": False})

    report = explain(EVENTS.snapshot())
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(format_report(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
