#!/usr/bin/env python
"""Fusion doctor: explain WHY a training loop didn't promote (or split).

Runs a training script (or a built-in demo loop) with the fusion flight
recorder armed, then aggregates the event timeline into a root-cause
report: which op poisoned the step cycle, with which reason code, how many
times — e.g.

    verdict : never_promoted
    headline: step never promoted: `dist.all_reduce` collective_unkeyed ×40
    findings:
      - cycle poison collective_unkeyed ×40 ...

Usage:

    # any training script (its own argv after --)
    JAX_PLATFORMS=cpu python tools/fusion_doctor.py train.py -- --epochs 1

    # built-in demos (acceptance fixtures): a tiny GPT-ish loop
    python tools/fusion_doctor.py --demo dropout   # clean promotion: the
                                                   # PRNG key is HOISTED
                                                   # (rng_rekey is gone)
    python tools/fusion_doctor.py --demo accum     # clean promotion of a
                                                   # k=4 grad-accumulation
                                                   # SUPER-cycle
    python tools/fusion_doctor.py --demo masked    # clean promotion
    python tools/fusion_doctor.py --demo dp        # never promotes:
                                                   # collective_unkeyed

    # machine-readable
    python tools/fusion_doctor.py --demo accum --json

    # the persistent AOT executable store (ops/aot_cache.py): list
    # artifacts (kind, digest, size, age, fingerprint match, corruption),
    # and collect it manually
    python tools/fusion_doctor.py --cache [--cache-dir DIR] [--gc]

    # diagnose a RUNNING process without attaching: pull the report from
    # its telemetry server's /doctor endpoint (FLAGS_telemetry_port,
    # profiler/telemetry_server.py) — same JSON schema as --json
    python tools/fusion_doctor.py --url http://host:9100 [--json]

The doctor only ARMS the recorder (FLAGS_profiler_events); it does not
change the fusion configuration of a user script — if the script runs with
caching/fusion off, the report says so instead of inventing activity.
"""
from __future__ import annotations

import argparse
import json
import os
import runpy
import sys

# runnable from a source checkout without an install
sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))


def _demo(variant, steps):
    """Tiny single-head GPT-ish loop (embedding → attention → [dropout] →
    projection → cross_entropy → SGD). `dropout` promotes CLEANLY since
    the PRNG key became a hoisted stream position (the universal-promotion
    acceptance fixture — it used to be the rng_rekey fixture); `masked`
    feeds an attention mask — a dispatch input — and promotes cleanly;
    `accum` runs the masked variant as a k=4 micro-batch gradient
    accumulation loop that promotes as a SUPER-cycle (one reusable
    fwd+bwd+accumulate sub-executable + one update executable, zero
    steady-state retraces at any k)."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.ops import manipulation as manip

    set_flags({"FLAGS_eager_op_cache": True,
               "FLAGS_eager_chain_fusion": True,
               "FLAGS_eager_chain_fusion_min_count": 4,
               "FLAGS_eager_step_fusion": True,
               "FLAGS_eager_step_fusion_min_count": 5})
    paddle.seed(0)
    rng = np.random.default_rng(0)
    B, T, D, V = 2, 8, 16, 32
    k_micro = 4 if variant == "accum" else 1
    micro = [(paddle.to_tensor(rng.integers(0, V, (B, T))),
              paddle.to_tensor(rng.integers(0, V, (B * T,))))
             for _ in range(k_micro)]
    emb_w = paddle.to_tensor(
        (rng.standard_normal((V, D)) * 0.1).astype(np.float32),
        stop_gradient=False)
    wq, wk, wv, wo = (
        paddle.to_tensor((rng.standard_normal((D, D)) * 0.1)
                         .astype(np.float32), stop_gradient=False)
        for _ in range(4))
    w_out = paddle.to_tensor(
        (rng.standard_normal((D, V)) * 0.1).astype(np.float32),
        stop_gradient=False)
    mask = None
    if variant == "masked":
        causal = np.tril(np.ones((T, T), bool))
        mask = paddle.to_tensor(causal[None, None])   # [1, 1, T, T]
    params = [emb_w, wq, wk, wv, wo, w_out]
    opt = paddle.optimizer.SGD(learning_rate=1e-2, parameters=params)

    for _ in range(steps):
        for ids, labels in micro:
            h = F.embedding(ids, emb_w)                   # [B, T, D]
            q = manip.reshape(paddle.matmul(h, wq), [B, T, 1, D])
            k = manip.reshape(paddle.matmul(h, wk), [B, T, 1, D])
            v = manip.reshape(paddle.matmul(h, wv), [B, T, 1, D])
            a = F.scaled_dot_product_attention(
                q, k, v, attn_mask=mask, is_causal=(mask is None))
            h = paddle.matmul(manip.reshape(a, [B, T, D]), wo)
            if variant in ("dropout", "accum"):
                h = F.dropout(h, 0.1)
            logits = manip.reshape(paddle.matmul(h, w_out), [B * T, V])
            loss = F.cross_entropy(logits, labels)
            loss.backward()
        opt.step()
        opt.clear_grad()


def _demo_dp(steps):
    """Data-parallel acceptance fixture: a small sharded-batch loop whose
    gradient sync calls `dist.all_reduce` over a hand-built Group WITHOUT a
    mesh-backed process group — the collective cannot be keyed, every
    cycle is poisoned `collective_unkeyed`, and the report reads "step
    never promoted: `dist.all_reduce` collective_unkeyed ×N". The fix the
    hint prescribes (mesh-backed groups, or dropping eager grad
    collectives so the SPMD promoter fuses the psum) is exactly what
    tests/test_spmd_fusion.py proves out."""
    import numpy as np
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.mesh import build_mesh, set_global_mesh
    from paddle_tpu.framework.flags import set_flags

    set_flags({"FLAGS_eager_op_cache": True,
               "FLAGS_eager_chain_fusion": True,
               "FLAGS_eager_chain_fusion_min_count": 4,
               "FLAGS_eager_step_fusion": True,
               "FLAGS_eager_step_fusion_min_count": 5})
    paddle.seed(0)
    n = jax.device_count()
    mesh = build_mesh(dp=n, pp=1, sharding=1, sep=1, mp=1)
    set_global_mesh(mesh)
    sharding = NamedSharding(mesh, P("data"))
    rng = np.random.default_rng(0)
    w = paddle.to_tensor(
        (rng.standard_normal((32, 8)) * 0.1).astype(np.float32),
        stop_gradient=False)
    opt = paddle.optimizer.SGD(learning_rate=1e-2, parameters=[w])
    group = dist.collective.Group(0, n, id=90, ranks=list(range(n)))
    for _ in range(steps):
        x = paddle.Tensor(jax.device_put(
            rng.standard_normal((2 * n, 32)).astype(np.float32), sharding),
            stop_gradient=True)
        h = paddle.matmul(x, w)
        loss = paddle.mean(paddle.multiply(h, h))
        loss.backward()
        dist.all_reduce(w.grad, group=group)   # unkeyable: pg-less group
        opt.step()
        opt.clear_grad()


def _demo_serve(steps):
    """Tiny continuous-batching serving run (paddle_tpu/serving): a small
    GPT over a deliberately tight KV pool AND a bounded queue, so the
    report shows the full serve.* lifecycle — kv_exhausted evictions plus
    the PR 7 resilience codes (queue_full refusal, client_cancel,
    deadline_expired) — and the PR 11 kernel-tier codes: the engine
    requests the Pallas kernel (demoted to blockwise off-TPU:
    `kernel_fallback`) over an int8 KV pool (`kv_quantized`). `--steps`
    is the number of requests churned through the batch."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.incubate.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import LLMEngine, ServeRefusal

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=64,
                    max_position_embeddings=64, hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0,
                    use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    engine = LLMEngine(model, max_batch_size=3, block_size=4,
                       num_blocks=10, watermark_blocks=1,
                       max_queue_depth=max(4, steps),
                       attention_kernel="pallas", kv_dtype="int8")
    rng = np.random.default_rng(0)
    base = (11, 12, 10, 5, 7, 9)
    prompts = [rng.integers(0, 128, base[i % len(base)]).tolist()
               for i in range(max(len(base), steps))]
    reqs = [engine.add_request(p, max_new_tokens=8) for p in prompts]
    # one stream the client abandons, one with a TTL the queue ahead of
    # it will outlast (it expires while QUEUED, at an iteration boundary)
    engine.cancel(reqs[-1].rid)
    engine.add_request(prompts[0], max_new_tokens=8, ttl_s=0.01)
    # fill the bounded queue until admission refuses
    try:
        for _ in range(2 * len(prompts)):
            engine.add_request(prompts[1], max_new_tokens=8)
    except ServeRefusal:
        pass
    engine.run()


def _demo_sample(steps):
    """Compiled-sampling + pipelined-decode fixture (PR 18,
    serving/sampling.py): mixed greedy/stochastic streams on a lag-1
    pipelined engine — per-slot temperature/top-k/top-p/penalty/seed ride
    the ONE decode program as value buffers, so the report must show a
    single decode compile across the whole heterogeneous churn. The
    serve section's `serve.sample` events carry the two PR 18 reason
    codes: a `sampler_mismatch` refusal (an out-of-contract sampler is
    rejected at admission, never silently clamped — a clamp would break
    the (seed, prompt, sampler) reproducibility contract) and the
    `commit_lag_rollback` cost of a client cancel landing at the lag-1
    pipeline boundary (one speculative token, by design)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.incubate.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import LLMEngine, ServeRefusal

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=64,
                    max_position_embeddings=64, hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0,
                    use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    engine = LLMEngine(model, max_batch_size=3, block_size=4,
                       pipeline_decode=True, logprobs_topk=2)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 128, int(n)).tolist()
               for n in rng.integers(4, 12, max(6, steps))]
    cfgs = [dict(),                                        # greedy slot
            dict(temperature=0.8, top_k=16, seed=101),
            dict(temperature=0.9, top_p=0.9,
                 repetition_penalty=1.2, seed=102)]
    reqs = [engine.add_request(p, max_new_tokens=8,
                               **cfgs[i % len(cfgs)])
            for i, p in enumerate(prompts)]
    # an out-of-contract sampler: refused at admission (sampler_mismatch)
    try:
        engine.add_request(prompts[0], max_new_tokens=8, temperature=-1.0)
    except (ServeRefusal, ValueError):
        pass
    # a client cancel while a pipelined launch is in flight: the commit
    # discards exactly that stream's speculative token (lag-1 rollback)
    for _ in range(6):
        engine.step()
    engine.cancel(reqs[1].rid)
    engine.run()


def _demo_tenants(steps):
    """Multi-tenant serving fixture (PR 17, serving/tenancy.py): eight
    tenants share one system prompt on a prefix-cache + batched-adapter
    + hot-swap engine, with a live weight swap mid-churn. The report's
    serving section shows the tenant line (prefix hits/misses/evictions/
    swaps) and `prefix_hit` findings with the aliasing hint — a CLEAN
    run: every code here is economy attribution, not a failure."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.incubate.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import LLMEngine

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=64,
                    max_position_embeddings=64, hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0,
                    use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    engine = LLMEngine(model, max_batch_size=4, block_size=4,
                       num_blocks=96, enable_prefix_cache=True,
                       max_adapters=4, adapter_rank=2, hot_swap=True)
    engine.register_adapter("tenant-a", seed=1, scale=8.0)
    engine.register_adapter("tenant-b", seed=2, scale=8.0)
    rng = np.random.default_rng(0)
    system_prompt = rng.integers(0, 128, 12).tolist()
    n = max(8, steps)
    plan = ("tenant-a", None, "tenant-b", None)
    for i in range(n):
        engine.add_request(system_prompt
                           + rng.integers(0, 128, 3).tolist(),
                           max_new_tokens=6, adapter=plan[i % len(plan)])
    for _ in range(3):
        engine.step()
    # live hot-swap mid-churn: same weights perturbed — the in-flight
    # streams re-prefill under the new epoch, zero recompiles
    engine.swap_weights([np.asarray(p._value) * 1.0001
                         for p in model.parameters()])
    engine.run()


def _demo_metrics(steps):
    """Telemetry-plane acceptance fixture: the masked GPT-ish loop run
    with FLAGS_metrics armed AND a guardian skip-step injected mid-run
    (FLAGS_check_numerics + guardian.inject_fault), so the doctor's
    `--metrics` summary shows a live registry with train_step_seconds
    percentiles, a goodput below 1.0, and the skipped-step wall time
    attributed to the `skipped` bucket."""
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.ops import guardian
    from paddle_tpu.profiler.metrics import reset_metrics

    set_flags({"FLAGS_metrics": True, "FLAGS_check_numerics": True,
               # warn-don't-raise: the injected NaN must flow into the
               # gradients so the guardian's skip-step rescue (not the
               # forward raise) is what the goodput report attributes
               "FLAGS_check_numerics_level": 1})
    reset_metrics()
    try:
        # fire while the loop is still eager (pre-promotion) so the NaN
        # poisons one step's grads and the update skips bitwise
        guardian.inject_fault("nan_output", op="matmul", after=8, times=1)
        _demo("masked", steps)
        guardian.flush()
    finally:
        guardian.clear_faults()
        set_flags({"FLAGS_check_numerics": False,
                   "FLAGS_check_numerics_level": 0})


def _demo_pp(steps):
    """Pipeline-parallel acceptance fixture: PipelineParallel.train_batch
    over a pipe=2 × virtual=2 interleaved mesh. The train step routes
    through the ops/spmd_fusion.py pipeline registry: ONE ppermute-handoff
    shard_map program, promoted with a canonical mesh-keyed signature —
    the report reads clean_promotion with step.promote + step.fire from
    the pipeline funnel. Eager per-op fusion stays OFF here: stage compute
    lives inside the compiled program, there is no eager cycle to record
    (runs on the emulated multi-device CPU mesh; --demo pp arms
    xla_force_host_platform_device_count=8 automatically)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh, set_global_mesh
    from paddle_tpu.distributed.fleet.meta_parallel import (
        PipelineParallel, PipelineLayer)
    from paddle_tpu.incubate.models import (
        GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
        gpt_pipeline_layers)

    from paddle_tpu.framework.flags import set_flags

    if jax.device_count() < 2:
        raise SystemExit(
            "--demo pp needs >=2 devices; run with "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8")
    # eager fusion OFF: every stage op runs under the pipeline program's
    # jit trace (tracer inputs) — recording those as poisons would be
    # noise about a loop that has no eager cycle at all
    set_flags({"FLAGS_eager_op_cache": False,
               "FLAGS_eager_chain_fusion": False,
               "FLAGS_eager_step_fusion": False})
    mesh = build_mesh(dp=1, pp=2, sharding=1, sep=1, mp=1,
                      devices=jax.devices()[:2])
    set_global_mesh(mesh)
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_hidden_layers=4,
                    num_attention_heads=4, intermediate_size=64,
                    max_position_embeddings=32, hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0,
                    use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    pl = PipelineLayer(gpt_pipeline_layers(model), num_stages=2,
                       loss_fn=GPTPretrainingCriterion(),
                       num_virtual_pipeline_stages=2)
    runner = PipelineParallel(pl, hcg=None)
    runner.accumulate_steps = 4
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=model.parameters())
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 128, (4, 16)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, 128, (4, 16)), jnp.int32)
    for _ in range(steps):
        runner.train_batch((ids, labels), opt)


def _demo_moe(steps):
    """Mixture-of-experts acceptance fixture: an MoELayer (gshard top-2
    gate) training loop. The expert dispatch fn closes over the layer —
    formerly an unkeyable closure that poisoned every cycle — but now
    stamps its (kind, gate, d_model, expert-axis, capacity) identity via
    dispatch.mark_collective, so the whole step promotes through the
    funnel: clean_promotion, zero steady-state retraces."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.incubate.distributed.models.moe import MoELayer

    set_flags({"FLAGS_eager_op_cache": True,
               "FLAGS_eager_chain_fusion": True,
               "FLAGS_eager_chain_fusion_min_count": 4,
               "FLAGS_eager_step_fusion": True,
               "FLAGS_eager_step_fusion_min_count": 5})
    paddle.seed(0)
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(
        (rng.standard_normal((16, 32)) * 0.5).astype(np.float32))
    moe = MoELayer(d_model=32, d_hidden=64, num_experts=8, gate="gshard")
    moe.train()
    opt = paddle.optimizer.SGD(learning_rate=1e-2,
                               parameters=moe.parameters())
    for _ in range(steps):
        y = moe(x)
        loss = paddle.mean(paddle.multiply(y, y)) + 0.01 * moe.l_aux
        loss.backward()
        opt.step()
        opt.clear_grad()


def _print_goodput(g):
    """One-line goodput rendering shared by --metrics and --url: the
    fraction, the buckets, and WHICH steps each non-productive bucket
    claimed (the PR 13 per-step attribution rings)."""
    print(f"goodput : {g['goodput']} over {g['steps']} step(s) "
          f"(p50 {g['step_ms_p50']} ms, buckets {g['buckets_s']})")
    for b, pretty in sorted((g.get("step_indices_pretty") or {}).items()):
        print(f"          {b} at step(s) {pretty}")


def _url_report(args) -> int:
    """`fusion_doctor --url http://host:port`: fetch the live /doctor
    report from a running process's telemetry server and render it
    exactly like a local run (JSON schema identical to --json, metrics/
    goodput sections present when the process has FLAGS_metrics armed)."""
    import urllib.request

    url = args.url.rstrip("/") + "/doctor"
    try:
        with urllib.request.urlopen(url, timeout=15) as r:
            report = json.loads(r.read().decode())
    except Exception as e:
        print(f"fusion_doctor: could not reach {url}: {e}\n"
              "is the process running with FLAGS_telemetry_port set?",
              file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(report, indent=2))
        return 0
    from paddle_tpu.profiler.explain import format_report
    print(format_report(report))
    if report.get("metrics"):
        from paddle_tpu.profiler.metrics import format_metrics_summary
        print(format_metrics_summary(report["metrics"]))
    if report.get("goodput"):
        _print_goodput(report["goodput"])
    return 0


def _watch_url(args) -> int:
    """`fusion_doctor --watch --url http://host:port`: poll the live
    /sentinel endpoint (--steps polls, ~2 s apart), one status line per
    window plus the full verdict on every latch transition. Exit 1 when
    drift is still latched at the end, so a supervisor can wire this as
    a probe."""
    import time as _time
    import urllib.request

    url = args.url.rstrip("/") + "/sentinel"
    was_degraded = None
    snap = {}
    for i in range(max(1, args.steps)):
        try:
            with urllib.request.urlopen(url, timeout=15) as r:
                snap = json.loads(r.read().decode())
        except Exception as e:
            print(f"fusion_doctor: could not reach {url}: {e}\n"
                  "is the process running with FLAGS_telemetry_port and "
                  "FLAGS_sentinel set?", file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(snap))
        else:
            checks = snap.get("checks") or {}
            state = "DRIFT" if snap.get("degraded") else (
                "armed" if snap.get("armed") else "disarmed")
            print(f"[{i:>3}] {state:<8} leg={snap.get('leg') or '-'} "
                  f"windows={snap.get('windows', 0)} "
                  f"checks={json.dumps(checks, sort_keys=True)}")
            if snap.get("degraded") != was_degraded:
                f = snap.get("finding")
                if snap.get("degraded") and f:
                    print(f"      verdict {f.get('reason')}: "
                          f"{f.get('message')}")
                elif was_degraded:
                    print("      recovered: bands clean again")
        was_degraded = bool(snap.get("degraded"))
        if i + 1 < max(1, args.steps):
            _time.sleep(2.0)
    return 1 if snap.get("degraded") else 0


def _print_sentinel(s):
    """Text rendering of the sentinel section (`--watch` local runs)."""
    if not s:
        return
    state = "DRIFT" if s.get("degraded") else "clean"
    print(f"sentinel: {state} | leg {s.get('leg') or '(self-calibrated)'} "
          f"| {s.get('windows', 0)} window(s), "
          f"checks {json.dumps(s.get('checks') or {}, sort_keys=True)}")
    for f in s.get("findings") or []:
        print(f"          {f.get('reason')}: {f.get('message')}")


def _cache_report(args) -> int:
    """`fusion_doctor --cache`: list the AOT executable store (kind,
    digest, size, age, environment-fingerprint match, label), report
    corrupt/quarantined/skewed entries, and with `--gc` run the size/age
    eviction manually."""
    from paddle_tpu.ops import aot_cache

    root = args.cache_dir or aot_cache.cache_dir()
    entries = aot_cache.store_entries(root)
    removed = []
    if args.gc:
        # the listing just CRC-verified every artifact: quarantine the
        # ones that failed so the sweep below removes them too
        for e in entries:
            if e["corrupt"] and not e["quarantined"]:
                p = os.path.join(root, e["file"])
                try:
                    os.replace(p, p + ".corrupt")
                except OSError:
                    pass
        removed = aot_cache.gc_store(root, purge_quarantine=True)
        entries = aot_cache.store_entries(root)
    n_corrupt = sum(1 for e in entries if e["corrupt"] or e["quarantined"])
    n_skew = sum(1 for e in entries
                 if e["fingerprint_match"] is False and not e["corrupt"]
                 and not e["quarantined"])
    total = sum(e["bytes"] for e in entries)
    if args.json:
        print(json.dumps({
            "dir": root, "entries": entries, "total_bytes": total,
            "corrupt": n_corrupt, "version_skew": n_skew,
            "fingerprint": aot_cache.fingerprint_digest(),
            "evicted": removed}, indent=2))
        return 0
    print(f"AOT executable store: {root}")
    print(f"  fingerprint {aot_cache.fingerprint_digest()} | "
          f"{len(entries)} artifact(s), {total / 1024:.1f} KiB | "
          f"{n_corrupt} corrupt/quarantined, {n_skew} version-skewed")
    if removed:
        print(f"  gc removed {len(removed)} file(s): "
              + ", ".join(removed[:8])
              + (" …" if len(removed) > 8 else ""))
    if entries:
        # provenance on a fleet-shared store: `host` names the member
        # that paid the export the rest of the fleet warm-starts from
        print(f"  {'kind':<7} {'digest':<12} {'size':>9} {'age':>8} "
              f"{'fp':>4} {'state':<8} {'host':<12} label")
        for e in entries:
            state = ("QUARANT" if e["quarantined"]
                     else "CORRUPT" if e["corrupt"] else "ok")
            fp = {True: "ok", False: "SKEW", None: "?"}[
                e["fingerprint_match"]]
            age = e["age_s"]
            age_s = f"{age / 3600:.1f}h" if age >= 3600 else f"{age:.0f}s"
            print(f"  {e['kind']:<7} {e.get('digest', '?')[:12]:<12} "
                  f"{e['bytes']:>9} {age_s:>8} {fp:>4} {state:<8} "
                  f"{(e.get('host') or '?')[:12]:<12} "
                  f"{e['label'] or ''}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fusion_doctor",
        description="explain why a training loop didn't promote/split "
                    "(fusion flight-recorder root-cause report)")
    ap.add_argument("script", nargs="?",
                    help="training script to run under the recorder")
    ap.add_argument("script_args", nargs=argparse.REMAINDER,
                    help="arguments passed to the script (after --)")
    ap.add_argument("--demo", choices=("dropout", "masked", "accum",
                                       "serve", "sample", "tenants",
                                       "dp", "pp", "moe", "metrics"),
                    help="run a built-in tiny GPT-ish demo loop instead "
                         "of a script (`dropout`: hoisted-key dropout "
                         "promotes cleanly; `accum`: a k=4 grad-"
                         "accumulation loop promotes as a super-cycle; "
                         "`serve`: a continuous-batching serving run "
                         "over a tight KV pool; `sample`: mixed "
                         "greedy/stochastic streams on a lag-1 "
                         "pipelined engine — sampler_mismatch refusal + "
                         "commit_lag_rollback; `tenants`: eight "
                         "tenants sharing a system prompt on a "
                         "prefix-cache + adapter + hot-swap engine; "
                         "`dp`: a sharded "
                         "data-parallel loop whose unkeyable grad "
                         "collective blocks promotion — "
                         "collective_unkeyed; `pp`: a pipe=2 × virtual=2 "
                         "interleaved pipeline promoting through the "
                         "spmd_fusion pipeline registry; `moe`: a keyed "
                         "gshard MoE layer riding the funnel; `metrics`: "
                         "the telemetry plane armed over a promoting "
                         "loop with an injected guardian skip — live "
                         "goodput/MFU)")
    ap.add_argument("--steps", type=int, default=20,
                    help="demo loop steps (requests, for --demo serve; "
                         "default 20)")
    ap.add_argument("--json", action="store_true",
                    help="print the report as JSON instead of text")
    ap.add_argument("--metrics", action="store_true",
                    help="arm the telemetry plane (FLAGS_metrics) for "
                         "the run and append the live registry summary "
                         "+ goodput accounting to the report")
    ap.add_argument("--cache", action="store_true",
                    help="inspect the persistent AOT executable store "
                         "(ops/aot_cache.py) instead of running a script: "
                         "list artifacts with fingerprint/corruption "
                         "state; combine with --gc to evict")
    ap.add_argument("--cache-dir", default=None,
                    help="AOT store root (default: the configured "
                         "FLAGS_aot_cache_dir / $PADDLE_TPU_CACHE_DIR/aot)")
    ap.add_argument("--url", default=None, metavar="http://host:port",
                    help="pull the report from a RUNNING process's "
                         "telemetry server /doctor endpoint "
                         "(FLAGS_telemetry_port) instead of running "
                         "anything locally")
    ap.add_argument("--lint", action="store_true",
                    help="run the promotion-safety static analyzer "
                         "(paddle_tpu/analysis, baseline applied) and "
                         "cross-reference runtime split/poison reasons "
                         "with the static findings that predicted them")
    ap.add_argument("--gc", action="store_true",
                    help="with --cache: run the size/age eviction now "
                         "(also removes quarantined *.corrupt files)")
    ap.add_argument("--watch", action="store_true",
                    help="arm the performance regression sentinel "
                         "(profiler/sentinel.py). With --url: poll the "
                         "running process's /sentinel endpoint (--steps "
                         "polls, one line each, exit 1 if drift is "
                         "latched). Locally: watch the --demo/script run "
                         "and append the sentinel verdict to the report")
    args = ap.parse_args(argv)
    if args.demo == "pp" and \
            "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        # the pipe demo needs a multi-device mesh; arm the emulated CPU
        # topology BEFORE the first jax import below
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            " --xla_force_host_platform_device_count=8").strip()
    if args.url:
        if args.watch:
            return _watch_url(args)
        return _url_report(args)
    if args.cache:
        return _cache_report(args)
    if not args.demo and not args.script:
        ap.error("either a script, --demo, --cache, or --url is required")

    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.profiler.events import EVENTS, clear_fusion_events
    from paddle_tpu.profiler.explain import explain, format_report

    clear_fusion_events()
    set_flags({"FLAGS_profiler_events": True})
    if args.watch:
        # short windows for a bounded doctor run: a 20-step demo should
        # still see a few evaluation windows (FLAGS_sentinel_window_s
        # governs long-running processes, not this)
        from paddle_tpu.profiler import sentinel as _sentinel
        _sentinel.arm(window_s=0.5)
    want_metrics = args.metrics or args.demo == "metrics"
    if want_metrics:
        from paddle_tpu.profiler.metrics import reset_metrics
        reset_metrics()
        set_flags({"FLAGS_metrics": True})
    try:
        if args.demo == "serve":
            _demo_serve(args.steps)
        elif args.demo == "sample":
            _demo_sample(args.steps)
        elif args.demo == "tenants":
            _demo_tenants(args.steps)
        elif args.demo == "dp":
            _demo_dp(args.steps)
        elif args.demo == "pp":
            _demo_pp(args.steps)
        elif args.demo == "moe":
            _demo_moe(args.steps)
        elif args.demo == "metrics":
            _demo_metrics(args.steps)
        elif args.demo:
            _demo(args.demo, args.steps)
        else:
            sa = args.script_args
            if sa and sa[0] == "--":
                sa = sa[1:]
            old_argv = sys.argv
            sys.argv = [args.script] + sa
            try:
                runpy.run_path(args.script, run_name="__main__")
            except SystemExit as e:
                if e.code not in (0, None):
                    print(f"fusion_doctor: script exited with {e.code} "
                          "(reporting on the events recorded so far)",
                          file=sys.stderr)
            finally:
                sys.argv = old_argv
    finally:
        set_flags({"FLAGS_profiler_events": False})

    report = explain(EVENTS.snapshot())
    if args.watch:
        from paddle_tpu.profiler import sentinel as _sentinel
        report["sentinel"] = _sentinel.sentinel_report()
        _sentinel.disarm()
    if args.lint:
        _attach_lint(report)
    if want_metrics:
        from paddle_tpu.profiler.metrics import (format_metrics_summary,
                                                 metrics_snapshot)
        from paddle_tpu.profiler.goodput import goodput_snapshot
        report["metrics"] = metrics_snapshot()
        report["goodput"] = goodput_snapshot()
        set_flags({"FLAGS_metrics": False})
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(format_report(report))
        if args.watch:
            _print_sentinel(report.get("sentinel") or {})
        if args.lint:
            _print_lint(report.get("lint") or {})
        if want_metrics:
            print(format_metrics_summary(report["metrics"]))
            _print_goodput(report["goodput"])
    return 0


def _attach_lint(report):
    """`fusion_doctor --lint`: run the static analyzer over the repo
    (suppression baseline applied) and cross-reference the RUNTIME
    split/poison/bypass reasons of this report with the STATIC findings
    carrying the same reason code — "this `rng_rekey` split was
    statically predicted at ops/random_ops.py:NN". One taxonomy, two
    observation times."""
    from paddle_tpu.analysis import analyze, Baseline, findings_to_dicts
    from paddle_tpu.analysis.baseline import DEFAULT_BASELINE

    findings = analyze()
    bl = Baseline.load(DEFAULT_BASELINE)
    live, muted = bl.split(findings)
    report["lint"] = {
        "findings": findings_to_dicts(live),
        "suppressed": len(muted),
        "stale_suppressions": len(bl.stale(findings)),
    }
    # runtime reasons observed in THIS window, by source section
    runtime = {}
    step = report.get("step") or {}
    for src in (step.get("split_reasons"), step.get("poisons"),
                (report.get("dispatch") or {}).get("bypass_reasons"),
                (report.get("chain") or {}).get("split_reasons")):
        for r in (src or {}):
            runtime[r] = runtime.get(r, 0) + (src[r].get("count") or 0)
    predicted = []
    for f in live:
        if runtime.get(f.reason_code):
            predicted.append(
                f"runtime `{f.reason_code}` (×{runtime[f.reason_code]}) was "
                f"statically predicted at {f.file}:{f.line} ({f.rule}: "
                f"{f.message})")
    report["lint"]["predicted"] = predicted
    report.setdefault("findings", []).extend(predicted)


def _print_lint(lint):
    n = len(lint.get("findings") or [])
    print(f"lint  : {n} unsuppressed static finding(s), "
          f"{lint.get('suppressed', 0)} suppressed, "
          f"{lint.get('stale_suppressions', 0)} stale suppression(s)")
    for f in (lint.get("findings") or [])[:12]:
        print(f"  - {f['file']}:{f['line']}: {f['rule']} "
              f"[{f['reason_code']}] {f['message']}")


if __name__ == "__main__":
    sys.exit(main())
