#!/usr/bin/env python
"""Metrics export: crash-safe JSONL sink + cross-process merge + render.

The registry (paddle_tpu/profiler/metrics.py) lives in one process; a
fleet has many. This tool is the boundary between them:

  * :class:`MetricsSink` — one file per process
    (``metrics-<pid>.jsonl``), one JSON line per snapshot, written
    through the shared atomic-write helpers (framework/io.py: tmp +
    fsync + rename + CRC-32 trailer) so a kill-9 mid-write can NEVER
    leave a torn file: the reader either sees the previous complete
    sink or the new one. ``write()`` is one-shot; ``start(interval_s)``
    runs a daemon thread for the periodic mode. History is bounded
    (``max_lines``, oldest dropped) so a week-long process keeps a
    week-long file from growing without bound.
  * :func:`read_sink` / :func:`merge_files` — parse sink files
    (CRC-verified when the trailer is present) and merge the LAST
    snapshot of each process's file into one fleet view through the
    per-metric ``METRIC_MERGE`` policy (profiler/metrics.py: counters
    and histogram buckets add; gauges sum, max, or last-wins per their
    contract entry — occupancy/tokens gauges ADD fleet-wide, watermarks
    take the max). Each row carries ``host`` + ``pid`` so
    tools/fleet_metrics.py can label per-host series.
  * CLI — merge sinks and render the result as Prometheus text
    exposition or the one-screen summary:

        python tools/metrics_export.py --merge /tmp/m/*.jsonl --prom
        python tools/metrics_export.py --merge a.jsonl b.jsonl
        python tools/metrics_export.py --snapshot out.jsonl   # this proc
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

__all__ = ["MetricsSink", "read_sink", "merge_files", "default_sink_path"]


def default_sink_path(root=None):
    root = root or os.environ.get("PADDLE_TPU_METRICS_DIR") \
        or "/tmp/paddle_tpu_metrics"
    return os.path.join(root, f"metrics-{os.getpid()}.jsonl")


class MetricsSink:
    """Periodic/one-shot JSONL sink for one process's registry."""

    def __init__(self, path=None, registry=None, max_lines=512):
        from paddle_tpu.profiler import metrics as _metrics
        self.path = path or default_sink_path()
        self._registry = registry or _metrics.REGISTRY
        self._max_lines = int(max_lines)
        self._lines = []
        self._thread = None
        self._stop = threading.Event()
        # resolved once: the host label cannot change mid-file (the
        # fleet merge keys per-host series on host:pid)
        try:
            import socket
            self._host = socket.gethostname()
        except Exception:
            self._host = ""

    def write(self):
        """Append one snapshot line and atomically rewrite the file.
        The whole file goes through _write_atomic (CRC trailer), so the
        sink survives kill -9 at any instant without torn content."""
        from paddle_tpu.framework.io import _write_atomic
        from paddle_tpu.profiler import goodput as _goodput
        row = {"ts": time.time(), "pid": os.getpid(), "host": self._host,
               "metrics": self._registry.snapshot(),
               "goodput": _goodput.ACCOUNTANT.snapshot()}
        self._lines.append(json.dumps(row, sort_keys=True))
        if len(self._lines) > self._max_lines:
            del self._lines[:-self._max_lines]
        _write_atomic(self.path,
                      ("\n".join(self._lines) + "\n").encode())
        return self.path

    # -- periodic mode ------------------------------------------------------
    def start(self, interval_s=15.0):
        """Write every `interval_s` seconds from a daemon thread until
        `stop()` (or process exit — the last atomic write stays
        complete)."""
        if self._thread is not None:
            return self

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.write()
                except Exception:
                    pass        # the sink must never take the server down

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="metrics-sink")
        self._thread.start()
        return self

    def stop(self, final_write=True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if final_write:
            try:
                self.write()
            except Exception:
                pass


def read_sink(path):
    """Parse one sink file into its snapshot rows (oldest first). The
    CRC trailer is verified when present (files written by MetricsSink
    always carry one); unparsable lines are skipped, never fatal."""
    from paddle_tpu.framework.io import read_verified_payload
    data = read_verified_payload(path, require_trailer=False)
    rows = []
    for line in data.decode(errors="replace").splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rows.append(json.loads(line))
        except ValueError:
            continue
    return rows


def merge_files(paths):
    """Fleet view: merge the LAST snapshot of every process sink."""
    from paddle_tpu.profiler.metrics import merge_snapshots
    snaps = []
    for p in paths:
        rows = read_sink(p)
        if rows:
            snaps.append(rows[-1].get("metrics") or {})
    return merge_snapshots(snaps)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="metrics_export",
        description="merge per-process metrics sinks / render exposition")
    ap.add_argument("--merge", nargs="+", default=None,
                    help="sink files (globs ok) to merge into one view")
    ap.add_argument("--prom", action="store_true",
                    help="render Prometheus text exposition instead of "
                         "the one-screen summary")
    ap.add_argument("--json", action="store_true",
                    help="print the merged snapshot as JSON")
    ap.add_argument("--snapshot", default=None, metavar="PATH",
                    help="write one snapshot of THIS process's registry "
                         "to PATH and exit (smoke/debug)")
    args = ap.parse_args(argv)

    from paddle_tpu.profiler import metrics as _metrics

    if args.snapshot:
        sink = MetricsSink(path=args.snapshot)
        print(sink.write())
        return 0
    if not args.merge:
        ap.error("--merge or --snapshot is required")
    paths = []
    for pat in args.merge:
        hit = sorted(glob.glob(pat))
        paths.extend(hit if hit else [pat])
    merged = merge_files(paths)
    if args.json:
        print(json.dumps(merged, indent=2, sort_keys=True))
    elif args.prom:
        sys.stdout.write(_metrics.exposition(merged))
    else:
        print(_metrics.format_metrics_summary(merged))
    return 0


if __name__ == "__main__":
    sys.exit(main())
