#!/usr/bin/env python
"""Perf baseline: the regression sentinel's checked-in band manager.

The runtime twin of tools/fusion_lint.py — same add/match/expire/
`--write-baseline` hygiene, applied to per-leg performance records
instead of static findings. A record is the JSON shape
`paddle_tpu.profiler.sentinel.capture_record` emits (bench.py embeds one
per leg under extra.sentinel_record; perf_smoke leg (q) writes its own);
the baseline (tools/perf_baselines.json) holds one tolerance-band entry
per leg.

Usage:

    # the CI gate (tier-1 wires exactly this through tests/
    # test_sentinel.py; exit 1 on any band violation OR unbaselined
    # record, exit 0 clean)
    python tools/perf_baseline.py --check records.json

    # seed/refresh entries from a fresh run's records (wide CPU-smoke
    # bands by default: --slack 25; tighten on the first real-TPU pass)
    python tools/perf_baseline.py --write-baseline records.json \
        --note "seeded from CPU smoke, band-tightening pass pending"

    # hygiene: list entries, report/drop legs no record exercises
    python tools/perf_baseline.py --list
    python tools/perf_baseline.py --check --expire records.json

Record files may be a single record object, a list, a JSON-lines stream
(bench.py output), or any nested document — every dict carrying the
record shape is extracted, so `--check BENCH_r06.json` just works.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))


def _parse_docs(path):
    """Whole-file JSON, falling back to JSON-lines (bench output)."""
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    try:
        return [json.loads(text)]
    except ValueError:
        docs = []
        for ln in text.splitlines():
            ln = ln.strip()
            if not ln:
                continue
            try:
                docs.append(json.loads(ln))
            except ValueError:
                continue
        if not docs:
            raise ValueError(f"{path}: neither JSON nor JSON-lines")
        return docs


def _extract_records(doc, out):
    """Recursively collect every dict that looks like a sentinel record
    (the capture_record shape)."""
    if isinstance(doc, dict):
        if {"leg", "kind", "compiles", "reasons"} <= set(doc):
            out.append(doc)
        else:
            for v in doc.values():
                _extract_records(v, out)
    elif isinstance(doc, list):
        for v in doc:
            _extract_records(v, out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="perf_baseline",
        description="per-leg performance baseline bands for the "
                    "regression sentinel (profiler/sentinel.py)")
    ap.add_argument("records", nargs="*",
                    help="record files (sentinel records, bench JSON-"
                         "lines, or any document embedding records)")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="baseline file (default: "
                         "tools/perf_baselines.json)")
    ap.add_argument("--check", action="store_true",
                    help="gate the records against their leg bands "
                         "(exit 1 on violation or unbaselined record)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="(re)seed a band entry per record leg")
    ap.add_argument("--note", default="",
                    help="with --write-baseline: the human note new "
                         "entries carry (required for new legs)")
    ap.add_argument("--slack", type=float, default=25.0,
                    help="with --write-baseline: latency/throughput "
                         "tolerance factor (default 25 — wide CPU-smoke "
                         "bands; drop toward 1.25 on real TPU passes)")
    ap.add_argument("--policy", default="",
                    help="with --write-baseline: the file-level band-"
                         "tightening policy line (kept if empty)")
    ap.add_argument("--expire", action="store_true",
                    help="drop baseline legs no provided record "
                         "exercises (otherwise stale legs only WARN)")
    ap.add_argument("--list", action="store_true", dest="list_legs",
                    help="print the baseline entries and exit")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report")
    args = ap.parse_args(argv)

    from paddle_tpu.profiler.sentinel import (DEFAULT_PERF_BASELINE,
                                              PerfBaseline)
    path = args.baseline or DEFAULT_PERF_BASELINE

    try:
        bl = PerfBaseline.load(path)
    except (ValueError, OSError) as e:
        print(f"perf_baseline: {e}", file=sys.stderr)
        return 2

    if args.list_legs:
        doc = {leg: {"kind": e.get("kind"), "note": e.get("note"),
                     "slack": e.get("slack"),
                     "bands": e.get("bands")}
               for leg, e in sorted(bl.legs.items())}
        if args.json:
            print(json.dumps({"version": 1, "path": path, "legs": doc},
                             indent=2))
        else:
            print(f"perf_baseline: {len(doc)} leg(s) in {path}")
            for leg, e in doc.items():
                print(f"  {leg:<16} [{e['kind']}] slack x{e['slack']} — "
                      f"{e['note']}")
        return 0

    records = []
    try:
        for p in args.records:
            if not os.path.exists(p):
                raise FileNotFoundError(f"record file {p!r} does not exist")
            for doc in _parse_docs(p):
                _extract_records(doc, records)
    except (OSError, ValueError) as e:
        print(f"perf_baseline: {e}", file=sys.stderr)
        return 2
    if not records:
        print("perf_baseline: no sentinel records found in the inputs "
              "(need dicts with leg/kind/compiles/reasons)",
              file=sys.stderr)
        return 2

    if args.write_baseline:
        try:
            if args.policy:
                bl.policy = args.policy
            for rec in records:
                bl.add(rec, note=args.note, slack=args.slack)
        except ValueError as e:
            print(f"perf_baseline: {e}", file=sys.stderr)
            return 2
        if args.expire:
            for leg in bl.expire(records):
                print(f"perf_baseline: expired retired leg {leg!r}")
        bl.save(path)
        print(f"perf_baseline: wrote {len(records)} leg entr"
              f"{'y' if len(records) == 1 else 'ies'} to {path} "
              f"(slack x{args.slack:g})")
        return 0

    # --check (also the default action when records are given)
    violations, passed, unbaselined = bl.split(records)
    stale = bl.stale(records)
    if args.expire and stale:
        bl.expire(records)
        bl.save(path)
    if args.json:
        print(json.dumps({
            "version": 1, "baseline": path,
            "checked": len(records),
            "passed": [r["leg"] for r in passed],
            "unbaselined": [r["leg"] for r in unbaselined],
            "stale_legs": stale,
            "violations": [{"leg": r["leg"], "findings": fs}
                           for r, fs in violations],
        }, indent=2))
    else:
        for rec, fs in violations:
            for f in fs:
                print(f"{rec['leg']}: {f['reason']} — {f['message']}")
        for rec in unbaselined:
            print(f"{rec['leg']}: no baseline entry (seed it with "
                  "--write-baseline)")
        for leg in stale:
            act = "expired" if args.expire else \
                "stale (no record exercises it; --expire to drop)"
            print(f"{leg}: {act}")
        print(f"perf_baseline: {len(violations)} violating, "
              f"{len(unbaselined)} unbaselined, {len(passed)} clean "
              f"record(s) against {path}")
    return 1 if (violations or unbaselined) else 0


if __name__ == "__main__":
    sys.exit(main())
