#!/usr/bin/env python
"""Fleet telemetry merge: N processes' metrics into ONE operator view.

The telemetry server (paddle_tpu/profiler/telemetry_server.py) exposes
one process; the JSONL sinks (tools/metrics_export.py) persist one
process; a fleet has many of both. This tool is the fleet boundary:

  * **scrape** — ``--url http://host:9100`` (repeatable) pulls
    ``/metrics.json`` + ``/goodput`` from live telemetry endpoints;
  * **sinks** — ``--sink '/shared/metrics/*.jsonl'`` (repeatable globs)
    reads the shared-directory JSONL sinks (the AOT-store-style analog:
    every host writes its own crash-safe file, any host merges them);
  * **merge** — one policy-honoring merge
    (profiler/metrics.METRIC_MERGE: sum for occurrence mass and
    fleet-additive gauges, max for watermarks, last for config values)
    PLUS a per-host-labeled exposition: every series gains a
    ``host="..."`` label so dashboards see both the fleet total and the
    straggler;
  * **fleet goodput + drift** — the fleet-truthful goodput is DERIVED
    from the summed goodput wall-time buckets (sum productive / sum
    total — exactly the hand-merge of the per-host accountant
    snapshots, pinned ±1e-9 by tests/test_telemetry_server.py), and the
    drift section names the slowest host: per-host step-time p50, the
    slowest/fastest ratio, per-host goodput and MFU, and each host's
    per-step skip/stall indices.

Usage::

    # scrape two live trainers
    python tools/fleet_metrics.py --url http://h1:9100 --url http://h2:9100

    # merge a shared sink directory into Prometheus text (host-labeled)
    python tools/fleet_metrics.py --sink '/shared/metrics/*.jsonl' --prom

    # one policy-merged exposition (no host labels), or the raw JSON view
    python tools/fleet_metrics.py --sink '...' --merged-prom
    python tools/fleet_metrics.py --url http://h1:9100 --json
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import urllib.request
from urllib.parse import urlparse

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

__all__ = ["fetch_host", "fetch_fleet", "sink_hosts", "relabel_snapshot",
           "fleet_view", "merge_goodput", "format_fleet_summary"]


def fetch_host(url, timeout=10):
    """Scrape one telemetry endpoint: (metrics snapshot, goodput
    snapshot). Raises on an unreachable host — the caller decides
    whether a partial fleet view is acceptable (the CLI warns and
    continues)."""
    base = url.rstrip("/")
    out = []
    for ep in ("/metrics.json", "/goodput"):
        with urllib.request.urlopen(base + ep, timeout=timeout) as r:
            out.append(json.loads(r.read().decode()))
    return out[0], out[1]


def fetch_fleet(url, timeout=10):
    """Scrape one host's `/fleet` elastic-fabric view
    (distributed/fabric.fleet_report): its membership generation plus —
    on the coordinator host — the whole fleet's per-host reported
    generations and `stale_hosts`. Returns None when the endpoint is
    absent (a pre-fabric server), unreachable, or unarmed; the fleet
    view then degrades to the metrics-only classification."""
    base = url.rstrip("/")
    try:
        with urllib.request.urlopen(base + "/fleet", timeout=timeout) as r:
            doc = json.loads(r.read().decode())
    except Exception:
        return None
    return doc if isinstance(doc, dict) and doc.get("armed") else None


def sink_hosts(patterns):
    """Read JSONL sinks into {host_label: (metrics, goodput)}. The host
    label is the sink row's `host:pid` when present (metrics_export
    stamps both), else the file's basename — unique per process either
    way."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import metrics_export
    hosts = {}
    paths = []
    for pat in patterns:
        hit = sorted(glob.glob(pat))
        paths.extend(hit if hit else [pat])
    for p in paths:
        rows = metrics_export.read_sink(p)
        if not rows:
            continue
        last = rows[-1]
        host = last.get("host")
        pid = last.get("pid")
        label = (f"{host}:{pid}" if host and pid
                 else os.path.splitext(os.path.basename(p))[0])
        hosts[label] = (last.get("metrics") or {},
                        last.get("goodput") or {})
    return hosts


def relabel_snapshot(snap, host):
    """Copy a registry snapshot with `host=<label>` added to every
    series — the per-host fleet exposition (distinct host labels keep
    every process's series separate through merge_snapshots)."""
    out = {}
    for name, fam in snap.items():
        series = []
        for row in fam.get("series", ()):
            row = json.loads(json.dumps(row))       # deep, JSON-typed copy
            labels = dict(row.get("labels") or {})
            labels["host"] = str(host)
            row["labels"] = labels
            series.append(row)
        out[name] = {"type": fam["type"], "help": fam.get("help", ""),
                     "labelnames": list(fam.get("labelnames", []))
                     + ["host"],
                     "series": series}
    return out


def merge_goodput(goodputs):
    """Hand-merge N accountant snapshots into the fleet-truthful view:
    wall-time buckets ADD (each host's wall clock is independent), fleet
    goodput = summed productive / summed total, throughput adds, and the
    per-step attribution indices keep their host prefix."""
    buckets = {}
    tokens_per_sec = 0.0
    steps = 0
    step_indices = {}
    for host, g in goodputs.items():
        for b, v in (g.get("buckets_s") or {}).items():
            buckets[b] = buckets.get(b, 0.0) + float(v)
        tokens_per_sec += float(g.get("tokens_per_sec") or 0.0)
        steps += int(g.get("steps") or 0)
        for b, idx in (g.get("step_indices") or {}).items():
            step_indices.setdefault(b, {})[host] = list(idx)
    total = sum(buckets.values())
    return {
        "steps": steps,
        "tokens_per_sec": round(tokens_per_sec, 2),
        "buckets_s": {b: round(v, 4) for b, v in sorted(buckets.items())},
        "goodput": (buckets.get("productive", 0.0) / total
                    if total > 0 else 0.0),
        "step_indices": step_indices,
    }


def _host_step_p50_ms(metrics, g):
    """One host's representative step-time p50 (ms): the training
    accountant's when it stepped, else the serving decode histogram."""
    p50 = float((g or {}).get("step_ms_p50") or 0.0)
    if p50 > 0:
        return p50
    from paddle_tpu.profiler.metrics import LogHistogram
    fam = (metrics or {}).get("serve_step_seconds") or {}
    for row in fam.get("series", ()):
        if row.get("count"):
            return LogHistogram.snapshot_quantile(row, 0.5) * 1e3
    return 0.0


def _fleet_generations(hosts, fleet):
    """{label: generation} + the stale label set, from per-host `/fleet`
    scrapes. Two stale signals agree by construction and are OR-ed here:
    a host's own reported generation trailing the fleet max, and the
    coordinator's `stale_hosts` list (fabric host_ids, mapped back to
    scrape labels via each member report's `host` field)."""
    generations = {}
    stale = set()
    host_id_to_label = {}
    coord_stale_ids = set()
    for label, rep in (fleet or {}).items():
        if not rep or label not in hosts:
            continue
        if rep.get("generation") is not None:
            generations[label] = int(rep["generation"])
        member = rep.get("member") or {}
        if member.get("host"):
            host_id_to_label[str(member["host"])] = label
        coord = rep.get("coordinator") or {}
        coord_stale_ids.update(str(h) for h in coord.get("stale_hosts")
                               or ())
    gmax = max(generations.values(), default=0)
    stale.update(h for h, g in generations.items() if g < gmax)
    stale.update(host_id_to_label.get(h, h) for h in coord_stale_ids)
    return generations, stale


def fleet_view(hosts, bands=None, leg=None, fleet=None):
    """{host: (metrics snapshot, goodput snapshot)} -> the full fleet
    report: policy-merged totals, host-labeled series, fleet goodput,
    and the drift section (slowest-host step-time ratio, per-host
    goodput/MFU, and — when a perf-baseline `bands` entry is given —
    per-host straggler classification against the SAME tolerance bands
    the regression sentinel enforces in-process). `fleet` optionally
    maps host labels to their `/fleet` scrapes (fetch_fleet): a host
    whose elastic-fabric generation trails the fleet's — or that the
    coordinator lists in `stale_hosts` — is classified `stale_member`
    and excluded from the drift ratio (its step times describe a mesh
    the fleet already rebuilt away from)."""
    from paddle_tpu.profiler.metrics import merge_snapshots
    merged = merge_snapshots([m for m, _ in hosts.values()])
    labeled = merge_snapshots([relabel_snapshot(m, h)
                               for h, (m, _) in hosts.items()])
    fleet_goodput = merge_goodput({h: g for h, (_, g) in hosts.items()})
    generations, stale = _fleet_generations(hosts, fleet)
    per_host = {}
    for h, (m, g) in sorted(hosts.items()):
        p50 = round(_host_step_p50_ms(m, g), 4)
        # a host that never finalized a goodput window and never served
        # is reporting, not running — it must not skew the drift stats
        active = int((g or {}).get("steps") or 0) > 0 or p50 > 0
        per_host[h] = {
            "status": ("stale_member" if h in stale
                       else "ok" if active else "no_data"),
            "goodput": (g or {}).get("goodput"),
            "mfu": (g or {}).get("mfu"),
            "tokens_per_sec": (g or {}).get("tokens_per_sec"),
            "step_p50_ms": p50,
            "step_indices": (g or {}).get("step_indices_pretty") or {},
        }
        if h in generations:
            per_host[h]["generation"] = generations[h]
    stepped = {h: v["step_p50_ms"] for h, v in per_host.items()
               if v["status"] == "ok" and v["step_p50_ms"] > 0}
    drift = {"per_host": per_host,
             "no_data_hosts": sorted(h for h, v in per_host.items()
                                     if v["status"] == "no_data")}
    if generations:
        drift["generations"] = generations
        drift["fleet_generation"] = max(generations.values())
    if stale:
        drift["stale_members"] = sorted(stale)
    # the ratio needs two measured hosts: a single host (or one measured
    # host among no_data peers) has no straggler to name, and a 1.0x
    # self-ratio would read as a finding
    if len(stepped) >= 2:
        slowest = max(stepped, key=stepped.get)
        fastest = min(stepped, key=stepped.get)
        drift.update({
            "slowest_host": slowest,
            "fastest_host": fastest,
            # the straggler statistic: >1.05 on a synchronous fleet
            # means the slow host gates every step
            "step_time_ratio": round(stepped[slowest]
                                     / stepped[fastest], 4)
            if stepped[fastest] > 0 else None,
        })
    if bands:
        drift["baseline_leg"] = leg
        drift["stragglers"] = _classify_hosts(hosts, per_host, bands)
    return {"hosts": sorted(hosts), "fleet_goodput": fleet_goodput,
            "drift": drift, "merged": merged, "labeled": labeled}


def _classify_hosts(hosts, per_host, bands):
    """Run each measured host's goodput snapshot through the sentinel's
    `classify` against a checked-in leg's bands. Only the dimensions a
    goodput snapshot carries (goodput floor, step-time bands, throughput
    floor) can fire — the event-histogram/compile bands need the
    in-process sentinel. {host: [findings]} for violating hosts only."""
    from paddle_tpu.profiler.sentinel import classify
    out = {}
    for h, (m, g) in sorted(hosts.items()):
        if per_host[h]["status"] != "ok":
            continue
        g = g or {}
        rec = {
            "leg": h, "kind": "train",
            "steps": int(g.get("steps") or 0),
            "serve_steps": 0,
            "goodput": float(g.get("goodput") or 0.0),
            "buckets_s": g.get("buckets_s") or {},
            "step_ms_p50": float(g.get("step_ms_p50") or 0.0),
            "step_ms_p99": float(g.get("step_ms_p99") or 0.0),
            "tokens_per_sec": float(g.get("tokens_per_sec") or 0.0),
            # closed-set dimensions a remote snapshot cannot see: keep
            # them band-neutral instead of trivially violating
            "reasons": {}, "compiles": {}, "hangs": 0, "skips": 0,
        }
        fs = classify(rec, bands)
        if fs:
            out[h] = fs
    return out


def format_fleet_summary(view):
    fg = view["fleet_goodput"]
    lines = ["================ fleet metrics ================",
             f"hosts   : {len(view['hosts'])} "
             f"({', '.join(view['hosts'][:8])}"
             + (" ..." if len(view["hosts"]) > 8 else "") + ")",
             f"goodput : {fg['goodput']:.4f} over {fg['steps']} step(s), "
             f"{fg['tokens_per_sec']} tok/s fleet-wide",
             f"buckets : " + " ".join(f"{b}={v}" for b, v
                                      in fg["buckets_s"].items() if v)]
    drift = view["drift"]
    if drift.get("fleet_generation") is not None:
        gens = drift.get("generations") or {}
        lines.append(
            f"fabric  : generation {drift['fleet_generation']} ("
            + ", ".join(f"{h}=g{g}" for h, g in sorted(gens.items()))
            + ")")
    if drift.get("step_time_ratio") is not None:
        lines.append(
            f"drift   : slowest {drift['slowest_host']} is "
            f"{drift['step_time_ratio']}x {drift['fastest_host']} "
            "(step-time p50 ratio)")
    if drift.get("no_data_hosts"):
        lines.append("no data : " + ", ".join(drift["no_data_hosts"])
                     + " (reporting but not running; excluded from drift)")
    if drift.get("stale_members"):
        lines.append(
            "stale   : " + ", ".join(drift["stale_members"])
            + " (heartbeating a generation the fleet rebuilt past; "
            "excluded from drift — restart or let the member rejoin)")
    for h, row in drift["per_host"].items():
        extra = ""
        idx = row.get("step_indices") or {}
        if idx:
            extra = " | " + "; ".join(f"{b} steps {s}"
                                      for b, s in sorted(idx.items()))
        if row["status"] == "no_data":
            lines.append(f"  {h:<24} no_data")
            continue
        if row["status"] == "stale_member":
            lines.append(f"  {h:<24} stale_member "
                         f"(generation {row.get('generation')})")
            continue
        lines.append(
            f"  {h:<24} goodput={row['goodput']} mfu={row['mfu']} "
            f"p50={row['step_p50_ms']}ms"
            f" tok/s={row['tokens_per_sec']}{extra}")
    for h, fs in sorted((drift.get("stragglers") or {}).items()):
        for f in fs:
            lines.append(f"  !! {h}: {f['reason']} — {f['message']} "
                         f"(leg {drift.get('baseline_leg')})")
    lines.append("===============================================")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fleet_metrics",
        description="merge N processes' telemetry (live /metrics.json "
                    "endpoints and/or shared JSONL sinks) into one "
                    "fleet view with per-host labels and a drift report")
    ap.add_argument("--url", action="append", default=[],
                    help="telemetry endpoint base URL (repeatable): "
                         "scrapes /metrics.json + /goodput")
    ap.add_argument("--sink", action="append", default=[],
                    help="JSONL sink file/glob (repeatable), as written "
                         "by tools/metrics_export.MetricsSink")
    ap.add_argument("--prom", action="store_true",
                    help="render the per-host-labeled fleet exposition")
    ap.add_argument("--merged-prom", action="store_true",
                    help="render the policy-merged exposition "
                         "(no host labels)")
    ap.add_argument("--json", action="store_true",
                    help="print the full fleet view as JSON")
    ap.add_argument("--leg", default=None,
                    help="classify every host against this perf-baseline "
                         "leg's tolerance bands (tools/perf_baselines."
                         "json) — cross-host straggler detection with "
                         "the regression sentinel's own classify()")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="with --leg: the perf baseline file (default: "
                         "tools/perf_baselines.json)")
    args = ap.parse_args(argv)
    if not args.url and not args.sink:
        ap.error("at least one --url or --sink is required")

    bands = None
    if args.leg:
        from paddle_tpu.profiler.sentinel import (DEFAULT_PERF_BASELINE,
                                                  PerfBaseline)
        bl = PerfBaseline.load(args.baseline or DEFAULT_PERF_BASELINE)
        entry = bl.match(args.leg)
        if entry is None:
            print(f"fleet_metrics: no perf-baseline entry for leg "
                  f"{args.leg!r} (run tools/perf_baseline.py --list)",
                  file=sys.stderr)
            return 1
        bands = entry.get("bands") or {}

    from paddle_tpu.profiler.metrics import exposition

    hosts = {}
    fleet = {}
    if args.sink:
        hosts.update(sink_hosts(args.sink))
    for url in args.url:
        label = urlparse(url).netloc or url
        try:
            hosts[label] = fetch_host(url)
        except Exception as e:
            print(f"fleet_metrics: {url} unreachable ({e}); continuing "
                  "with the rest of the fleet", file=sys.stderr)
            continue
        # best-effort elastic-fabric scrape: absent/unarmed -> None, and
        # the view degrades to the metrics-only classification
        fleet[label] = fetch_fleet(url)
    if not hosts:
        print("fleet_metrics: no reachable hosts / readable sinks",
              file=sys.stderr)
        return 1
    view = fleet_view(hosts, bands=bands, leg=args.leg, fleet=fleet)
    if args.json:
        print(json.dumps(view, indent=2, sort_keys=True, default=str))
    elif args.prom:
        sys.stdout.write(exposition(view["labeled"]))
    elif args.merged_prom:
        sys.stdout.write(exposition(view["merged"]))
    else:
        print(format_fleet_summary(view))
    return 0


if __name__ == "__main__":
    sys.exit(main())
