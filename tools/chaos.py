#!/usr/bin/env python
"""Chaos harness: prove the non-finite step guardian + crash-safe
checkpoints (PR 5) and the serving resilience layer (PR 7) survive
deliberately hostile conditions.

Training scenarios, each exercising one failure class a multi-day training
run WILL eventually hit:

  nan        a poisoned (all-NaN) batch lands in a PROMOTED dynamic-loss-
             scaled AMP loop (FLAGS_check_numerics + GradScaler riding ONE
             fused whole-step executable). Must hold: parameters bitwise
             unchanged, loss scale halved, no fusion split and no retrace
             (the skip happened in-graph), and the fusion doctor attributes
             the missing update to `nonfinite_skip`.

  exception  a fault hook (ops/guardian.inject_fault) raises ChaosFault
             from inside a dispatched op mid-step. Must hold: the exception
             surfaces cleanly to the training loop, the loop recovers on
             the next batch, parameters stay finite, and the firing is
             attributed as `injected_fault`.

  kill       a training subprocess (AMP + Momentum + LR schedule +
             EpochRange checkpoints) is SIGKILLed mid-epoch, then re-run.
             Must hold: the rerun resumes from the last atomic checkpoint
             (never a torn one), the optimizer step counter / LR schedule /
             loss scale continue exactly, and the final parameters match an
             uninterrupted run.

  warm_restart  PR 9: a training worker with the persistent AOT executable
             cache armed (FLAGS_aot_cache, ops/aot_cache.py) is SIGKILLed
             mid-run AFTER its fused step was promoted and stored. Must
             hold: the restarted process (same store + StepCheckpointer
             state) records ONE observation cycle and re-promotes the
             fused step at its first boundary with ZERO fresh compiles —
             no dispatch.retrace events, no chain compiles, no whole-step
             retrace; every executable deserializes from the store
             (aot.hit) — firing the restored step on the second cycle,
             and the combined loss trajectory matches an uninterrupted
             run. Then every artifact on disk is corrupted in place: a
             fresh worker must degrade to transparent recompiles
             (attributed `artifact_corrupt`, files quarantined), finish
             the run with an identical trajectory, and never crash.

Serving scenarios (PR 7), the same methodology against LLMEngine:

  serve_hang        an injected decode hang (guardian.inject_fault
                    "hang") trips the FLAGS_serve_step_timeout_ms
                    watchdog. Must hold: rung 1 (retry) recovers with the
                    decode program still compiled exactly once, rung 2
                    (two consecutive hangs) rebuilds and still finishes,
                    every stream stays token-identical to generate(), and
                    the doctor attributes `step_hang`.

  serve_fused_fault a poisoned fused decode output (`nan_output` on
                    "serve.decode") discards the launch and finishes the
                    in-flight streams through the eager generate() path.
                    Must hold: token-identical outputs, `decode_fault`
                    attributed, NO decode rebuild (the poison models a
                    transient fault), and the engine serves new requests
                    afterwards.

  serve_kill        a serving subprocess (ServeCheckpointer ticking every
                    step) is SIGKILLed mid-serve, then re-run against the
                    same checkpoint dir. Must hold: the restarted engine
                    restores every in-flight request and finishes each
                    stream BYTE-identically to an uninterrupted run —
                    including SAMPLED streams (PR 18), whose serialized
                    (seed, sampler) identity plus position-derived keys
                    make the resume a replay, not a re-roll.

  telemetry         PR 13: a "stall" fault (the wall-clock hang variant)
                    wedges two decode steps under an armed telemetry
                    server. Must hold: /healthz flips 503 within one
                    watchdog window, /readyz is 503 while the degraded
                    latch holds, both recover after the first clean
                    step, streams stay token-identical, and /goodput
                    names the stalled step indices.

  sentinel          PR 19: the perf regression sentinel, armed on short
                    self-calibrated windows, watches the same stall
                    storm. Must hold: the degraded latch flips within
                    one evaluation window with a machine-readable
                    verdict ({reason, metric, observed, bound} on the
                    REASON_CODES contract), /readyz is 503 with the
                    finding attached, the latch recovers on the first
                    clean window after the fault clears, and the storm's
                    streams finish token-identically.

Elastic-fleet scenarios (PR 20, distributed/fabric.py), multi-process:

  fleet_kill        N CPU workers rendezvous through the stdlib-TCP
                    coordinator, train a dp=N data-parallel loop (full
                    deterministic global batch per step, so every
                    replica computes identical state), and ONE worker is
                    SIGKILLed mid-accumulation. Must hold: the
                    coordinator declares the host lost within its lease
                    (`host_lost`), bumps the generation exactly once,
                    and the survivors — within seconds, not a re-warmup
                    — restore the latest StepCheckpointer snapshot,
                    rebuild the dp=N-1 mesh through the `mesh_mismatch`
                    split/re-promote path, and finish with a loss
                    trajectory allclose to an UNINTERRUPTED run on the
                    shrunk mesh. Then a restarted worker rejoins at the
                    current generation and re-promotes with ZERO fresh
                    compiles — every executable deserializes from the
                    shared AOT store (`fleet.rejoin`, aot.hit).

  fleet_flap        a slow-but-alive worker suppresses heartbeats for
                    most of — but less than — its lease while the fleet
                    trains on. Must hold: ZERO rebuilds, the generation
                    never moves, and both workers finish with finite,
                    identical trajectories. Lease grace absorbs slow;
                    only silence past the lease is loss.

Every decision flows through the PR 4 fusion flight recorder, so each
scenario's report embeds the doctor's verdict.

    JAX_PLATFORMS=cpu python tools/chaos.py                # all scenarios
    JAX_PLATFORMS=cpu python tools/chaos.py --scenario serve_hang --json
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# runnable from a source checkout without an install
sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))


# ---------------------------------------------------------------------------
# in-process scenarios
# ---------------------------------------------------------------------------

def _amp_loop_state(seed=0):
    import numpy as np
    import paddle_tpu as paddle

    rng = np.random.default_rng(seed)
    x = paddle.to_tensor(rng.standard_normal((4, 16)).astype(np.float32))
    w = paddle.to_tensor(rng.standard_normal((16, 16)).astype(np.float32),
                         stop_gradient=False)
    b = paddle.to_tensor(rng.standard_normal(16).astype(np.float32),
                         stop_gradient=False)
    opt = paddle.optimizer.SGD(learning_rate=1e-2, parameters=[w, b])
    scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0,
                                   decr_every_n_nan_or_inf=1)
    return x, w, b, opt, scaler


def _amp_step(x, w, b, opt, scaler):
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    loss = F.gelu(paddle.add(paddle.matmul(x, w), b)).sum()
    scaler.scale(loss).backward()
    scaler.step(opt)
    scaler.update()
    opt.clear_grad()


def _arm(min_count=5):
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.ops.dispatch import clear_dispatch_cache
    from paddle_tpu.ops import guardian
    from paddle_tpu.profiler.events import clear_fusion_events
    set_flags({"FLAGS_check_numerics": True,
               "FLAGS_eager_chain_fusion": True,
               "FLAGS_eager_step_fusion": True,
               "FLAGS_eager_chain_fusion_min_count": 3,
               "FLAGS_eager_step_fusion_min_count": min_count,
               "FLAGS_profiler_events": True})
    clear_dispatch_cache()
    clear_fusion_events()
    guardian.reset_guardian_stats()
    guardian.clear_faults()


def scenario_nan():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.ops import guardian
    from paddle_tpu.profiler import step_fusion_stats
    from paddle_tpu.profiler.explain import explain

    _arm()
    x, w, b, opt, scaler = _amp_loop_state()
    for _ in range(10):
        _amp_step(x, w, b, opt, scaler)
    s0 = step_fusion_stats()
    w_before = np.asarray(w._value).copy()
    scale_before = scaler.get_init_loss_scaling()

    xbad = paddle.to_tensor(np.full((4, 16), np.nan, np.float32))
    _amp_step(xbad, w, b, opt, scaler)
    guardian.flush()

    s1 = step_fusion_stats()
    stats = guardian.guardian_stats()
    rep = explain()
    failures = []
    if s0["fused_steps"] == 0:
        failures.append("AMP loop never promoted to a fused step")
    if s1["fused_steps"] <= s0["fused_steps"]:
        failures.append("poisoned batch did not run through the fused step")
    if s1["fallback_splits"] != s0["fallback_splits"]:
        failures.append("poisoned batch split the fused replay")
    if not np.array_equal(w_before, np.asarray(w._value)):
        failures.append("parameters changed on a non-finite batch")
    scale_after = scaler.get_init_loss_scaling()
    if scale_after != scale_before / 2:
        failures.append(
            f"loss scale {scale_before} -> {scale_after}, expected halving")
    if stats["steps_skipped"] < 1 or stats["scaler_backoffs"] < 1:
        failures.append(f"guardian stats missed the skip: {stats}")
    if rep["guardian"].get("nonfinite_skip", {}).get("count", 0) < 1:
        failures.append("doctor did not attribute nonfinite_skip")
    # recovery: a clean batch updates again without a retrace
    _amp_step(x, w, b, opt, scaler)
    s2 = step_fusion_stats()
    if np.array_equal(w_before, np.asarray(w._value)):
        failures.append("parameters did not update after recovery")
    if s2["retraces"] != s1["retraces"]:
        failures.append("recovery retraced the fused step")
    return {"ok": not failures, "failures": failures,
            "scale": [scale_before, scale_after],
            "guardian": stats, "doctor": rep["headline"]}


def scenario_exception():
    import numpy as np
    from paddle_tpu.ops import guardian
    from paddle_tpu.profiler.explain import explain

    _arm()
    # stay on per-op dispatch: fault hooks fire on REAL dispatches only —
    # chain/step replays defer their ops, so chaos against fused paths
    # poisons batch inputs instead (the nan scenario)
    from paddle_tpu.framework.flags import set_flags
    set_flags({"FLAGS_eager_chain_fusion": False,
               "FLAGS_eager_step_fusion": False})
    x, w, b, opt, scaler = _amp_loop_state(seed=1)
    for _ in range(4):
        _amp_step(x, w, b, opt, scaler)
    w_before = np.asarray(w._value).copy()

    inj = guardian.inject_fault("raise", op="gelu")
    caught = 0
    try:
        _amp_step(x, w, b, opt, scaler)
    except guardian.ChaosFault:
        caught = 1
        opt.clear_grad()
    finally:
        inj.remove()
    failures = []
    if not caught:
        failures.append("injected mid-step exception did not surface")
    if not np.array_equal(w_before, np.asarray(w._value)):
        failures.append("interrupted step modified parameters")
    # recovery: the loop keeps training afterwards
    for _ in range(3):
        _amp_step(x, w, b, opt, scaler)
    guardian.flush()
    stats = guardian.guardian_stats()
    rep = explain()
    if np.array_equal(w_before, np.asarray(w._value)):
        failures.append("loop did not recover after the exception")
    if not np.all(np.isfinite(np.asarray(w._value))):
        failures.append("parameters went non-finite after recovery")
    if stats["faults_injected"] != 1:
        failures.append(f"expected 1 injected fault, saw {stats}")
    if rep["guardian"].get("injected_fault", {}).get("count", 0) != 1:
        failures.append("doctor did not attribute injected_fault")
    return {"ok": not failures, "failures": failures,
            "guardian": stats, "doctor": rep["headline"]}


# ---------------------------------------------------------------------------
# serving scenarios (PR 7)
# ---------------------------------------------------------------------------

def _arm_serve():
    """Serving-scenario arming: flight recorder on, injectors/stats
    clean — and the numerics guardian OFF (a prior training scenario may
    have left it on; its lazy check queue must not interleave with the
    serving engine's jit-traced model calls)."""
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.ops import guardian
    from paddle_tpu.profiler.events import clear_fusion_events
    set_flags({"FLAGS_check_numerics": False,
               "FLAGS_profiler_events": True})
    guardian.flush()
    guardian.reset_thread_state()
    guardian.reset_guardian_stats()
    guardian.clear_faults()
    clear_fusion_events()


def _serve_setup():
    """Deterministic tiny GPT + engine workload shared by the serving
    scenarios (and bit-reproducible across processes: weights come from
    the framework RNG after paddle.seed)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.incubate.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=64,
                    max_position_embeddings=64, hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0,
                    use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.default_rng(11)
    prompts = [rng.integers(0, 128, int(n)).tolist() for n in (9, 6, 12)]
    return model, prompts


def _serve_refs(model, prompts, n):
    import numpy as np
    return [np.asarray(model.generate(np.asarray([p], np.int64),
                                      max_new_tokens=n,
                                      do_sample=False)._value)[0].tolist()
            for p in prompts]


def scenario_serve_hang():
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.ops import guardian
    from paddle_tpu.profiler.events import clear_fusion_events
    from paddle_tpu.profiler.explain import explain
    from paddle_tpu.serving import LLMEngine, FINISHED

    _arm_serve()
    set_flags({"FLAGS_serve_step_timeout_ms": 2000})
    model, prompts = _serve_setup()
    refs = _serve_refs(model, prompts, 8)
    failures = []
    try:
        # -- rung 1: one hang -> retry, same executable ---------------------
        clear_fusion_events()
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        reqs = [engine.add_request(p, max_new_tokens=8) for p in prompts]
        for _ in range(3):
            engine.step()
        guardian.inject_fault("hang", op="serve.decode", times=1)
        engine.run()
        guardian.clear_faults()
        st = engine.stats()
        if st["hangs"] < 1:
            failures.append("watchdog never fired on the injected hang")
        if st["decode_compiles"] != 1:
            failures.append(
                f"rung 1 (retry) recompiled decode "
                f"{st['decode_compiles']}x, expected exactly 1")
        for r, ref in zip(reqs, refs):
            if r.state != FINISHED or r.generated != ref:
                failures.append(
                    f"stream {r.rid} not token-identical after hang "
                    f"recovery (state {r.state})")
        rep = explain()
        if rep["serving"]["hangs"] < 1 \
                or "step_hang" not in rep["serving"]["reasons"]:
            failures.append("doctor did not attribute step_hang")
        if rep["verdict"] != "serving_degraded":
            failures.append(
                f"doctor verdict {rep['verdict']!r}, expected "
                "serving_degraded")

        # -- rung 2: two consecutive hangs -> rebuild, still finishes -------
        engine2 = LLMEngine(model, max_batch_size=2, block_size=4)
        reqs2 = [engine2.add_request(p, max_new_tokens=8) for p in prompts]
        for _ in range(3):
            engine2.step()
        guardian.inject_fault("hang", op="serve.decode", times=2)
        engine2.run()
        guardian.clear_faults()
        st2 = engine2.stats()
        if st2["hangs"] != 2:
            failures.append(f"expected 2 hangs at rung 2, saw "
                            f"{st2['hangs']}")
        if st2["decode_compiles"] != 2:
            failures.append(
                f"rung 2 (rebuild) should trace exactly once more "
                f"(saw {st2['decode_compiles']} compiles)")
        for r, ref in zip(reqs2, refs):
            if r.state != FINISHED or r.generated != ref:
                failures.append(
                    f"stream {r.rid} not token-identical after rebuild")
        return {"ok": not failures, "failures": failures,
                "hangs": [st["hangs"], st2["hangs"]],
                "doctor": rep["headline"]}
    finally:
        guardian.clear_faults()
        set_flags({"FLAGS_serve_step_timeout_ms": 0})


def scenario_serve_fused_fault():
    from paddle_tpu.ops import guardian
    from paddle_tpu.profiler.events import clear_fusion_events
    from paddle_tpu.profiler.explain import explain
    from paddle_tpu.serving import LLMEngine, FINISHED

    _arm_serve()
    model, prompts = _serve_setup()
    refs = _serve_refs(model, prompts, 8)
    failures = []
    clear_fusion_events()
    engine = LLMEngine(model, max_batch_size=2, block_size=4)
    reqs = [engine.add_request(p, max_new_tokens=8) for p in prompts]
    for _ in range(3):
        engine.step()
    guardian.inject_fault("nan_output", op="serve.decode", times=1)
    engine.run()
    guardian.clear_faults()
    st = engine.stats()
    if st["eager_fallbacks"] < 1:
        failures.append("poisoned decode did not trigger the eager "
                        "fallback")
    if st["decode_compiles"] != 1:
        failures.append(
            f"transient poison must not rebuild decode (saw "
            f"{st['decode_compiles']} compiles)")
    for r, ref in zip(reqs, refs):
        if r.state != FINISHED or r.generated != ref:
            failures.append(
                f"stream {r.rid} fallback not token-identical "
                f"(state {r.state})")
    rep = explain()
    if "decode_fault" not in rep["serving"]["reasons"]:
        failures.append("doctor did not attribute decode_fault")
    # the engine must still serve NEW work on the compiled path
    again = engine.add_request(prompts[0], max_new_tokens=8)
    engine.run()
    if again.state != FINISHED or again.generated != refs[0]:
        failures.append("engine did not serve new requests after the "
                        "fallback")
    if engine.stats()["decode_compiles"] != 1:
        failures.append("post-fault serving retraced the decode program")
    return {"ok": not failures, "failures": failures,
            "guardian": guardian.guardian_stats(),
            "doctor": rep["headline"]}


def scenario_telemetry():
    """PR 13: the live observability plane under an injected wedge. A
    serving engine runs with the telemetry server armed while a chaos
    "stall" fault (guardian.inject_fault — the wall-clock hang variant)
    wedges two consecutive decode steps for the full watchdog budget.
    Must hold: a scraper polling /healthz at ~100 Hz observes the flip
    to unhealthy (503) within one watchdog window of the hang, /readyz
    reads 503 while the degraded latch is set, BOTH recover after the
    first clean decode step, every stream still finishes
    token-identically, and /goodput names the stalled step indices."""
    import threading
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.ops import guardian
    from paddle_tpu.profiler import telemetry_server
    from paddle_tpu.profiler.metrics import reset_metrics
    from paddle_tpu.serving import LLMEngine, FINISHED

    _arm_serve()
    budget_ms = 150
    set_flags({"FLAGS_serve_step_timeout_ms": budget_ms,
               "FLAGS_metrics": True})
    reset_metrics()
    model, prompts = _serve_setup()
    refs = _serve_refs(model, prompts, 8)
    failures = []
    srv = telemetry_server.start(port=0)
    samples = []                    # (t, endpoint, status, body)
    stop = threading.Event()

    def probe(ep):
        return telemetry_server.probe_endpoint(f"{srv.url}/{ep}",
                                               timeout=5)

    def scraper():
        while not stop.is_set():
            for ep in ("healthz", "readyz"):
                try:
                    st, body = probe(ep)
                    samples.append((time.perf_counter(), ep, st, body))
                except Exception:
                    pass
            time.sleep(0.01)        # ~100 Hz across both endpoints

    try:
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        reqs = [engine.add_request(p, max_new_tokens=8) for p in prompts]
        for _ in range(3):
            engine.step()           # warm + heartbeat established
        st0, _ = probe("healthz")
        if st0 != 200:
            failures.append("healthz not 200 on a healthy stepping "
                            "engine")
        thr = threading.Thread(target=scraper, daemon=True)
        thr.start()
        t_hang = time.perf_counter()
        guardian.inject_fault("stall", op="serve.decode", times=2)
        engine.run()                # wedges ~2x budget, then recovers
        guardian.clear_faults()
        stop.set()
        thr.join(timeout=10)
        # -- liveness flipped within one watchdog window ----------------
        bad_health = [t for t, ep, st, _ in samples
                      if ep == "healthz" and st == 503]
        if not bad_health:
            failures.append("healthz never flipped unhealthy during the "
                            "injected stall")
        else:
            # scrape cadence (~20ms across endpoints) rides on top of
            # the one-window bound; allow it as slack
            flip_s = min(bad_health) - t_hang
            if flip_s > 2 * budget_ms / 1e3 + 0.25:
                failures.append(
                    f"healthz took {flip_s:.3f}s to flip (watchdog "
                    f"window {budget_ms}ms)")
        if not any(ep == "readyz" and st == 503
                   for _, ep, st, _ in samples):
            failures.append("readyz never reported the degraded latch")
        # -- recovery ---------------------------------------------------
        st_h, body_h = probe("healthz")
        st_r, body_r = probe("readyz")
        if st_h != 200:
            failures.append(f"healthz did not recover (still {st_h}: "
                            f"{body_h})")
        if st_r != 200:
            failures.append(f"readyz did not recover (still {st_r})")
        for r, ref in zip(reqs, refs):
            if r.state != FINISHED or r.generated != ref:
                failures.append(
                    f"stream {r.rid} not token-identical through the "
                    f"stall (state {r.state})")
        _, good = probe("goodput")
        stalled = (good.get("step_indices") or {}).get("stalled") or []
        if len(stalled) < 1:
            failures.append("goodput did not attribute the stalled step "
                            "indices")
        hangs = engine.stats()["hangs"]
        if hangs < 2:
            failures.append(f"expected 2 watchdog firings, saw {hangs}")
        return {"ok": not failures, "failures": failures,
                "hangs": hangs, "scrapes": len(samples),
                "unhealthy_scrapes": len(bad_health),
                "stalled_steps": stalled}
    finally:
        stop.set()
        guardian.clear_faults()
        telemetry_server.stop()
        set_flags({"FLAGS_serve_step_timeout_ms": 0,
                   "FLAGS_metrics": False})


def scenario_sentinel():
    """PR 19: the perf regression sentinel under an injected drift. A
    serving engine runs with the telemetry server up and the sentinel
    armed on short self-calibrated windows; after >=1 clean window, a
    chaos "stall" fault wedges two consecutive decode steps (a split/
    hang storm the baseline histogram has never seen). Must hold: the
    sentinel flips its degraded latch within one evaluation window of
    the storm with a machine-readable verdict (split_regression family,
    {reason, metric, observed, bound}), /readyz reads 503 with that
    finding attached under "sentinel", /sentinel serves the full
    snapshot schema, the latch RECOVERS on the first clean window after
    the fault clears (readyz 200 again), and the streams served through
    the storm finish token-identically."""
    import numpy as np
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.ops import guardian
    from paddle_tpu.profiler import sentinel as snt
    from paddle_tpu.profiler import telemetry_server
    from paddle_tpu.profiler.metrics import reset_metrics
    from paddle_tpu.serving import LLMEngine, FINISHED

    _arm_serve()
    budget_ms = 120
    window_s = 0.4
    set_flags({"FLAGS_serve_step_timeout_ms": budget_ms,
               "FLAGS_metrics": True})
    reset_metrics()
    snt.disarm()
    snt.SENTINEL.reset()
    model, prompts = _serve_setup()
    refs = _serve_refs(model, prompts, 8)
    failures = []
    srv = telemetry_server.start(port=0)

    def probe(ep):
        return telemetry_server.probe_endpoint(f"{srv.url}/{ep}",
                                               timeout=5)

    def filler(engine, n=3):
        rng = np.random.default_rng(engine.stats()["steps"] + 1)
        for k in (5, 7, 9)[:n]:
            engine.add_request(rng.integers(0, 128, k).tolist(),
                               max_new_tokens=4)
        engine.run()

    try:
        engine = LLMEngine(model, max_batch_size=4, block_size=4)
        filler(engine)              # decode compiled pre-calibration
        snt.arm(window_s=window_s)
        deadline = time.perf_counter() + 60
        while snt.SENTINEL.windows < 2 and time.perf_counter() < deadline:
            filler(engine)
        if snt.SENTINEL.band_source != "self":
            failures.append("sentinel never self-calibrated on clean "
                            "serve traffic")
        if snt.SENTINEL.degraded:
            failures.append("sentinel degraded on CLEAN traffic before "
                            "any fault was injected")
        st0, body0 = probe("readyz")
        if st0 != 200 or not body0.get("sentinel", {}).get("armed"):
            failures.append(f"readyz pre-fault not 200/armed (st={st0})")

        # -- the storm: two wedged decode steps mid-stream --------------
        t_inject = time.perf_counter()
        guardian.inject_fault("stall", op="serve.decode", times=2)
        reqs = [engine.add_request(p, max_new_tokens=8) for p in prompts]
        engine.run()                # wedges ~2x budget, then recovers
        guardian.clear_faults()
        t_evidence = time.perf_counter()   # storm is now in the counters
        while not snt.SENTINEL.degraded \
                and time.perf_counter() < deadline:
            filler(engine, n=1)     # drive the window edge
        trip_s = time.perf_counter() - t_inject
        detect_s = time.perf_counter() - t_evidence
        if not snt.SENTINEL.degraded:
            failures.append("the stall storm never tripped the sentinel")
        elif detect_s > window_s + 5.0:
            # detection latency, not total trip time: engine.run() under a
            # wedged budget stretches with host load, the window edge must
            # not (one window + filler-round slop).
            failures.append(f"sentinel took {detect_s:.2f}s after the "
                            f"storm landed to trip (window {window_s}s)")
        finding = dict(snt.SENTINEL.finding or {})
        if finding.get("reason") not in ("split_regression",
                                         "compile_storm", "perf_drift",
                                         "latency_drift"):
            failures.append(f"verdict {finding.get('reason')!r} is not "
                            "a REASON_CODES drift verdict")
        if not {"metric", "observed", "bound",
                "message"} <= set(finding):
            failures.append(f"finding not machine-readable: {finding}")
        st_r, body_r = probe("readyz")
        if st_r != 503:
            failures.append(f"readyz not 503 while degraded (st={st_r})")
        rz_finding = (body_r.get("sentinel") or {}).get("finding") or {}
        if rz_finding.get("reason") != finding.get("reason"):
            failures.append("readyz did not attach the sentinel finding")
        st_s, body_s = probe("sentinel")
        if st_s != 200 or not {"armed", "degraded", "finding", "windows",
                               "checks", "history",
                               "last_record"} <= set(body_s):
            failures.append("/sentinel snapshot schema incomplete")

        # -- recovery ---------------------------------------------------
        deadline = time.perf_counter() + 60
        while snt.SENTINEL.degraded and time.perf_counter() < deadline:
            filler(engine, n=1)
        if snt.SENTINEL.degraded:
            failures.append("sentinel never recovered after the fault "
                            "cleared")
        st_h, _ = probe("readyz")
        if st_h != 200:
            failures.append(f"readyz did not recover (still {st_h})")
        recovered = any(h.get("verdict") == "clean"
                        for h in snt.SENTINEL.history)
        if not recovered:
            failures.append("no clean window recorded after recovery")
        for r, ref in zip(reqs, refs):
            if r.state != FINISHED or r.generated != ref:
                failures.append(
                    f"stream {r.rid} not token-identical through the "
                    f"storm (state {r.state})")
        return {"ok": not failures, "failures": failures,
                "trip_s": round(trip_s, 3),
                "detect_s": round(detect_s, 3),
                "verdict": finding.get("reason"),
                "finding": finding,
                "windows": snt.SENTINEL.windows,
                "checks": dict(snt.SENTINEL.checks)}
    finally:
        guardian.clear_faults()
        snt.disarm()
        snt.SENTINEL.reset()
        telemetry_server.stop()
        set_flags({"FLAGS_serve_step_timeout_ms": 0,
                   "FLAGS_metrics": False})


def serve_child_main(args):
    """One resumable serving run (invoked as `chaos.py --serve-child`):
    deterministic engine + workload, ServeCheckpointer ticking every
    step, optional SIGKILL at a chosen engine step. Writes {rid: tokens}
    JSON on completion."""
    from paddle_tpu.incubate.checkpoint import ServeCheckpointer
    from paddle_tpu.serving import LLMEngine

    model, prompts = _serve_setup()
    engine = LLMEngine(model, max_batch_size=2, block_size=4)
    ck = ServeCheckpointer(args.ckpt_dir, save_every_n_steps=1,
                           max_checkpoints=3)
    restored = engine.restore_state(ck.restore())
    if not restored:
        for i, p in enumerate(prompts):
            kw = {}
            if i % 2:
                # every other stream samples: (seed, prompt, sampler) must
                # reproduce byte-identically across the kill-9 resume —
                # the serialized sampler identity + fold_in(seed, position)
                # keys make the replayed stream a replay, not a re-roll
                kw = dict(temperature=0.9, top_k=20, top_p=0.9,
                          seed=4242 + i)
            engine.add_request(p, max_new_tokens=10, request_id=f"s{i}",
                               **kw)
    n = 0
    while True:
        if args.kill_at is not None and n == int(args.kill_at):
            os.kill(os.getpid(), signal.SIGKILL)
        alive = engine.step()
        n += 1
        ck.tick(n, engine.state_payload())
        if not alive:
            break
    out = {r.rid: list(r.generated)
           for r in engine.requests.values()}
    out["__resumed__"] = len(restored)
    with open(args.out, "w") as f:
        json.dump(out, f)
    return 0


def _spawn_serve_child(ckpt_dir, out, kill_at=None, timeout=300):
    cmd = [sys.executable, os.path.abspath(__file__), "--serve-child",
           "--ckpt-dir", ckpt_dir, "--out", out]
    if kill_at is not None:
        cmd += ["--kill-at", str(kill_at)]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, env=env)


def scenario_serve_kill():
    failures = []
    with tempfile.TemporaryDirectory() as tmp:
        ck_a = os.path.join(tmp, "interrupted")
        ck_b = os.path.join(tmp, "clean")
        out_resumed = os.path.join(tmp, "resumed.json")
        out_clean = os.path.join(tmp, "clean.json")

        # run 1: killed after 4 engine steps (streams mid-flight)
        r1 = _spawn_serve_child(ck_a, out_resumed, kill_at=4)
        if r1.returncode != -signal.SIGKILL:
            failures.append(
                f"expected SIGKILL death, rc={r1.returncode} "
                f"stderr={r1.stderr[-500:]}")
        if os.path.exists(out_resumed):
            failures.append("killed serve run still wrote final output")

        # run 2: same ckpt dir — must restore and finish every stream
        r2 = _spawn_serve_child(ck_a, out_resumed)
        if r2.returncode != 0:
            failures.append(f"resumed serve run failed: "
                            f"{r2.stderr[-800:]}")

        # reference: uninterrupted run
        r3 = _spawn_serve_child(ck_b, out_clean)
        if r3.returncode != 0:
            failures.append(f"reference serve run failed: "
                            f"{r3.stderr[-800:]}")

        if not failures:
            with open(out_resumed) as f:
                res = json.load(f)
            with open(out_clean) as f:
                ref = json.load(f)
            if res.pop("__resumed__") < 1:
                failures.append("restarted engine restored no requests")
            ref.pop("__resumed__")
            if set(res) != set(ref):
                failures.append(
                    f"stream sets differ: {sorted(res)} vs {sorted(ref)}")
            for rid in sorted(set(res) & set(ref)):
                if res[rid] != ref[rid]:
                    failures.append(
                        f"stream {rid} not byte-identical after kill-9 "
                        "resume")
    return {"ok": not failures, "failures": failures}


def tenant_child_main(args):
    """One resumable HOT-SWAP serving run (invoked as `chaos.py
    --tenant-child`): a hot_swap engine in lockstep (batch >= streams),
    ServeCheckpointer ticking every step, and a live weight swap staged
    once every stream has >= 3 tokens — a TOKEN-space boundary, so the
    cutover lands at the same token index in every run regardless of how
    resume re-prefills re-shuffle the step count. `--kill-mode staged`
    SIGKILLs between stage and commit (the pending set must die with the
    process); `--kill-mode committed` SIGKILLs after the cutover has
    been checkpointed (the restart must refuse to resume under the OLD
    weights: torn_swap). Writes {rid: tokens} JSON on completion plus
    `__torn_refusals__` — how many restores the torn-swap guard bounced
    before the child loaded the matching weight set."""
    import numpy as np
    from paddle_tpu.incubate.checkpoint import ServeCheckpointer
    from paddle_tpu.serving import LLMEngine, ServeRefusal

    SWAP_TOKENS = 3
    model, prompts = _serve_setup()
    # the incoming weight set, derived from the SEEDED construction
    # weights before anything mutates them: bit-reproducible in every
    # child process, killed or clean
    w2 = [np.asarray(p._value) * np.float32(1.0001)
          for p in model.parameters()]
    engine = LLMEngine(model, max_batch_size=4, block_size=4,
                       hot_swap=True)
    ck = ServeCheckpointer(args.ckpt_dir, save_every_n_steps=1,
                           max_checkpoints=3)
    torn = 0
    payload = ck.restore()
    try:
        restored = engine.restore_state(payload)
    except ServeRefusal as e:
        if e.reason != "torn_swap":
            raise
        # the snapshot was taken under the NEW weights: load them first
        # (the supervisor pattern), then resume — never decode a single
        # token against the torn set
        torn = 1
        engine.swap_weights(w2)
        restored = engine.restore_state(payload)
    if not restored:
        for i, p in enumerate(prompts):
            engine.add_request(p, max_new_tokens=10, request_id=f"s{i}")
    n = 0
    while True:
        live = [r for r in engine.requests.values() if not r.finished]
        if engine.weight_epoch == 0 and live \
                and all(len(r.generated) >= SWAP_TOKENS for r in live):
            if args.kill_mode == "staged":
                # mid-hot-swap: staged, never committed — the pending
                # weights must die with the process
                engine.stage_weights(w2)
                os.kill(os.getpid(), signal.SIGKILL)
            engine.swap_weights(w2)
            if args.kill_mode == "committed":
                # cutover done; checkpoint it, then die before serving
                # another step under the new epoch
                ck.tick(n + 1000, engine.state_payload())
                os.kill(os.getpid(), signal.SIGKILL)
        alive = engine.step()
        n += 1
        ck.tick(n, engine.state_payload())
        if not alive:
            break
    out = {r.rid: list(r.generated) for r in engine.requests.values()}
    out["__resumed__"] = len(restored)
    out["__torn_refusals__"] = torn
    out["__epoch__"] = engine.weight_epoch
    with open(args.out, "w") as f:
        json.dump(out, f)
    return 0


def _spawn_tenant_child(ckpt_dir, out, kill_mode=None, timeout=300):
    cmd = [sys.executable, os.path.abspath(__file__), "--tenant-child",
           "--ckpt-dir", ckpt_dir, "--out", out]
    if kill_mode:
        cmd += ["--kill-mode", kill_mode]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, env=env)


def scenario_tenant_swap():
    """PR 17: SIGKILL around a live weight hot-swap. Three runs share
    the deterministic child: clean (the reference), killed between
    stage and commit (the staged set must vanish with the process), and
    killed after the committed cutover was checkpointed (the restart
    must be REFUSED under the old weights — torn_swap — then finish
    byte-identically once the matching set is loaded)."""
    failures = []
    with tempfile.TemporaryDirectory() as tmp:
        out_clean = os.path.join(tmp, "clean.json")
        r0 = _spawn_tenant_child(os.path.join(tmp, "ck_clean"), out_clean)
        if r0.returncode != 0:
            failures.append(f"clean tenant run failed: {r0.stderr[-800:]}")
            return {"ok": False, "failures": failures}
        with open(out_clean) as f:
            ref = json.load(f)
        if ref["__epoch__"] != 1:
            failures.append(
                f"clean run served epoch {ref['__epoch__']}, expected 1")

        for mode, want_torn in (("staged", 0), ("committed", 1)):
            ck = os.path.join(tmp, f"ck_{mode}")
            out = os.path.join(tmp, f"{mode}.json")
            r1 = _spawn_tenant_child(ck, out, kill_mode=mode)
            if r1.returncode != -signal.SIGKILL:
                failures.append(
                    f"[{mode}] expected SIGKILL death, rc={r1.returncode} "
                    f"stderr={r1.stderr[-500:]}")
                continue
            if os.path.exists(out):
                failures.append(f"[{mode}] killed run wrote final output")
            r2 = _spawn_tenant_child(ck, out)
            if r2.returncode != 0:
                failures.append(
                    f"[{mode}] restarted run failed: {r2.stderr[-800:]}")
                continue
            with open(out) as f:
                res = json.load(f)
            if res["__resumed__"] < 1:
                failures.append(f"[{mode}] restart restored no requests")
            if res["__torn_refusals__"] != want_torn:
                failures.append(
                    f"[{mode}] torn-swap refusals: "
                    f"{res['__torn_refusals__']}, expected {want_torn}")
            if res["__epoch__"] < 1:
                failures.append(
                    f"[{mode}] restart finished on epoch "
                    f"{res['__epoch__']} — streams decoded against the "
                    "old weights")
            for rid in sorted(k for k in ref if not k.startswith("__")):
                if res.get(rid) != ref[rid]:
                    failures.append(
                        f"[{mode}] stream {rid} not byte-identical "
                        "through the kill/restart cutover")
    return {"ok": not failures, "failures": failures}


# ---------------------------------------------------------------------------
# warm-restart scenario (PR 9): AOT store + StepCheckpointer child
# ---------------------------------------------------------------------------

def aot_child_main(args):
    """One AOT-warm-startable training run (invoked as `chaos.py
    --aot-child`): deterministic per-step batches, SGD, the persistent
    executable store armed, StepCheckpointer ticking every step so a
    restart resumes STATE from the checkpoint and COMPILATION from the
    store. Writes a JSON report: per-step losses, the first loop
    iteration (relative to this process) that fired a fused step, and the
    compile/AOT event counts the parent asserts on."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.incubate.checkpoint import StepCheckpointer
    from paddle_tpu.profiler import (dispatch_cache_stats,
                                     chain_fusion_stats,
                                     step_fusion_stats, aot_cache_stats)
    from paddle_tpu.profiler.events import EVENTS

    set_flags({"FLAGS_aot_cache": True,
               "FLAGS_aot_cache_dir": args.aot_dir,
               "FLAGS_eager_chain_fusion_min_count": 3,
               "FLAGS_eager_step_fusion_min_count": 5,
               "FLAGS_profiler_events": True})
    paddle.seed(7)
    rng = np.random.default_rng(11)
    w = paddle.to_tensor(rng.standard_normal((8, 8)).astype(np.float32),
                         stop_gradient=False)
    bias = paddle.to_tensor(rng.standard_normal(8).astype(np.float32),
                            stop_gradient=False)
    opt = paddle.optimizer.SGD(learning_rate=1e-2, parameters=[w, bias])
    model = {"w": w, "b": bias}
    ck = StepCheckpointer(args.ckpt_dir, save_every_n_steps=1,
                          max_checkpoints=3)
    resumed = ck.restore(model=model, optimizer=opt)
    start = resumed + 1
    kill_at = None if args.kill_at is None else int(args.kill_at)
    losses = {}
    first_fired_rel = None
    # lead with clear_grad so the FIRST cycle already has the steady-state
    # signature (clear_grad otherwise rides the next cycle): the restarted
    # worker's very first boundary then matches the stored step artifact
    opt.clear_grad()
    for rel, step in enumerate(range(start, int(args.steps))):
        if kill_at is not None and step == kill_at:
            os.kill(os.getpid(), signal.SIGKILL)
        srng = np.random.default_rng(1000 + step)
        x = paddle.to_tensor(
            srng.standard_normal((4, 8)).astype(np.float32))
        loss = F.gelu(paddle.add(paddle.matmul(x, w), bias)).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
        if first_fired_rel is None \
                and step_fusion_stats()["fused_steps"] > 0:
            first_fired_rel = rel
        losses[str(step)] = float(loss)
        ck.tick(step, model=model, optimizer=opt)
    ev = EVENTS.snapshot()

    def n(cat):
        return sum(1 for e in ev if e["cat"] == cat)

    report = {
        "resumed_step": resumed,
        "losses": losses,
        "first_fired_rel": first_fired_rel,
        "params": {"w": np.asarray(w._value).tolist(),
                   "b": np.asarray(bias._value).tolist()},
        "dispatch_retraces": dispatch_cache_stats()["retraces"],
        "chain_retraces": chain_fusion_stats()["retraces"],
        "step_retraces": step_fusion_stats()["retraces"],
        "steps_promoted": step_fusion_stats()["steps_promoted"],
        "fused_steps": step_fusion_stats()["fused_steps"],
        "aot": aot_cache_stats(),
        "events": {"dispatch_retrace": n("dispatch.retrace"),
                   "chain_compile": n("chain.compile"),
                   "step_promote": n("step.promote"),
                   "step_fire": n("step.fire"),
                   "aot_hit": n("aot.hit"),
                   "aot_store": n("aot.store"),
                   "aot_corrupt": n("aot.corrupt")},
    }
    with open(args.out, "w") as f:
        json.dump(report, f)
    return 0


def _spawn_aot_child(aot_dir, ckpt_dir, out, steps, kill_at=None,
                     timeout=300):
    cmd = [sys.executable, os.path.abspath(__file__), "--aot-child",
           "--aot-dir", aot_dir, "--ckpt-dir", ckpt_dir, "--out", out,
           "--steps", str(steps)]
    if kill_at is not None:
        cmd += ["--kill-at", str(kill_at)]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, env=env)


def scenario_warm_restart(steps=14, kill_at=9):
    import numpy as np

    failures = []
    with tempfile.TemporaryDirectory() as tmp:
        store = os.path.join(tmp, "aot")
        cold_store = os.path.join(tmp, "aot_cold")
        out_warm = os.path.join(tmp, "warm.json")
        out_ref = os.path.join(tmp, "ref.json")
        out_cor = os.path.join(tmp, "corrupt.json")

        # run 1: populate the store (fused step promotes at min_count 5,
        # the artifact lands on the first fire), then die by SIGKILL
        # mid-run — after promotion, before completion
        r1 = _spawn_aot_child(store, os.path.join(tmp, "ck_a"), out_warm,
                              steps, kill_at=kill_at)
        if r1.returncode != -signal.SIGKILL:
            failures.append(f"expected SIGKILL death, rc={r1.returncode} "
                            f"stderr={r1.stderr[-500:]}")

        # run 2: the warm restart — same store, same checkpoint dir
        r2 = _spawn_aot_child(store, os.path.join(tmp, "ck_a"), out_warm,
                              steps)
        if r2.returncode != 0:
            failures.append(f"warm restart failed: {r2.stderr[-800:]}")

        # reference: uninterrupted run, cold store, fresh checkpoints
        r3 = _spawn_aot_child(cold_store, os.path.join(tmp, "ck_b"),
                              out_ref, steps)
        if r3.returncode != 0:
            failures.append(f"reference run failed: {r3.stderr[-800:]}")

        warm = ref = None
        if not failures:
            with open(out_warm) as f:
                warm = json.load(f)
            with open(out_ref) as f:
                ref = json.load(f)
            if warm["resumed_step"] < 0:
                failures.append("restart did not resume from the "
                                "checkpoint")
            # THE acceptance: zero fresh compiles in the restarted
            # process — every executable deserialized from the store
            for k in ("dispatch_retraces", "chain_retraces",
                      "step_retraces"):
                if warm[k] != 0:
                    failures.append(
                        f"warm restart paid {warm[k]} {k}: the store did "
                        "not eliminate the warmup")
            if warm["events"]["dispatch_retrace"] \
                    or warm["events"]["chain_compile"]:
                failures.append(
                    f"warm restart emitted compile events: "
                    f"{warm['events']}")
            if warm["events"]["aot_hit"] < 3:
                failures.append(
                    f"warm restart loaded only "
                    f"{warm['events']['aot_hit']} artifacts")
            if warm["steps_promoted"] < 1:
                failures.append("warm restart never promoted")
            # promote at the FIRST boundary, fire on the next cycle
            if warm["first_fired_rel"] is None \
                    or warm["first_fired_rel"] > 1:
                failures.append(
                    f"first fused fire at relative cycle "
                    f"{warm['first_fired_rel']} (expected <= 1: promote "
                    "at the first boundary, fire on the next)")
            # loss trajectory: killed-run prefix is gone, but the warm
            # restart's steps must match the uninterrupted reference at
            # the same global indices (the fused ONE-program layout
            # differs from per-op dispatch in the last ULP)
            for k, v in warm["losses"].items():
                if abs(v - ref["losses"][k]) > 1e-4:
                    failures.append(
                        f"loss diverged at step {k}: {v} vs "
                        f"{ref['losses'][k]}")
                    break
            for k in ("w", "b"):
                a = np.asarray(warm["params"][k])
                c = np.asarray(ref["params"][k])
                if not np.allclose(a, c, rtol=0, atol=1e-5):
                    failures.append(
                        f"param {k} diverged after warm restart "
                        f"(max |Δ|={np.max(np.abs(a - c)):.3e})")

        # corruption leg: flip a byte mid-payload in EVERY artifact — a
        # fresh worker must quarantine + recompile, never crash
        import glob as _glob
        for p in _glob.glob(os.path.join(store, "*.aot")):
            with open(p, "rb") as f:
                data = bytearray(f.read())
            data[len(data) // 2] ^= 0xFF
            with open(p, "wb") as f:
                f.write(data)
        r4 = _spawn_aot_child(store, os.path.join(tmp, "ck_c"), out_cor,
                              steps)
        if r4.returncode != 0:
            failures.append(
                f"corrupted store crashed the worker: {r4.stderr[-800:]}")
        elif not failures:
            with open(out_cor) as f:
                cor = json.load(f)
            if cor["events"]["aot_corrupt"] < 1:
                failures.append("corrupted artifacts were not attributed "
                                "artifact_corrupt")
            if cor["steps_promoted"] < 1:
                failures.append("worker did not re-promote after "
                                "recompiling corrupt artifacts")
            for k, v in cor["losses"].items():
                if abs(v - ref["losses"][k]) > 1e-4:
                    failures.append(
                        f"corruption-leg loss diverged at step {k}")
                    break
            if not _glob.glob(os.path.join(store, "*.corrupt")):
                failures.append("corrupt artifacts were not quarantined")
    return {"ok": not failures, "failures": failures}


# ---------------------------------------------------------------------------
# elastic-fleet scenarios (PR 20): coordinator in the parent, one child
# process per fleet host, dp=world data-parallel training per child
# ---------------------------------------------------------------------------

def fleet_child_main(args):
    """One elastic-fleet training worker (invoked as `chaos.py
    --fleet-child`): rendezvous through the stdlib-TCP coordinator, then
    a dp=world data-parallel loop over virtual CPU devices with the FULL
    deterministic global batch each step — every replica computes
    identical state, so fleet size changes move placement, not math.
    Gradient accumulation (two microbatches per step) gives `--kill-at`
    a mid-accumulation SIGKILL point. At every step boundary the worker
    polls the fabric; a new generation restores the latest shared
    StepCheckpointer snapshot, rebuilds the mesh for the new world, and
    re-places its batch — the promoted step drops through the
    `mesh_mismatch` split path and re-promotes (AOT warm when the
    topology was seen before). Rank 0 ticks the shared checkpoint.
    Writes a JSON report of losses, rebuild records, compile/AOT
    counters, and fleet event counts."""
    import numpy as np
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.incubate.checkpoint import StepCheckpointer
    from paddle_tpu.distributed import fabric
    from paddle_tpu.distributed.mesh import set_global_mesh
    from paddle_tpu.profiler import (dispatch_cache_stats,
                                     chain_fusion_stats,
                                     step_fusion_stats, aot_cache_stats)
    from paddle_tpu.profiler.events import EVENTS

    set_flags({"FLAGS_aot_cache": True,
               "FLAGS_aot_cache_dir": args.aot_dir,
               "FLAGS_eager_chain_fusion_min_count": 3,
               "FLAGS_eager_step_fusion_min_count": 5,
               "FLAGS_profiler_events": True,
               "FLAGS_metrics": True})
    host, _, port = args.coord.rpartition(":")
    prev_gen = int(args.prev_gen or 0)
    member = fabric.Member((host, int(port)), args.host_id,
                           gen_seen=prev_gen)
    rank, spec = member.join(timeout=120.0)
    mesh = fabric.mesh_for_spec(spec)
    set_global_mesh(mesh)
    sharding = NamedSharding(mesh, P("data"))
    # a rejoiner warms the shared store into the page cache before its
    # first boundary — `artifacts` == 0 here would predict a cold
    # compile. Must run AFTER set_global_mesh: the store fingerprint
    # carries the mesh topology token.
    prefetch = fabric.prefetch_artifacts(args.aot_dir) if prev_gen else None

    def place_params(params, mesh):
        # checkpoint restore materializes on the default device; the
        # stored/promoted program expects the parameters replicated on
        # the live mesh (where committed fused updates leave them)
        repl = NamedSharding(mesh, P())
        for p in params:
            p._value = jax.device_put(p._value, repl)

    paddle.seed(7)
    rng = np.random.default_rng(11)
    w = paddle.to_tensor(rng.standard_normal((8, 8)).astype(np.float32),
                         stop_gradient=False)
    bias = paddle.to_tensor(rng.standard_normal(8).astype(np.float32),
                            stop_gradient=False)
    opt = paddle.optimizer.SGD(learning_rate=1e-2, parameters=[w, bias])
    model = {"w": w, "b": bias}
    ck = StepCheckpointer(args.ckpt_dir, save_every_n_steps=1,
                          max_checkpoints=3)
    resumed = ck.restore(model=model, optimizer=opt)
    if resumed >= 0:
        place_params([w, bias], mesh)
    kill_at = None if args.kill_at is None else int(args.kill_at)
    pause_at = None if args.pause_at is None else int(args.pause_at)
    losses = {}
    rebuilds = []
    step_wall_t = []
    first_fired_rel = None
    rel = 0
    step = resumed + 1
    opt.clear_grad()
    while step < int(args.steps):
        new_spec = member.poll()
        if new_spec is not None:
            # the fleet changed under us: back to the last consistent
            # snapshot, new mesh, re-place — losing a host costs the
            # steps since the last tick, not a warmup
            resumed = ck.restore(model=model, optimizer=opt)
            mesh = fabric.mesh_for_spec(new_spec)
            set_global_mesh(mesh)
            sharding = NamedSharding(mesh, P("data"))
            place_params([w, bias], mesh)
            rebuilds.append({"at_step": step, "resumed": resumed,
                             "generation": new_spec["generation"],
                             "world": new_spec["world"],
                             "rank": member.rank, "t": time.time()})
            opt.clear_grad()
            step = resumed + 1
            continue
        if pause_at is not None and step == pause_at:
            member.pause_heartbeats(float(args.pause_hb))
            time.sleep(float(args.pause_hb))     # slow-but-alive
        if args.step_ms:
            # pace the loop so the fleet is still mid-run when a lease
            # expires (tiny CPU steps would otherwise outrun detection)
            time.sleep(float(args.step_ms) / 1e3)
        mb_losses = []
        for micro in range(2):
            srng = np.random.default_rng(10_000 * (micro + 1) + step)
            xb = srng.standard_normal((6, 8)).astype(np.float32)
            x = paddle.Tensor(jax.device_put(xb, sharding),
                              stop_gradient=True)
            # MEAN-reduced loss: the data-parallel pmean contract
            # (ops/spmd_fusion.py) needs pmean(local batch means) == the
            # global batch mean — a sum-reduced loss would diverge under
            # probation and demote the program to the plain jit lowering
            loss = F.gelu(paddle.add(paddle.matmul(x, w), bias)).mean()
            loss.backward()
            mb_losses.append(loss)
            if kill_at is not None and step == kill_at and micro == 0:
                with open(args.out + ".kill", "w") as f:
                    f.write(repr(time.time()))
                os.kill(os.getpid(), signal.SIGKILL)
        opt.step()
        opt.clear_grad()
        # read the losses only AFTER the boundary: a host sync inside
        # the accumulation cycle would split the whole-step observation
        total = sum(float(l) for l in mb_losses)
        if first_fired_rel is None \
                and step_fusion_stats()["fused_steps"] > 0:
            first_fired_rel = rel
        losses[str(step)] = total
        step_wall_t.append(time.perf_counter())
        if member.rank == 0:
            ck.tick(step, model=model, optimizer=opt)
        rel += 1
        step += 1
    ev = EVENTS.snapshot()
    try:
        # bench.py's dp2x2 leg lifts this into its own record (the
        # restamp pattern the serve legs use); chaos scenarios ignore it
        from paddle_tpu.profiler.sentinel import capture_record
        sentinel = capture_record("fleet_child")
    except Exception:
        sentinel = None

    def n(cat):
        return sum(1 for e in ev if e["cat"] == cat)

    report = {
        "host": args.host_id,
        "rank": member.rank,
        "generation": member.generation,
        "resumed_step": resumed,
        "losses": losses,
        "rebuilds": rebuilds,
        "step_wall_t": step_wall_t,
        "sentinel_record": sentinel,
        "first_fired_rel": first_fired_rel,
        "prefetch": prefetch,
        "dispatch_retraces": dispatch_cache_stats()["retraces"],
        "chain_retraces": chain_fusion_stats()["retraces"],
        "step_retraces": step_fusion_stats()["retraces"],
        "steps_promoted": step_fusion_stats()["steps_promoted"],
        "fused_steps": step_fusion_stats()["fused_steps"],
        "aot": aot_cache_stats(),
        "events": {"aot_hit": n("aot.hit"),
                   "aot_store": n("aot.store"),
                   "dispatch_retrace": n("dispatch.retrace"),
                   "chain_compile": n("chain.compile"),
                   "fleet_rebuild": n("fleet.rebuild"),
                   "step_split": n("step.split"),
                   "mesh_mismatch": sum(
                       1 for e in ev
                       if e.get("reason") == "mesh_mismatch")},
    }
    member.close()
    with open(args.out, "w") as f:
        json.dump(report, f)
    return 0


def _spawn_fleet_child(coord, host_id, aot_dir, ckpt_dir, out, steps,
                       kill_at=None, prev_gen=None, pause_at=None,
                       pause_hb=None, step_ms=0):
    cmd = [sys.executable, os.path.abspath(__file__), "--fleet-child",
           "--coord", coord, "--host-id", host_id, "--aot-dir", aot_dir,
           "--ckpt-dir", ckpt_dir, "--out", out, "--steps", str(steps),
           "--step-ms", str(step_ms)]
    if kill_at is not None:
        cmd += ["--kill-at", str(kill_at)]
    if prev_gen:
        cmd += ["--prev-gen", str(prev_gen)]
    if pause_at is not None:
        cmd += ["--pause-at", str(pause_at), "--pause-hb", str(pause_hb)]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    # every fleet process sees the same virtual device pool, so the mesh
    # topology token (and with it the AOT fingerprint) matches across
    # hosts and phases
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=4"
                        ).strip()
    return subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True, env=env)


def _drain_fleet_children(procs, timeout=600):
    done = {}
    for name, p in procs.items():
        try:
            outs, errs = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            outs, errs = p.communicate()
        done[name] = (p.returncode, errs)
    return done


def scenario_fleet_kill(steps=26, kill_at=8, lease_s=1.5):
    import numpy as np
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.profiler.events import EVENTS
    from paddle_tpu.distributed import fabric

    set_flags({"FLAGS_profiler_events": True})
    failures = []
    with tempfile.TemporaryDirectory() as tmp:
        aot = os.path.join(tmp, "aot")
        ck_fleet = os.path.join(tmp, "ck_fleet")
        outs = {h: os.path.join(tmp, f"{h}.json")
                for h in ("w0", "w1", "w2", "r0", "r1",
                          "j0", "j1", "j2")}

        # phase 1: 3 workers rendezvous, w2 is SIGKILLed mid-accumulation
        seq0 = EVENTS.total
        coord = fabric.Coordinator(lease_s=lease_s, expected=3)
        addr = f"{coord.host}:{coord.port}"
        procs = {h: _spawn_fleet_child(
                     addr, h, aot, ck_fleet, outs[h], steps,
                     kill_at=kill_at if h == "w2" else None, step_ms=150)
                 for h in ("w0", "w1", "w2")}
        rcs = _drain_fleet_children(procs)
        gen_after = coord.generation
        ev = [e for e in EVENTS.snapshot() if e["seq"] > seq0]
        coord.close()
        if rcs["w2"][0] != -signal.SIGKILL:
            failures.append(f"w2 expected SIGKILL death, "
                            f"rc={rcs['w2'][0]}")
        for h in ("w0", "w1"):
            if rcs[h][0] != 0:
                failures.append(
                    f"survivor {h} failed: {rcs[h][1][-800:]}")
        lost = [e for e in ev if e["cat"] == "fleet.leave"
                and e.get("reason") == "host_lost"]
        if len(lost) != 1 or lost[0]["op"] != "w2":
            failures.append(f"expected exactly one host_lost for w2, "
                            f"got {[(e['op'],) for e in lost]}")
        if gen_after != 2:
            failures.append(
                f"coordinator at generation {gen_after} after one "
                "rendezvous + one loss (expected 2)")
        t_kill = None
        if os.path.exists(outs["w2"] + ".kill"):
            with open(outs["w2"] + ".kill") as f:
                t_kill = float(f.read())
        else:
            failures.append("w2 never reached its kill point")
        survivors = {}
        for h in ("w0", "w1"):
            if rcs[h][0] == 0 and os.path.exists(outs[h]):
                with open(outs[h]) as f:
                    survivors[h] = json.load(f)
        for h, rep in survivors.items():
            rb = rep["rebuilds"]
            if len(rb) != 1 or rb[0]["generation"] != 2 \
                    or rb[0]["world"] != 2:
                failures.append(
                    f"{h} rebuilds {rb}: expected exactly one, at "
                    "generation 2 / world 2")
                continue
            if rb[0]["resumed"] < 0:
                failures.append(f"{h} did not resume from the shared "
                                "checkpoint on rebuild")
            # the lose-a-host-in-SECONDS budget: lease expiry + reaper
            # tick + heartbeat propagation + one step boundary
            if t_kill is not None and rb[0]["t"] - t_kill > lease_s * 3:
                failures.append(
                    f"{h} adopted the rebuild {rb[0]['t'] - t_kill:.2f}s "
                    f"after the kill (budget {lease_s * 3:.1f}s)")
            # the promoted ONE-program step must notice the new mesh
            # (split and/or retrace — a world change shrinks the device
            # SET, so it lands in the split/retrace family rather than
            # the same-pool relayout's mesh_mismatch kill) and keep
            # firing fused on the shrunk mesh afterwards
            if rep["events"]["step_split"] < 1 \
                    and rep["step_retraces"] < 1 \
                    and rep["events"]["mesh_mismatch"] < 1:
                failures.append(
                    f"{h}'s promoted step sailed through the mesh "
                    "change without a split or retrace")
            if rep["fused_steps"] < 1:
                failures.append(f"{h} never fired a fused step")
            if len(rep["losses"]) != steps:
                failures.append(f"{h} finished {len(rep['losses'])} of "
                                f"{steps} steps")

        # phase 2: the reference — an UNINTERRUPTED run on the shrunk
        # (dp=2) mesh, fresh checkpoints, same shared store
        if not failures:
            coord2 = fabric.Coordinator(lease_s=lease_s, expected=2)
            addr2 = f"{coord2.host}:{coord2.port}"
            procs2 = {h: _spawn_fleet_child(
                          addr2, h, aot, os.path.join(tmp, "ck_ref"),
                          outs[h], steps)
                      for h in ("r0", "r1")}
            rcs2 = _drain_fleet_children(procs2)
            coord2.close()
            for h in ("r0", "r1"):
                if rcs2[h][0] != 0:
                    failures.append(
                        f"reference {h} failed: {rcs2[h][1][-800:]}")
        if not failures:
            with open(outs["r0"]) as f:
                ref = json.load(f)
            for h, rep in survivors.items():
                rb_step = rep["rebuilds"][0]["resumed"] + 1
                for k, v in rep["losses"].items():
                    if int(k) < rb_step:
                        continue
                    if abs(v - ref["losses"][k]) > 1e-4:
                        failures.append(
                            f"{h} post-rebuild loss diverged from the "
                            f"clean shrunk-mesh run at step {k}: {v} vs "
                            f"{ref['losses'][k]}")
                        break

        # phase 3: the restarted worker REJOINS a full fleet at the
        # current generation and re-promotes with zero fresh compiles —
        # the dp=3 artifacts it stored before dying serve it back
        if not failures:
            seq1 = EVENTS.total
            coord3 = fabric.Coordinator(lease_s=lease_s, expected=3)
            addr3 = f"{coord3.host}:{coord3.port}"
            procs3 = {}
            for h, prev in (("j0", None), ("j1", None), ("j2", 1)):
                procs3[h] = _spawn_fleet_child(
                    addr3, h, aot, ck_fleet, outs[h], steps + 6,
                    prev_gen=prev)
            rcs3 = _drain_fleet_children(procs3)
            ev3 = [e for e in EVENTS.snapshot() if e["seq"] > seq1]
            coord3.close()
            for h in ("j0", "j1", "j2"):
                if rcs3[h][0] != 0:
                    failures.append(
                        f"rejoin-phase {h} failed: {rcs3[h][1][-800:]}")
            if not any(e["cat"] == "fleet.rejoin" and e["op"] == "j2"
                       for e in ev3):
                failures.append("coordinator never attributed j2 as a "
                                "fleet.rejoin")
        if not failures:
            with open(outs["j2"]) as f:
                rej = json.load(f)
            if rej["resumed_step"] < 0:
                failures.append("rejoiner did not pull the shared "
                                "checkpoint")
            if not rej["prefetch"] or rej["prefetch"]["artifacts"] < 1:
                failures.append(
                    f"prefetch warmed {rej.get('prefetch')} — the "
                    "shared store is invisible to the rejoiner")
            # THE acceptance: zero fresh compiles in the rejoined worker
            for k in ("dispatch_retraces", "chain_retraces",
                      "step_retraces"):
                if rej[k] != 0:
                    failures.append(
                        f"rejoiner paid {rej[k]} {k}: the shared store "
                        "did not eliminate the warmup")
            if rej["events"]["dispatch_retrace"] \
                    or rej["events"]["chain_compile"]:
                failures.append(f"rejoiner emitted compile events: "
                                f"{rej['events']}")
            if rej["events"]["aot_hit"] < 3:
                failures.append(
                    f"rejoiner loaded only {rej['events']['aot_hit']} "
                    "artifacts from the shared store")
            if rej["first_fired_rel"] is None \
                    or rej["first_fired_rel"] > 1:
                failures.append(
                    f"rejoiner first fused fire at relative step "
                    f"{rej['first_fired_rel']} (expected <= 1)")
    return {"ok": not failures, "failures": failures}


def scenario_fleet_flap(steps=12, lease_s=2.0, pause_frac=0.6):
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.profiler.events import EVENTS
    from paddle_tpu.distributed import fabric

    set_flags({"FLAGS_profiler_events": True})
    failures = []
    pause = lease_s * pause_frac
    with tempfile.TemporaryDirectory() as tmp:
        aot = os.path.join(tmp, "aot")
        outs = {h: os.path.join(tmp, f"{h}.json") for h in ("f0", "f1")}
        seq0 = EVENTS.total
        coord = fabric.Coordinator(lease_s=lease_s, expected=2)
        addr = f"{coord.host}:{coord.port}"
        procs = {
            "f0": _spawn_fleet_child(addr, "f0", aot,
                                     os.path.join(tmp, "ck"), outs["f0"],
                                     steps, pause_at=4, pause_hb=pause),
            "f1": _spawn_fleet_child(addr, "f1", aot,
                                     os.path.join(tmp, "ck"), outs["f1"],
                                     steps),
        }
        rcs = _drain_fleet_children(procs)
        ev = [e for e in EVENTS.snapshot() if e["seq"] > seq0]
        coord.close()
        for h in ("f0", "f1"):
            if rcs[h][0] != 0:
                failures.append(f"{h} failed: {rcs[h][1][-800:]}")
        if any(e["cat"] == "fleet.leave"
               and e.get("reason") == "host_lost" for e in ev):
            failures.append(
                f"a {pause:.1f}s heartbeat gap inside a {lease_s}s "
                "lease flapped membership")
        reports = {}
        for h in ("f0", "f1"):
            if os.path.exists(outs[h]):
                with open(outs[h]) as f:
                    reports[h] = json.load(f)
        for h, rep in reports.items():
            if rep["rebuilds"]:
                failures.append(f"{h} adopted a rebuild during an "
                                "in-lease slow spell")
            if rep["generation"] != 1:
                failures.append(f"{h} ended at generation "
                                f"{rep['generation']} (expected 1)")
        if len(reports) == 2 and not failures:
            a, b = reports["f0"]["losses"], reports["f1"]["losses"]
            if a != b:
                failures.append("replica trajectories diverged across "
                                "the slow spell")
    return {"ok": not failures, "failures": failures}


# ---------------------------------------------------------------------------
# kill scenario: child training loop + parent orchestration
# ---------------------------------------------------------------------------

def child_main(args):
    """One resumable AMP training run (invoked as `chaos.py --child`).
    Deterministic per (epoch, step): seeded batches, a NaN batch every 7th
    step (exercising skip-step through the crash boundary), Momentum +
    StepDecay so accumulator/step-counter/LR state must all round-trip."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.incubate.checkpoint import train_epoch_range

    set_flags({"FLAGS_check_numerics": True,
               "FLAGS_eager_chain_fusion_min_count": 3,
               "FLAGS_eager_step_fusion_min_count": 5})
    paddle.seed(7)
    rng = np.random.default_rng(11)
    w = paddle.to_tensor(rng.standard_normal((8, 8)).astype(np.float32),
                         stop_gradient=False)
    bias = paddle.to_tensor(rng.standard_normal(8).astype(np.float32),
                            stop_gradient=False)
    sched = paddle.optimizer.lr.StepDecay(learning_rate=0.05, step_size=2,
                                          gamma=0.5)
    opt = paddle.optimizer.Momentum(learning_rate=sched, momentum=0.9,
                                    parameters=[w, bias])
    scaler = paddle.amp.GradScaler(init_loss_scaling=256.0,
                                   decr_every_n_nan_or_inf=1)
    model = {"w": w, "b": bias}
    er = train_epoch_range(args.epochs, save_dir=args.ckpt_dir,
                           run_id="chaos", max_checkpoints=2)
    er.restore(model=model, optimizer=opt, scaler=scaler)
    resumed_from = er.restored_from
    kill_at = None
    if args.kill_at:
        kill_at = tuple(int(v) for v in args.kill_at.split(":"))
    for epoch in er:
        for step in range(args.steps):
            if kill_at == (epoch, step):
                os.kill(os.getpid(), signal.SIGKILL)
            srng = np.random.default_rng(1000 * epoch + step)
            batch = srng.standard_normal((4, 8)).astype(np.float32)
            if (epoch * args.steps + step) % 7 == 5:
                batch[:] = np.nan
            x = paddle.to_tensor(batch)
            loss = F.gelu(paddle.add(paddle.matmul(x, w), bias)).sum()
            scaler.scale(loss).backward()
            scaler.step(opt)
            scaler.update()
            opt.clear_grad()
        sched.step()
        er.save(epoch, model=model, optimizer=opt, scaler=scaler,
                extra={"epoch": epoch})
    paddle.save(
        {"w": w, "b": bias,
         "scale": scaler.get_init_loss_scaling(),
         "step_count": int(getattr(opt, "_step_count", 0)),
         "lr": float(opt.get_lr()),
         "resumed_from": resumed_from},
        args.out)
    return 0


def _spawn_child(ckpt_dir, out, epochs, steps, kill_at=None, timeout=300):
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--ckpt-dir", ckpt_dir, "--out", out,
           "--epochs", str(epochs), "--steps", str(steps)]
    if kill_at:
        cmd += ["--kill-at", kill_at]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, env=env)


def scenario_kill(epochs=3, steps=6):
    import numpy as np
    import paddle_tpu as paddle

    failures = []
    with tempfile.TemporaryDirectory() as tmp:
        ck_a = os.path.join(tmp, "interrupted")
        ck_b = os.path.join(tmp, "clean")
        out_resumed = os.path.join(tmp, "resumed.pd")
        out_clean = os.path.join(tmp, "clean.pd")

        # run 1: killed mid-epoch (epoch 1, step 3 — epoch 0's checkpoint
        # exists, epoch 1 is half done)
        r1 = _spawn_child(ck_a, out_resumed, epochs, steps, kill_at="1:3")
        if r1.returncode != -signal.SIGKILL:
            failures.append(
                f"expected the child to die by SIGKILL, got rc={r1.returncode}"
                f" stderr={r1.stderr[-500:]}")
        if os.path.exists(out_resumed):
            failures.append("killed run still produced a final state file")

        # run 2: same ckpt dir — must resume from epoch 0's checkpoint and
        # finish
        r2 = _spawn_child(ck_a, out_resumed, epochs, steps)
        if r2.returncode != 0:
            failures.append(f"resumed run failed: {r2.stderr[-800:]}")

        # reference: uninterrupted run in a fresh dir
        r3 = _spawn_child(ck_b, out_clean, epochs, steps)
        if r3.returncode != 0:
            failures.append(f"reference run failed: {r3.stderr[-800:]}")

        if not failures:
            res = paddle.load(out_resumed)
            ref = paddle.load(out_clean)
            if res["resumed_from"] != 0:
                failures.append(
                    f"rerun resumed from epoch {res['resumed_from']}, "
                    "expected 0 (the last completed before the kill)")
            for k in ("scale", "step_count", "lr"):
                if res[k] != ref[k]:
                    failures.append(
                        f"{k} diverged after resume: {res[k]} != {ref[k]}")
            for k in ("w", "b"):
                a = np.asarray(res[k]._value)
                c = np.asarray(ref[k]._value)
                # whole-step fusion warms up at different step indices in
                # the resumed process, and the ONE-program step differs
                # from per-op dispatch in the last ULP (ROADMAP follow-on
                # (d)) — state equality above is exact, params are
                # float-equal to tight tolerance
                if not np.allclose(a, c, rtol=0, atol=1e-5):
                    failures.append(
                        f"param {k} diverged after resume "
                        f"(max |Δ|={np.max(np.abs(a - c)):.3e})")
    return {"ok": not failures, "failures": failures}


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

SCENARIOS = {"nan": scenario_nan, "exception": scenario_exception,
             "kill": scenario_kill, "warm_restart": scenario_warm_restart,
             "serve_hang": scenario_serve_hang,
             "serve_fused_fault": scenario_serve_fused_fault,
             "serve_kill": scenario_serve_kill,
             "tenant_swap": scenario_tenant_swap,
             "telemetry": scenario_telemetry,
             "sentinel": scenario_sentinel,
             "fleet_kill": scenario_fleet_kill,
             "fleet_flap": scenario_fleet_flap}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scenario", default="all",
                    choices=["all"] + sorted(SCENARIOS))
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    # internal: child training/serving runs for the kill scenarios
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--serve-child", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--tenant-child", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--kill-mode", default=None,
                    choices=("staged", "committed"),
                    help=argparse.SUPPRESS)
    ap.add_argument("--aot-child", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--fleet-child", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--coord", help=argparse.SUPPRESS)
    ap.add_argument("--host-id", help=argparse.SUPPRESS)
    ap.add_argument("--prev-gen", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--pause-at", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--pause-hb", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--step-ms", default=0, help=argparse.SUPPRESS)
    ap.add_argument("--ckpt-dir", help=argparse.SUPPRESS)
    ap.add_argument("--aot-dir", help=argparse.SUPPRESS)
    ap.add_argument("--out", help=argparse.SUPPRESS)
    ap.add_argument("--epochs", type=int, default=3, help=argparse.SUPPRESS)
    ap.add_argument("--steps", type=int, default=6, help=argparse.SUPPRESS)
    ap.add_argument("--kill-at", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.child:
        return child_main(args)
    if args.serve_child:
        return serve_child_main(args)
    if args.tenant_child:
        return tenant_child_main(args)
    if args.aot_child:
        return aot_child_main(args)
    if args.fleet_child:
        return fleet_child_main(args)

    names = sorted(SCENARIOS) if args.scenario == "all" else [args.scenario]
    report = {}
    ok = True
    for name in names:
        t0 = time.perf_counter()
        res = SCENARIOS[name]()
        res["seconds"] = round(time.perf_counter() - t0, 2)
        report[name] = res
        ok = ok and res["ok"]
        if not args.json:
            status = "OK" if res["ok"] else "FAIL"
            print(f"chaos[{name}]: {status} ({res['seconds']}s)")
            for f in res.get("failures", []):
                print(f"  - {f}", file=sys.stderr)
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    elif ok:
        print("chaos: all scenarios OK")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
