#!/usr/bin/env python
"""Chaos harness: prove the non-finite step guardian + crash-safe
checkpoints survive deliberately hostile conditions (PR 5).

Three scenarios, each exercising one failure class a multi-day training run
WILL eventually hit:

  nan        a poisoned (all-NaN) batch lands in a PROMOTED dynamic-loss-
             scaled AMP loop (FLAGS_check_numerics + GradScaler riding ONE
             fused whole-step executable). Must hold: parameters bitwise
             unchanged, loss scale halved, no fusion split and no retrace
             (the skip happened in-graph), and the fusion doctor attributes
             the missing update to `nonfinite_skip`.

  exception  a fault hook (ops/guardian.inject_fault) raises ChaosFault
             from inside a dispatched op mid-step. Must hold: the exception
             surfaces cleanly to the training loop, the loop recovers on
             the next batch, parameters stay finite, and the firing is
             attributed as `injected_fault`.

  kill       a training subprocess (AMP + Momentum + LR schedule +
             EpochRange checkpoints) is SIGKILLed mid-epoch, then re-run.
             Must hold: the rerun resumes from the last atomic checkpoint
             (never a torn one), the optimizer step counter / LR schedule /
             loss scale continue exactly, and the final parameters match an
             uninterrupted run.

Every guardian decision flows through the PR 4 fusion flight recorder, so
each scenario's report embeds the doctor's verdict.

    JAX_PLATFORMS=cpu python tools/chaos.py                # all scenarios
    JAX_PLATFORMS=cpu python tools/chaos.py --scenario nan --json
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# runnable from a source checkout without an install
sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))


# ---------------------------------------------------------------------------
# in-process scenarios
# ---------------------------------------------------------------------------

def _amp_loop_state(seed=0):
    import numpy as np
    import paddle_tpu as paddle

    rng = np.random.default_rng(seed)
    x = paddle.to_tensor(rng.standard_normal((4, 16)).astype(np.float32))
    w = paddle.to_tensor(rng.standard_normal((16, 16)).astype(np.float32),
                         stop_gradient=False)
    b = paddle.to_tensor(rng.standard_normal(16).astype(np.float32),
                         stop_gradient=False)
    opt = paddle.optimizer.SGD(learning_rate=1e-2, parameters=[w, b])
    scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0,
                                   decr_every_n_nan_or_inf=1)
    return x, w, b, opt, scaler


def _amp_step(x, w, b, opt, scaler):
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    loss = F.gelu(paddle.add(paddle.matmul(x, w), b)).sum()
    scaler.scale(loss).backward()
    scaler.step(opt)
    scaler.update()
    opt.clear_grad()


def _arm(min_count=5):
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.ops.dispatch import clear_dispatch_cache
    from paddle_tpu.ops import guardian
    from paddle_tpu.profiler.events import clear_fusion_events
    set_flags({"FLAGS_check_numerics": True,
               "FLAGS_eager_chain_fusion": True,
               "FLAGS_eager_step_fusion": True,
               "FLAGS_eager_chain_fusion_min_count": 3,
               "FLAGS_eager_step_fusion_min_count": min_count,
               "FLAGS_profiler_events": True})
    clear_dispatch_cache()
    clear_fusion_events()
    guardian.reset_guardian_stats()
    guardian.clear_faults()


def scenario_nan():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.ops import guardian
    from paddle_tpu.profiler import step_fusion_stats
    from paddle_tpu.profiler.explain import explain

    _arm()
    x, w, b, opt, scaler = _amp_loop_state()
    for _ in range(10):
        _amp_step(x, w, b, opt, scaler)
    s0 = step_fusion_stats()
    w_before = np.asarray(w._value).copy()
    scale_before = scaler.get_init_loss_scaling()

    xbad = paddle.to_tensor(np.full((4, 16), np.nan, np.float32))
    _amp_step(xbad, w, b, opt, scaler)
    guardian.flush()

    s1 = step_fusion_stats()
    stats = guardian.guardian_stats()
    rep = explain()
    failures = []
    if s0["fused_steps"] == 0:
        failures.append("AMP loop never promoted to a fused step")
    if s1["fused_steps"] <= s0["fused_steps"]:
        failures.append("poisoned batch did not run through the fused step")
    if s1["fallback_splits"] != s0["fallback_splits"]:
        failures.append("poisoned batch split the fused replay")
    if not np.array_equal(w_before, np.asarray(w._value)):
        failures.append("parameters changed on a non-finite batch")
    scale_after = scaler.get_init_loss_scaling()
    if scale_after != scale_before / 2:
        failures.append(
            f"loss scale {scale_before} -> {scale_after}, expected halving")
    if stats["steps_skipped"] < 1 or stats["scaler_backoffs"] < 1:
        failures.append(f"guardian stats missed the skip: {stats}")
    if rep["guardian"].get("nonfinite_skip", {}).get("count", 0) < 1:
        failures.append("doctor did not attribute nonfinite_skip")
    # recovery: a clean batch updates again without a retrace
    _amp_step(x, w, b, opt, scaler)
    s2 = step_fusion_stats()
    if np.array_equal(w_before, np.asarray(w._value)):
        failures.append("parameters did not update after recovery")
    if s2["retraces"] != s1["retraces"]:
        failures.append("recovery retraced the fused step")
    return {"ok": not failures, "failures": failures,
            "scale": [scale_before, scale_after],
            "guardian": stats, "doctor": rep["headline"]}


def scenario_exception():
    import numpy as np
    from paddle_tpu.ops import guardian
    from paddle_tpu.profiler.explain import explain

    _arm()
    # stay on per-op dispatch: fault hooks fire on REAL dispatches only —
    # chain/step replays defer their ops, so chaos against fused paths
    # poisons batch inputs instead (the nan scenario)
    from paddle_tpu.framework.flags import set_flags
    set_flags({"FLAGS_eager_chain_fusion": False,
               "FLAGS_eager_step_fusion": False})
    x, w, b, opt, scaler = _amp_loop_state(seed=1)
    for _ in range(4):
        _amp_step(x, w, b, opt, scaler)
    w_before = np.asarray(w._value).copy()

    inj = guardian.inject_fault("raise", op="gelu")
    caught = 0
    try:
        _amp_step(x, w, b, opt, scaler)
    except guardian.ChaosFault:
        caught = 1
        opt.clear_grad()
    finally:
        inj.remove()
    failures = []
    if not caught:
        failures.append("injected mid-step exception did not surface")
    if not np.array_equal(w_before, np.asarray(w._value)):
        failures.append("interrupted step modified parameters")
    # recovery: the loop keeps training afterwards
    for _ in range(3):
        _amp_step(x, w, b, opt, scaler)
    guardian.flush()
    stats = guardian.guardian_stats()
    rep = explain()
    if np.array_equal(w_before, np.asarray(w._value)):
        failures.append("loop did not recover after the exception")
    if not np.all(np.isfinite(np.asarray(w._value))):
        failures.append("parameters went non-finite after recovery")
    if stats["faults_injected"] != 1:
        failures.append(f"expected 1 injected fault, saw {stats}")
    if rep["guardian"].get("injected_fault", {}).get("count", 0) != 1:
        failures.append("doctor did not attribute injected_fault")
    return {"ok": not failures, "failures": failures,
            "guardian": stats, "doctor": rep["headline"]}


# ---------------------------------------------------------------------------
# kill scenario: child training loop + parent orchestration
# ---------------------------------------------------------------------------

def child_main(args):
    """One resumable AMP training run (invoked as `chaos.py --child`).
    Deterministic per (epoch, step): seeded batches, a NaN batch every 7th
    step (exercising skip-step through the crash boundary), Momentum +
    StepDecay so accumulator/step-counter/LR state must all round-trip."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.incubate.checkpoint import train_epoch_range

    set_flags({"FLAGS_check_numerics": True,
               "FLAGS_eager_chain_fusion_min_count": 3,
               "FLAGS_eager_step_fusion_min_count": 5})
    paddle.seed(7)
    rng = np.random.default_rng(11)
    w = paddle.to_tensor(rng.standard_normal((8, 8)).astype(np.float32),
                         stop_gradient=False)
    bias = paddle.to_tensor(rng.standard_normal(8).astype(np.float32),
                            stop_gradient=False)
    sched = paddle.optimizer.lr.StepDecay(learning_rate=0.05, step_size=2,
                                          gamma=0.5)
    opt = paddle.optimizer.Momentum(learning_rate=sched, momentum=0.9,
                                    parameters=[w, bias])
    scaler = paddle.amp.GradScaler(init_loss_scaling=256.0,
                                   decr_every_n_nan_or_inf=1)
    model = {"w": w, "b": bias}
    er = train_epoch_range(args.epochs, save_dir=args.ckpt_dir,
                           run_id="chaos", max_checkpoints=2)
    er.restore(model=model, optimizer=opt, scaler=scaler)
    resumed_from = er.restored_from
    kill_at = None
    if args.kill_at:
        kill_at = tuple(int(v) for v in args.kill_at.split(":"))
    for epoch in er:
        for step in range(args.steps):
            if kill_at == (epoch, step):
                os.kill(os.getpid(), signal.SIGKILL)
            srng = np.random.default_rng(1000 * epoch + step)
            batch = srng.standard_normal((4, 8)).astype(np.float32)
            if (epoch * args.steps + step) % 7 == 5:
                batch[:] = np.nan
            x = paddle.to_tensor(batch)
            loss = F.gelu(paddle.add(paddle.matmul(x, w), bias)).sum()
            scaler.scale(loss).backward()
            scaler.step(opt)
            scaler.update()
            opt.clear_grad()
        sched.step()
        er.save(epoch, model=model, optimizer=opt, scaler=scaler,
                extra={"epoch": epoch})
    paddle.save(
        {"w": w, "b": bias,
         "scale": scaler.get_init_loss_scaling(),
         "step_count": int(getattr(opt, "_step_count", 0)),
         "lr": float(opt.get_lr()),
         "resumed_from": resumed_from},
        args.out)
    return 0


def _spawn_child(ckpt_dir, out, epochs, steps, kill_at=None, timeout=300):
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--ckpt-dir", ckpt_dir, "--out", out,
           "--epochs", str(epochs), "--steps", str(steps)]
    if kill_at:
        cmd += ["--kill-at", kill_at]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, env=env)


def scenario_kill(epochs=3, steps=6):
    import numpy as np
    import paddle_tpu as paddle

    failures = []
    with tempfile.TemporaryDirectory() as tmp:
        ck_a = os.path.join(tmp, "interrupted")
        ck_b = os.path.join(tmp, "clean")
        out_resumed = os.path.join(tmp, "resumed.pd")
        out_clean = os.path.join(tmp, "clean.pd")

        # run 1: killed mid-epoch (epoch 1, step 3 — epoch 0's checkpoint
        # exists, epoch 1 is half done)
        r1 = _spawn_child(ck_a, out_resumed, epochs, steps, kill_at="1:3")
        if r1.returncode != -signal.SIGKILL:
            failures.append(
                f"expected the child to die by SIGKILL, got rc={r1.returncode}"
                f" stderr={r1.stderr[-500:]}")
        if os.path.exists(out_resumed):
            failures.append("killed run still produced a final state file")

        # run 2: same ckpt dir — must resume from epoch 0's checkpoint and
        # finish
        r2 = _spawn_child(ck_a, out_resumed, epochs, steps)
        if r2.returncode != 0:
            failures.append(f"resumed run failed: {r2.stderr[-800:]}")

        # reference: uninterrupted run in a fresh dir
        r3 = _spawn_child(ck_b, out_clean, epochs, steps)
        if r3.returncode != 0:
            failures.append(f"reference run failed: {r3.stderr[-800:]}")

        if not failures:
            res = paddle.load(out_resumed)
            ref = paddle.load(out_clean)
            if res["resumed_from"] != 0:
                failures.append(
                    f"rerun resumed from epoch {res['resumed_from']}, "
                    "expected 0 (the last completed before the kill)")
            for k in ("scale", "step_count", "lr"):
                if res[k] != ref[k]:
                    failures.append(
                        f"{k} diverged after resume: {res[k]} != {ref[k]}")
            for k in ("w", "b"):
                a = np.asarray(res[k]._value)
                c = np.asarray(ref[k]._value)
                # whole-step fusion warms up at different step indices in
                # the resumed process, and the ONE-program step differs
                # from per-op dispatch in the last ULP (ROADMAP follow-on
                # (d)) — state equality above is exact, params are
                # float-equal to tight tolerance
                if not np.allclose(a, c, rtol=0, atol=1e-5):
                    failures.append(
                        f"param {k} diverged after resume "
                        f"(max |Δ|={np.max(np.abs(a - c)):.3e})")
    return {"ok": not failures, "failures": failures}


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

SCENARIOS = {"nan": scenario_nan, "exception": scenario_exception,
             "kill": scenario_kill}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scenario", default="all",
                    choices=["all"] + sorted(SCENARIOS))
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    # internal: child training run for the kill scenario
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--ckpt-dir", help=argparse.SUPPRESS)
    ap.add_argument("--out", help=argparse.SUPPRESS)
    ap.add_argument("--epochs", type=int, default=3, help=argparse.SUPPRESS)
    ap.add_argument("--steps", type=int, default=6, help=argparse.SUPPRESS)
    ap.add_argument("--kill-at", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.child:
        return child_main(args)

    names = sorted(SCENARIOS) if args.scenario == "all" else [args.scenario]
    report = {}
    ok = True
    for name in names:
        t0 = time.perf_counter()
        res = SCENARIOS[name]()
        res["seconds"] = round(time.perf_counter() - t0, 2)
        report[name] = res
        ok = ok and res["ok"]
        if not args.json:
            status = "OK" if res["ok"] else "FAIL"
            print(f"chaos[{name}]: {status} ({res['seconds']}s)")
            for f in res.get("failures", []):
                print(f"  - {f}", file=sys.stderr)
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    elif ok:
        print("chaos: all scenarios OK")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
